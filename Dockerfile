# iotml:latest — the image every manifest under deploy/ runs.
#
# The reference ships per-app images built FROM tensorflow/tensorflow with
# the tfio-kafka wheel dropped in (reference
# python-scripts/AUTOENCODER-TensorFlow-IO-Kafka/Dockerfile:1-8).  Here one
# image carries the whole framework: the Python package, the native C++
# stream engine built from source inside the image, and the test suite (so
# `docker run iotml:latest -m pytest tests/ -q` is a self-contained smoke
# test of the artifact that will run in the cluster).
#
# Accelerator flavor is a build arg:
#   docker build -t iotml:latest .                     # CPU (dev/CI)
#   docker build --build-arg JAX_FLAVOR=tpu -t iotml:latest .   # TPU pods
FROM python:3.12-slim

ARG JAX_FLAVOR=cpu

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app

# dependency layer first: rebuilds of the code don't re-resolve wheels
COPY requirements.txt .
RUN pip install --no-cache-dir -r requirements.txt \
    && if [ "$JAX_FLAVOR" = "tpu" ]; then \
         pip install --no-cache-dir "jax[tpu]" \
           -f https://storage.googleapis.com/jax-releases/libtpu_releases.html; \
       else \
         pip install --no-cache-dir "jax[cpu]"; \
       fi

COPY hivemq-mqtt-tensorflow-kafka-realtime-iot-machine-learning-training-inference_tpu \
     ./hivemq-mqtt-tensorflow-kafka-realtime-iot-machine-learning-training-inference_tpu
COPY tests ./tests
COPY deploy ./deploy
COPY bench.py __graft_entry__.py ./

# short import alias (mirrors the repo's `iotml` symlink)
RUN ln -s hivemq-mqtt-tensorflow-kafka-realtime-iot-machine-learning-training-inference_tpu iotml \
    # native stream engine: fused fetch+decode + Avro columnar decoder
    && make -C iotml/cpp \
    && python -c "import iotml, iotml.stream.native"

ENV PYTHONPATH=/app
ENTRYPOINT ["python"]
# default: the whole platform in one process (deploy/platform.yaml overrides
# args; training/predict Jobs override command+args entirely)
CMD ["-m", "iotml.cli.up", "--host=0.0.0.0"]
