"""Benchmark suite: the reference's streaming workloads on one chip.

Reference baselines (BASELINE.md):
- train: the autoencoder job consumes 10,000 car-sensor records from Kafka
  (batch 100 × take 100) for 20 epochs in ~10 min on an n1-standard-8 pod
  ⇒ ≈16.7 distinct records/sec (python-scripts/README.md:20).
- fleet ingest: the full scenario is 100k MQTT clients at 1 msg/10 s ⇒
  ≈10,000 msgs/s fleet-wide steady state (scenario.xml:13-14,48-49).

One JSON line per metric on stdout (the headline metric is printed LAST so
line-oriented consumers keep finding it):

  fleet_ingest_msgs_per_sec        raw-socket MQTT fleet → epoll listener →
                                   Kafka bridge → stream topic (L1→L3)
  fleet_ingest_native_msgs_per_sec the same fleet through the C++ ingest
                                   engine (cpp/mqtt_ingest.cc)
  fleet_ingest_multiproc_msgs_per_sec
                                   15,000 connections from separate load-
                                   generator processes into the C++ engine
                                   (server fd budget only — the scale path)
  wire_train_records_per_sec_per_chip
                                   the SAME train job as the headline, but
                                   over the TCP Kafka wire protocol with the
                                   native C++ client's fused fetch+decode —
                                   the networked path the reference's
                                   KafkaDataset consumer actually exercises
                                   (cardata-v3.py:46-47), SASL/PLAIN on
  flash_attention_fwd_bwd_tokens_per_sec
                                   the long-context capability (65,536-token
                                   causal step) as a recorded number
  serve_rows_per_sec               long-lived scorer drain incl. ordered
                                   write-back to the predictions topic
  ksql_pipeline_records_per_sec    the four-object KSQL pipeline's pump rate
  streaming_train_records_per_sec_per_chip
                                   in-process upper bound (no network hop)
  e2e_platform_records_per_sec     EVERY stage live at once (fleet → MQTT →
                                   bridge → KSQL → train + serve →
                                   predictions) at a paced 12k msgs/s
  e2e_latency_ms                   publish→prediction flow-completion
                                   latency (p50; p95 alongside)

Statistics: every timed bench runs `IOTML_BENCH_PASSES` warm passes
(default 7) after one cold pass (XLA compile); the reported value is the
p50 and each line carries p50/p95/n_passes.
"""

import json
import os
import resource
import socket
import sys
import threading
import time

TRAIN_BASELINE_RPS = 10_000 / 600.0   # reference: 10k records / ~10 min
FLEET_BASELINE_MPS = 10_000.0         # reference scenario fleet rate
PASSES = int(os.environ.get("IOTML_BENCH_PASSES", "7"))

N_RECORDS = 10_000
EPOCHS = 20
BATCH = 100


def _percentiles(walls):
    xs = sorted(walls)
    p50 = xs[len(xs) // 2]
    p95 = xs[min(len(xs) - 1, int(round(0.95 * (len(xs) - 1))))]
    return p50, p95


def _emit(metric, value, unit, vs_baseline, **extra):
    line = {"metric": metric, "value": round(value, 2), "unit": unit,
            "vs_baseline": round(vs_baseline, 2)}
    line.update(extra)
    print(json.dumps(line), flush=True)


def _fill_broker(broker, n_records, num_cars=100, failure_rate=0.01):
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    gen = FleetGenerator(FleetScenario(num_cars=num_cars,
                                       failure_rate=failure_rate))
    gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=n_records // num_cars)
    return broker


# --------------------------------------------------------------- train
def bench_train_inproc():
    """Headline: generate → framed-Avro broker log → consume → decode →
    normalize → filter → batch → 20 jit epochs, all in-process (the
    no-network upper bound)."""
    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.train.loop import Trainer

    def run_job():
        broker = _fill_broker(Broker(), N_RECORDS)
        consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                                  group="cardata-autoencoder")
        batches = SensorBatches(consumer, batch_size=BATCH, only_normal=True)
        trainer = Trainer(CAR_AUTOENCODER)
        t0 = time.perf_counter()
        history = trainer.fit_compiled(batches, epochs=EPOCHS)
        return time.perf_counter() - t0, history

    cold_wall, history = run_job()
    from iotml.obs.profile import maybe_trace
    walls = []
    with maybe_trace(os.environ.get("IOTML_PROFILE")):
        for _ in range(PASSES):
            wall, _ = run_job()
            walls.append(wall)
    p50, p95 = _percentiles(walls)
    return dict(value=N_RECORDS / p50, cold_wall_s=round(cold_wall, 2),
                p50_s=round(p50, 3), p95_s=round(p95, 3),
                n_passes=len(walls),
                final_loss=round(float(history["loss"][-1]), 6))


def bench_train_wire():
    """The identical train job over TCP: KafkaWireServer front, native C++
    client (fused fetch + framing strip + Avro decode in one call per
    partition), SASL/PLAIN on — the reference consumer's actual shape
    (cardata-v3.py:7-15,46-47)."""
    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.stream.native_kafka import NativeKafkaBroker
    from iotml.train.loop import Trainer

    backing = _fill_broker(Broker(), N_RECORDS)

    def run_job(srv):
        client = NativeKafkaBroker(f"127.0.0.1:{srv.port}",
                                   sasl_username="svc", sasl_password="pw")
        try:
            consumer = StreamConsumer(client, ["SENSOR_DATA_S_AVRO:0:0"],
                                      group="cardata-autoencoder")
            batches = SensorBatches(consumer, batch_size=BATCH,
                                    only_normal=True)
            trainer = Trainer(CAR_AUTOENCODER)
            t0 = time.perf_counter()
            history = trainer.fit_compiled(batches, epochs=EPOCHS)
            return time.perf_counter() - t0, history
        finally:
            client.close()

    with KafkaWireServer(backing, credentials=("svc", "pw")) as srv:
        cold_wall, history = run_job(srv)
        walls = []
        for _ in range(PASSES):
            wall, _ = run_job(srv)
            walls.append(wall)
    p50, p95 = _percentiles(walls)
    return dict(value=N_RECORDS / p50, cold_wall_s=round(cold_wall, 2),
                p50_s=round(p50, 3), p95_s=round(p95, 3),
                n_passes=len(walls),
                final_loss=round(float(history["loss"][-1]), 6))


# --------------------------------------------------------------- serve
def bench_serve():
    """Long-lived scorer: drain the stream through the jit eval in bounded
    super-batches and write predictions back in order (np.array2string
    payload parity) — the reference's predict Deployment without the
    restart churn (python-scripts/README.md:24)."""
    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.serve.scorer import StreamScorer
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.producer import OutputSequence
    from iotml.train.loop import Trainer

    broker = _fill_broker(Broker(), N_RECORDS)
    broker.create_topic("model-predictions")
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
    trainer = Trainer(CAR_AUTOENCODER)
    trainer.fit(SensorBatches(consumer, batch_size=BATCH, only_normal=True),
                epochs=1)

    def run_drain():
        c = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
        out = OutputSequence(broker, "model-predictions", partition=0)
        scorer = StreamScorer(CAR_AUTOENCODER, trainer.state.params,
                              SensorBatches(c, batch_size=BATCH), out,
                              threshold=5.0)
        t0 = time.perf_counter()
        n = scorer.score_available()
        return time.perf_counter() - t0, n

    cold_wall, n_rows = run_drain()
    walls = []
    for _ in range(PASSES):
        wall, n = run_drain()
        assert n == n_rows
        walls.append(wall)
    p50, p95 = _percentiles(walls)
    return dict(value=n_rows / p50, cold_wall_s=round(cold_wall, 2),
                p50_s=round(p50, 3), p95_s=round(p95, 3),
                n_passes=len(walls), rows_per_drain=n_rows)


# ---------------------------------------------------------------- ksql
def bench_ksql_pipeline():
    """The reference's four-object KSQL pipeline (JSON stream → AVRO CSAS →
    rekey CSAS → 5-min CTAS) pumped over a seeded sensor-data topic — the
    stream-preprocessing stage's sustained rate (input records/s through
    ALL FOUR queries).  Native-codec batch encode/decode carries the Avro
    legs; vs_baseline is the 10k msgs/s fleet rate the stage must keep up
    with."""
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.stream.broker import Broker
    from iotml.streamproc import SqlEngine, install_reference_pipeline

    walls = []
    n = 0
    for _ in range(max(3, PASSES // 2)):
        broker = Broker()
        gen = FleetGenerator(FleetScenario(num_cars=100, failure_rate=0.01))
        n = gen.publish(broker, "sensor-data", n_ticks=200,
                        encoding="json", partitions=2)
        engine = SqlEngine(broker)
        install_reference_pipeline(engine)
        t0 = time.perf_counter()
        engine.pump()
        walls.append(time.perf_counter() - t0)
    p50, p95 = _percentiles(walls)
    return dict(value=n / p50, records_in=n, p50_s=round(p50, 3),
                p95_s=round(p95, 3), n_passes=len(walls))


# ------------------------------------------------------------- longctx
def bench_long_context():
    """Flash attention at 65,536 tokens, forward+backward — the long-
    context claim (PARITY) as a recorded number instead of prose, with a
    defensible efficiency figure alongside.  On CPU (no TPU attached) the
    shape drops to something the reference kernel in interpret mode can
    stomach, and the line says so.

    On-device time is separated from the tunnel wall with the K-step
    trick: a jitted fori_loop of K data-dependent steps costs
    (dispatch + K·step), so per-step = (wall(K) − wall(1)) / (K − 1) —
    no profiler plumbing, immune to the tunnel's per-dispatch latency.
    MFU uses the conventional algorithmic count (7 causal matmuls:
    2 fwd + 5 bwd = 7·T²·D·B·H FLOPs) over the v5e bf16 peak."""
    import jax
    import jax.numpy as jnp

    from iotml.ops.attention import flash_attention

    on_tpu = jax.default_backend() not in ("cpu",)
    T = 65_536 if on_tpu else 2_048
    B, H, D = 1, 4, 64
    interpret = not on_tpu
    # 1024² blocks: the measured sweet spot on v5e (the 128² default is
    # grid-overhead-bound at this T — ~8× slower)
    bq = bk = 1024 if on_tpu else 256
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, T, H, D),
                                 jnp.bfloat16) for i in range(3))

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=bq, block_k=bk,
                                       interpret=interpret).astype(
                                           jnp.float32))

    # all three grads, reduced into the timed output: with dq only, XLA
    # could dead-code-eliminate the dk/dv halves of the backward and the
    # "fwd+bwd" number would overstate the kernel
    grad = jax.value_and_grad(loss, argnums=(0, 1, 2))

    def make_multi(n):
        @jax.jit
        def f(q, k, v):
            def body(_, acc):
                # data dependency on acc so XLA cannot hoist or CSE the
                # step out of the loop (grads are consumed, not DCE'd)
                l, (dq, dk, dv) = grad(q + acc.astype(jnp.bfloat16) * 0,
                                       k, v)
                return (acc + l + jnp.sum(dq.astype(jnp.float32))
                        + jnp.sum(dk.astype(jnp.float32))
                        + jnp.sum(dv.astype(jnp.float32)))
            return jax.lax.fori_loop(0, n, body, jnp.float32(0))
        return f

    step1, step5 = make_multi(1), make_multi(5)

    def timed(f):
        # a host read of the reduced scalar is the sync point: over the
        # experimental TPU tunnel, block_until_ready alone has been seen
        # returning before the step finished
        t0 = time.perf_counter()
        float(f(q, k, v))
        return time.perf_counter() - t0

    cold = timed(step1)
    n_passes = max(3, PASSES // 2)
    walls = [timed(step1) for _ in range(n_passes)]
    p50, p95 = _percentiles(walls)
    out = dict(value=T / p50, tokens=T, cold_wall_s=round(cold, 2),
               p50_s=round(p50, 4), p95_s=round(p95, 4),
               n_passes=n_passes, backend=jax.default_backend())
    if on_tpu:
        timed(step5)  # compile
        w5 = min(timed(step5) for _ in range(3))
        w1 = min(walls)
        on_device = (w5 - w1) / 4
        if on_device > 0.001:  # degenerate (tunnel jitter): omit, don't lie
            flops = 7.0 * T * T * D * B * H  # 2 fwd + 5 bwd causal matmuls
            kind = jax.devices()[0].device_kind
            # bf16 peaks per chip; unknown generations report achieved
            # FLOP/s but no MFU claim
            peaks = {"TPU v5 lite": 197e12, "TPU v5e": 197e12,
                     "TPU v5": 459e12, "TPU v5p": 459e12,
                     "TPU v4": 275e12, "TPU v6 lite": 918e12,
                     "TPU v6e": 918e12}
            peak = next((p for k, p in peaks.items()
                         if kind.startswith(k)), None)
            out.update(on_device_step_s=round(on_device, 4),
                       achieved_tflops=round(flops / on_device / 1e12, 1),
                       device_kind=kind)
            if peak:
                out["mfu_pct"] = round(
                    100.0 * flops / on_device / peak, 1)
    return out


# --------------------------------------------------------------- fleet
def _fleet_worker(port, conn_ids, payload, stop, counts, idx, barrier,
                  errors):
    """One worker thread owning a slice of the fleet's sockets: connect
    them all, then round-robin qos-0 publishes until stop.

    Failure containment: any connect/CONNACK failure aborts the shared
    barrier so the main thread fails fast (BrokenBarrierError) instead of
    blocking forever on a worker that died pre-barrier."""
    from iotml.mqtt.wire import CONNACK, connect_packet, publish_packet

    socks = []
    try:
        for cid in conn_ids:
            s = socket.create_connection(("127.0.0.1", port), timeout=30)
            s.sendall(connect_packet(cid))
            buf = b""
            while len(buf) < 4:
                chunk = s.recv(4 - len(buf))
                if not chunk:
                    raise ConnectionError(f"EOF before CONNACK for {cid}")
                buf += chunk
            if buf[0] >> 4 != CONNACK:
                raise ConnectionError(f"expected CONNACK, got {buf[0] >> 4}")
            socks.append((s, publish_packet(
                f"vehicles/sensor/data/{cid}", payload, qos=0)))
    except Exception:
        barrier.abort()
        raise
    barrier.wait(timeout=120)
    # burst of frames per syscall: the benched quantity is SERVER capacity,
    # and on a box co-hosting load generators and server (the reference ran
    # its simulator fleet on separate nodes), per-frame sendall costs would
    # measure the publisher's Python loop instead
    burst = 8
    socks = [(s, pkt * burst) for s, pkt in socks]
    sent = 0
    try:
        while not stop.is_set():
            for s, pkt in socks:
                s.sendall(pkt)
                sent += burst
            counts[idx] = sent
    except OSError as e:
        # a worker dying mid-frame leaves a truncated stream + an
        # undercounted `sent` — surface it instead of silently skewing
        # delivered_pct
        errors.append(f"worker {idx}: {e!r}")
    counts[idx] = sent
    for s, _ in socks:
        try:
            s.close()
        except OSError:
            pass


def _car_payload() -> bytes:
    """A real car record as the fleet's message payload (JSON over MQTT →
    bridge → sensor-data, the platform fleet's shape, cli/up.py)."""
    from iotml.core.schema import KSQL_CAR_SCHEMA
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    gen = FleetGenerator(FleetScenario(num_cars=1))
    return json.dumps(
        gen.row_record(gen.step_columns(), 0, KSQL_CAR_SCHEMA)).encode()


def _drive_fleet(port, n_conns, duration, payload, forwarded_fn, conns_fn,
                 stream, partitions=10):
    """Shared fleet driver: N raw sockets publish qos-0 for `duration`
    seconds against whatever MQTT front listens on `port`; counts only
    messages that reached the stream broker."""
    n_workers = min(16, max(2, 2 * (os.cpu_count() or 4)))
    ids = [f"electric-vehicle-{i:05d}" for i in range(n_conns)]
    slices = [ids[w::n_workers] for w in range(n_workers)]
    stop = threading.Event()
    counts = [0] * n_workers
    errors: list = []
    barrier = threading.Barrier(n_workers + 1)
    threads = [threading.Thread(
        target=_fleet_worker,
        args=(port, slices[w], payload, stop, counts, w, barrier, errors),
        daemon=True) for w in range(n_workers)]

    # ru_maxrss is a LIFETIME high-water mark — after the compute benches
    # it is already at peak and the delta would read ~0.  Sample current
    # VmRSS during THIS window instead.
    def _vm_rss_kb() -> int:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS"):
                        return int(line.split()[1])
        except OSError:
            pass
        return 0

    rss0 = _vm_rss_kb()
    rss_peak = [rss0]
    rss_stop = threading.Event()

    def _rss_sampler():
        while not rss_stop.is_set():
            rss_peak[0] = max(rss_peak[0], _vm_rss_kb())
            time.sleep(0.1)

    rss_thread = threading.Thread(target=_rss_sampler, daemon=True)
    rss_thread.start()
    t_setup = time.perf_counter()
    for t in threads:
        t.start()
    barrier.wait(timeout=180)   # all sockets connected (or fail fast)
    setup_s = time.perf_counter() - t_setup
    live_conns = conns_fn()
    t0 = time.perf_counter()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    # a worker that failed to join is still publishing: its count would be
    # snapshotted below while forwarded keeps growing, corrupting
    # delivered_pct — record stragglers so the line is self-describing
    stragglers = sum(1 for t in threads if t.is_alive())
    if stragglers:
        errors.append(f"{stragglers} worker(s) failed to join in 30s")
    elapsed = time.perf_counter() - t0
    # drain: the front keeps parsing the kernel-buffered backlog after the
    # publishers stop; the drain time COUNTS toward the rate (forwarded
    # messages divided by publish window alone would overstate throughput)
    t_drain = time.perf_counter()
    deadline = time.time() + 120
    sent = sum(counts)
    last, last_t = -1, time.time()
    while forwarded_fn() < sent and time.time() < deadline:
        f = forwarded_fn()
        if f != last:
            last, last_t = f, time.time()
        elif time.time() - last_t > 5:
            break  # no forward progress: stragglers are not coming
        time.sleep(0.05)
    drain_s = time.perf_counter() - t_drain
    forwarded = forwarded_fn()
    rss_stop.set()
    rss_thread.join(timeout=2)
    rss1 = rss_peak[0]
    in_stream = sum(stream.end_offset("sensor-data", p)
                    for p in range(partitions))
    out = dict(value=forwarded / (elapsed + drain_s), n_conns=live_conns,
               duration_s=round(elapsed, 2), setup_s=round(setup_s, 2),
               drain_s=round(drain_s, 2),
               sent=sent, forwarded=forwarded, in_stream_topic=in_stream,
               delivered_pct=round(100.0 * forwarded / max(sent, 1), 2),
               broker_rss_delta_mb=round((rss1 - rss0) / 1024.0, 1))
    if errors:
        out["worker_errors"] = errors[:4]
    return out


FLEET_PARTITIONS = 10  # the reference provisions sensor-data with 10


def _fleet_stream():
    """Stream broker with the reference's retention bound: sensor-data is
    capped the way retention.ms=100000 caps it (~100 s of the 10k msgs/s
    fleet), keeping broker memory bounded under the firehose."""
    from iotml.stream.broker import Broker

    stream = Broker()
    stream.create_topic("sensor-data", partitions=FLEET_PARTITIONS,
                        retention_messages=10_000)  # × partitions ≈ 100k
    return stream


def bench_fleet_ingest():
    """The 100k-car scenario shape at reduced scale: N real TCP
    connections (default 9,000 — both socket ends share one process's fd
    limit) publishing car-record qos-0 payloads into the epoll MQTT
    listener, bridged to the Kafka topic — counting only messages that
    arrived in the stream broker (L1→L2→L3 complete)."""
    from iotml.mqtt.bridge import KafkaBridge
    from iotml.mqtt.broker import MqttBroker
    from iotml.mqtt.eventserver import MqttEventServer

    n_conns = int(os.environ.get("IOTML_BENCH_FLEET_CONNS", "9000"))
    duration = float(os.environ.get("IOTML_BENCH_FLEET_SECONDS", "8"))
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))

    payload = _car_payload()
    mqtt_broker = MqttBroker()
    stream = _fleet_stream()
    bridge = KafkaBridge(mqtt_broker, stream, partitions=FLEET_PARTITIONS)
    with MqttEventServer(mqtt_broker) as srv:
        return _drive_fleet(srv.port, n_conns, duration, payload,
                            bridge.forwarded,
                            lambda: srv.connection_count, stream,
                            partitions=FLEET_PARTITIONS)


def bench_fleet_ingest_native():
    """Same fleet, same payloads, but through the C++ ingest engine
    (cpp/mqtt_ingest.cc): frame parsing and acking in native code, Python
    only sees bulk drains — the HiveMQ-native analogue of the ingest
    edge."""
    from iotml.mqtt.native_ingest import NativeIngestBridge

    n_conns = int(os.environ.get("IOTML_BENCH_FLEET_CONNS", "9000"))
    duration = float(os.environ.get("IOTML_BENCH_FLEET_SECONDS", "8"))
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))

    payload = _car_payload()
    stream = _fleet_stream()
    with NativeIngestBridge(stream, partitions=FLEET_PARTITIONS) as bridge:
        return _drive_fleet(bridge.port, n_conns, duration, payload,
                            bridge.forwarded,
                            lambda: bridge.ingest.connection_count, stream,
                            partitions=FLEET_PARTITIONS)


# Self-contained load-generator child: stdlib only (run with -S: no site,
# no sitecustomize, no jax — a child is sockets and bytes).  Owns its slice
# of the fleet's client sockets so the SERVER process's fd table is the
# only fd budget that binds, the way the reference's simulator nodes are
# separate from its HiveMQ nodes (scenario.xml runs the fleet elsewhere).
_FLEET_CHILD_SRC = r"""
import base64, resource, socket, struct, sys, time
port, n, prefix, duration, payload_b64 = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], float(sys.argv[4]),
    sys.argv[5])
payload = base64.b64decode(payload_b64)
soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))


def varlen(x):
    out = bytearray()
    while True:
        b = x % 128
        x //= 128
        out.append(b | 0x80 if x else b)
        if not x:
            return bytes(out)


def mstr(s):
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def connect_packet(cid):
    body = mstr("MQTT") + bytes([4, 2]) + struct.pack(">H", 60) + mstr(cid)
    return b"\x10" + varlen(len(body)) + body


def publish_packet(topic, pl):
    body = mstr(topic) + pl
    return b"\x30" + varlen(len(body)) + body


socks = []
for i in range(n):
    cid = f"{prefix}-{i:05d}"
    s = socket.create_connection(("127.0.0.1", port), timeout=120)
    s.sendall(connect_packet(cid))
    buf = b""
    while len(buf) < 4:
        chunk = s.recv(4 - len(buf))
        if not chunk:
            raise SystemExit(f"EOF before CONNACK for {cid}")
        buf += chunk
    assert buf[0] >> 4 == 2, "expected CONNACK"
    socks.append((s, publish_packet(f"vehicles/sensor/data/{cid}",
                                    payload) * 8))
sys.stdout.write("READY\n")
sys.stdout.flush()
sys.stdin.readline()  # GO
t0 = time.time()
sent = 0
try:
    while time.time() - t0 < duration:
        for s, pkt in socks:
            s.sendall(pkt)
            sent += 8
except OSError as e:
    sys.stdout.write(f"ERR {e!r}\n")
sys.stdout.write(f"SENT {sent}\n")
sys.stdout.flush()
for s, _ in socks:
    try:
        s.close()
    except OSError:
        pass
"""


def bench_fleet_ingest_multiproc():
    """Fleet scale past one process's fd table: load-generator SUBPROCESSES
    each own a slice of the client sockets (the reference runs its 100k-car
    simulator on separate nodes, scenario.xml:13-14), so only the server's
    fd budget binds.  15,000 connections into the C++ ingest engine;
    delivered_pct counts only messages that reached the stream topic.

    broker_rss_delta_mb here is honest in a way the in-process bench
    cannot be: the publishers live in other processes, so the sampled RSS
    is the SERVER's alone."""
    import base64
    import subprocess

    from iotml.mqtt.native_ingest import NativeIngestBridge

    n_conns = int(os.environ.get("IOTML_BENCH_FLEET_MP_CONNS", "15000"))
    n_children = 5
    duration = float(os.environ.get("IOTML_BENCH_FLEET_SECONDS", "8"))
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))

    payload_b64 = base64.b64encode(_car_payload()).decode()
    stream = _fleet_stream()

    def _vm_rss_kb():
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS"):
                        return int(line.split()[1])
        except OSError:
            pass
        return 0

    per = n_conns // n_children
    with NativeIngestBridge(stream, partitions=FLEET_PARTITIONS) as bridge:
        rss0 = _vm_rss_kb()
        rss_peak = [rss0]
        rss_stop = threading.Event()

        def _rss_sampler():
            while not rss_stop.is_set():
                rss_peak[0] = max(rss_peak[0], _vm_rss_kb())
                time.sleep(0.1)

        threading.Thread(target=_rss_sampler, daemon=True).start()
        t_setup = time.perf_counter()
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("PALLAS_AXON", "AXON_", "JAX_"))}
        children = [
            subprocess.Popen(
                [sys.executable, "-S", "-c", _FLEET_CHILD_SRC,
                 str(bridge.port), str(per), f"ev-{c}", str(duration),
                 payload_b64],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
                text=True)
            for c in range(n_children)
        ]
        try:
            for ch in children:
                line = ch.stdout.readline().strip()
                if line != "READY":
                    raise RuntimeError(f"load child failed: {line!r}")
            setup_s = time.perf_counter() - t_setup
            live_conns = bridge.ingest.connection_count
            t0 = time.perf_counter()
            for ch in children:
                ch.stdin.write("GO\n")
                ch.stdin.flush()
            sent = 0
            errors = []
            for ch in children:
                for line in ch.stdout:
                    line = line.strip()
                    if line.startswith("SENT "):
                        sent += int(line.split()[1])
                        break
                    if line.startswith("ERR"):
                        errors.append(line)
                ch.wait(timeout=120)
            elapsed = time.perf_counter() - t0
            t_drain = time.perf_counter()
            deadline = time.time() + 180
            last, last_t = -1, time.time()
            while bridge.forwarded() < sent and time.time() < deadline:
                f = bridge.forwarded()
                if f != last:
                    last, last_t = f, time.time()
                elif time.time() - last_t > 10:
                    break  # no forward progress: stragglers are not coming
                time.sleep(0.05)
            drain_s = time.perf_counter() - t_drain
            forwarded = bridge.forwarded()
        finally:
            rss_stop.set()
            for ch in children:
                if ch.poll() is None:
                    ch.kill()
        in_stream = sum(stream.end_offset("sensor-data", p)
                        for p in range(FLEET_PARTITIONS))
        out = dict(value=forwarded / (elapsed + drain_s),
                   n_conns=live_conns, n_load_procs=n_children,
                   duration_s=round(elapsed, 2), setup_s=round(setup_s, 2),
                   drain_s=round(drain_s, 2), sent=sent,
                   forwarded=forwarded, in_stream_topic=in_stream,
                   delivered_pct=round(100.0 * forwarded / max(sent, 1), 2),
                   broker_rss_delta_mb=round(
                       (rss_peak[0] - rss0) / 1024.0, 1))
        if errors:
            out["worker_errors"] = errors[:4]
        return out


def bench_e2e_platform():
    """THE reference claim, measured: every layer live at once.  The demo
    the reference actually runs is fleet → HiveMQ → Kafka → KSQL →
    training AND scoring concurrently, predictions written back
    (README.md:100-108, scenario.xml:13-14) — not one leg at a time.

    One process hosts the full platform (cli/up.py: MQTT epoll front +
    bridge, wire broker, four-object KSQL pipeline, registry/connect);
    paced publishers drive real MQTT at ~1.5× the reference's 10k msgs/s
    fleet steady state; a trainer continuously fits fixed-size slices
    from SENSOR_DATA_S_AVRO on the TPU; a scorer continuously drains the
    same stream through the jit eval and writes np.array2string
    predictions to model-predictions — all at the same time.

    Latency is flow-completion: marker (published_count, t) pairs are
    stamped every 250 ms; a marker resolves when the prediction topic's
    total record count reaches the marker's published count, i.e. when
    every record published up to t has traversed MQTT → bridge → KSQL →
    scorer → predictions.  This UPPER-bounds per-record latency (it
    includes finishing the whole backlog ahead of the marker)."""
    from iotml.cli.up import Platform
    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.serve.scorer import StreamScorer
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.producer import OutputSequence
    from iotml.train.loop import Trainer

    # 12k msgs/s = 1.2× the reference fleet's 10k steady state — the
    # highest paced rate at which the WHOLE concurrent pipeline (incl.
    # training) holds flow-completion latency bounded on this box; the
    # per-leg benches record each stage's isolated headroom above it
    target_rate = float(os.environ.get("IOTML_BENCH_E2E_RATE", "12000"))
    window_s = float(os.environ.get("IOTML_BENCH_E2E_SECONDS", "20"))
    n_conns = 200
    n_pub_threads = 4

    platform = Platform(retention_messages=30_000).start()
    stop = threading.Event()
    err: list = []

    # ---- continuous KSQL pump (the stream-preprocessing stage)
    def ksql_pump():
        while not stop.is_set():
            try:
                if platform.sql.pump() == 0:
                    time.sleep(0.02)
            except Exception as e:  # noqa: BLE001 - surfaced at the end
                err.append(f"ksql: {e!r}")
                return

    # ---- continuous training: fixed-size slices from committed offsets
    # (fixed shape → the scanned/fused fit compiles once, then every
    # round reuses it — per-round recompiles would serialize the chip)
    train_stats = {"rounds": 0, "records": 0}

    def train_loop():
        spec = platform.broker.topic("SENSOR_DATA_S_AVRO")
        trainer = Trainer(CAR_AUTOENCODER)
        group = "cardata-autoencoder-e2e"
        take = 2_000
        while not stop.is_set():
            try:
                consumer = StreamConsumer.from_committed(
                    platform.broker, "SENSOR_DATA_S_AVRO",
                    range(spec.partitions), group=group)
                avail = sum(
                    platform.broker.end_offset("SENSOR_DATA_S_AVRO", p)
                    - (platform.broker.committed(
                        group, "SENSOR_DATA_S_AVRO", p) or 0)
                    for p in range(spec.partitions))
                if avail < take:
                    time.sleep(0.1)
                    continue
                batches = SensorBatches(consumer, batch_size=BATCH,
                                        take=take, only_normal=True)
                trainer.fit_compiled(batches, epochs=1)
                consumer.commit()
                train_stats["rounds"] += 1
                train_stats["records"] += take
            except Exception as e:  # noqa: BLE001
                err.append(f"train: {e!r}")
                return

    # ---- continuous scoring → model-predictions (the predict pod)
    def serve_loop(scorer):
        while not stop.is_set():
            try:
                if scorer.score_available() == 0:
                    time.sleep(0.02)
            except Exception as e:  # noqa: BLE001
                err.append(f"serve: {e!r}")
                return

    # ---- paced MQTT publishers (the fleet above the reference rate)
    sent_counts = [0] * n_pub_threads
    payload = _car_payload()

    def publisher(w):
        from iotml.mqtt.wire import CONNACK, connect_packet, publish_packet

        conns = []
        per = n_conns // n_pub_threads
        try:
            for i in range(per):
                cid = f"e2e-{w}-{i:03d}"
                s = socket.create_connection(
                    ("127.0.0.1", platform.mqtt.port), timeout=30)
                s.sendall(connect_packet(cid))
                buf = b""
                while len(buf) < 4:
                    chunk = s.recv(4 - len(buf))
                    if not chunk:
                        raise ConnectionError(f"EOF before CONNACK ({cid})")
                    buf += chunk
                if buf[0] >> 4 != CONNACK:
                    raise ConnectionError(f"expected CONNACK, got {buf[0]}")
                conns.append((s, publish_packet(
                    f"vehicles/sensor/data/{cid}", payload)))
            rate = target_rate / n_pub_threads
            sent = 0
            t0 = time.perf_counter()
            while not stop.is_set():
                for s, pkt in conns:
                    s.sendall(pkt)
                    sent += 1
                sent_counts[w] = sent
                # pace to the target rate (deadline arithmetic, not a
                # fixed sleep: sendall stalls must not lower the rate)
                ahead = sent / rate - (time.perf_counter() - t0)
                if ahead > 0:
                    time.sleep(ahead)
        except OSError as e:
            if not stop.is_set():
                err.append(f"publisher {w}: {e!r}")
        finally:
            for s, _ in conns:
                try:
                    s.close()
                except OSError:
                    pass

    def predictions_total():
        spec = platform.broker.topic("model-predictions")
        return sum(platform.broker.end_offset("model-predictions", p)
                   for p in range(spec.partitions))

    threads = [threading.Thread(target=ksql_pump, daemon=True)]
    sc_spec = None
    try:
        # scorer needs trained-ish params: init from a tiny local fit
        from iotml.stream.broker import Broker as _B
        warm = _fill_broker(_B(), 2000)
        wc = StreamConsumer(warm, ["SENSOR_DATA_S_AVRO:0:0"])
        trainer0 = Trainer(CAR_AUTOENCODER)
        trainer0.fit_compiled(
            SensorBatches(wc, batch_size=BATCH, only_normal=True), epochs=1)
        spec = platform.broker.topic("SENSOR_DATA_S_AVRO")
        sc_spec = [f"SENSOR_DATA_S_AVRO:{p}:0" for p in range(spec.partitions)]
        scorer = StreamScorer(
            CAR_AUTOENCODER, trainer0.state.params,
            SensorBatches(StreamConsumer(platform.broker, sc_spec,
                                         group="scorer-e2e", eof=False),
                          batch_size=BATCH),
            OutputSequence(platform.broker, "model-predictions",
                           partition=0), threshold=5.0)
        threads += [threading.Thread(target=train_loop, daemon=True),
                    threading.Thread(target=serve_loop, args=(scorer,),
                                     daemon=True)]
        threads += [threading.Thread(target=publisher, args=(w,),
                                     daemon=True)
                    for w in range(n_pub_threads)]
        for t in threads:
            t.start()
        # ---- warmup: first records through every stage (compiles the
        # scorer's eval + the trainer's fit before the measured window)
        warm_deadline = time.time() + 240
        while predictions_total() < 2_000 and time.time() < warm_deadline:
            if err:
                raise RuntimeError(err[0])
            time.sleep(0.1)
        if predictions_total() < 2_000:
            raise RuntimeError("e2e warmup: predictions not flowing")
        # ---- measured window
        t_win0 = time.perf_counter()
        sent0 = sum(sent_counts)
        preds0 = predictions_total()
        lat_samples: list = []
        next_marker = time.perf_counter()
        pending: list = []
        while time.perf_counter() - t_win0 < window_s:
            now = time.perf_counter()
            if now >= next_marker:
                pending.append((sum(sent_counts), now))
                next_marker = now + 0.25
            done_total = predictions_total()
            while pending and done_total >= pending[0][0]:
                lat_samples.append(now - pending[0][1])
                pending.pop(0)
            time.sleep(0.02)
        t_win = time.perf_counter() - t_win0
        sent_win = sum(sent_counts) - sent0
        preds_win = predictions_total() - preds0
        # resolve markers still pending (bounded: they measure the tail)
        tail_deadline = time.time() + 30
        while pending and time.time() < tail_deadline:
            done_total = predictions_total()
            now = time.perf_counter()
            while pending and done_total >= pending[0][0]:
                lat_samples.append(now - pending[0][1])
                pending.pop(0)
            time.sleep(0.02)
    finally:
        stop.set()
        try:
            for t in threads:
                if t.ident is not None:  # a setup failure may leave some
                    t.join(timeout=15)   # threads created but unstarted
        finally:
            platform.stop()  # ALWAYS: a leaked platform (epoll front,
            #                  servers) would outlive the bench and mask
            #                  the original error
    if err:
        raise RuntimeError("; ".join(err[:3]))
    lat_ms = sorted(x * 1000.0 for x in lat_samples)
    # None, not NaN: json.dumps(NaN) is not valid JSON and would break
    # strict line-oriented consumers of the metric lines
    p50, p95 = _percentiles(lat_ms) if lat_ms else (None, None)
    return dict(
        value=preds_win / t_win,
        window_s=round(t_win, 2),
        publish_rate_msgs_per_sec=round(sent_win / t_win, 1),
        predictions_in_window=preds_win,
        unresolved_markers=len(pending),
        latency_ms_p50=round(p50, 1) if p50 is not None else None,
        latency_ms_p95=round(p95, 1) if p95 is not None else None,
        n_latency_markers=len(lat_ms),
        train_rounds=train_stats["rounds"],
        records_trained=train_stats["records"],
        stages="fleet+mqtt+bridge+ksql+train+serve concurrent",
    )


def main():
    t_all = time.perf_counter()

    # Execution order ≠ print order: the compute benches run FIRST (clean
    # allocator/process state — the fleet benches churn GBs of message
    # objects that fragment the heap and depress later timings), but the
    # headline metric still PRINTS last for line-oriented consumers.
    # Results are recorded as each bench completes and flushed in the
    # finally block, so a late bench failure cannot discard the metrics
    # already measured.
    results = {}
    order = [
        ("fleet_ingest_msgs_per_sec", "msgs/s", FLEET_BASELINE_MPS),
        ("fleet_ingest_native_msgs_per_sec", "msgs/s", FLEET_BASELINE_MPS),
        # 15k connections from SEPARATE load-generator processes (only the
        # server's fd table binds — the reference's simulator-on-its-own-
        # nodes shape)
        ("fleet_ingest_multiproc_msgs_per_sec", "msgs/s",
         FLEET_BASELINE_MPS),
        ("wire_train_records_per_sec_per_chip", "records/s",
         TRAIN_BASELINE_RPS),
        # no reference twin for long context (its only sequence mechanism
        # is an LSTM at look_back=1): vs_baseline deliberately 0
        ("flash_attention_fwd_bwd_tokens_per_sec", "tokens/s", None),
        # serve compares against the same measured reference job rate —
        # its predict pod scores the identical 10k-record slice per cycle
        # (cardata-v3.py:269-274)
        ("serve_rows_per_sec", "rows/s", TRAIN_BASELINE_RPS),
        # the preprocessing stage must keep pace with fleet ingest
        ("ksql_pipeline_records_per_sec", "records/s", FLEET_BASELINE_MPS),
        # the whole platform live at once: fleet → MQTT → bridge → KSQL →
        # train + serve concurrently, predictions written back — the
        # reference's actual demo shape, with publish→prediction
        # flow-completion latency riding along as fields
        ("e2e_platform_records_per_sec", "records/s", FLEET_BASELINE_MPS),
        ("e2e_latency_ms", "ms", None),
        # the headline stays the LAST printed line (the driver parses the
        # final JSON line as the headline metric)
        ("streaming_train_records_per_sec_per_chip", "records/s",
         TRAIN_BASELINE_RPS),
    ]
    import gc

    def run(name, fn):
        # a full collection between benches: each bench churns millions of
        # objects, and leftover garbage measurably depresses the next
        # bench's timings on this single-core box
        gc.collect()
        results[name] = fn()

    try:
        run("streaming_train_records_per_sec_per_chip", bench_train_inproc)
        run("wire_train_records_per_sec_per_chip", bench_train_wire)
        run("flash_attention_fwd_bwd_tokens_per_sec", bench_long_context)
        run("serve_rows_per_sec", bench_serve)
        run("ksql_pipeline_records_per_sec", bench_ksql_pipeline)
        run("fleet_ingest_msgs_per_sec", bench_fleet_ingest)
        try:
            run("fleet_ingest_native_msgs_per_sec",
                bench_fleet_ingest_native)
        except Exception as e:  # no toolchain: the Python front remains
            print(f"# fleet_ingest_native skipped: {e}", file=sys.stderr)
        try:
            run("fleet_ingest_multiproc_msgs_per_sec",
                bench_fleet_ingest_multiproc)
        except Exception as e:
            print(f"# fleet_ingest_multiproc skipped: {e}", file=sys.stderr)
        res = None
        try:
            run("e2e_platform_records_per_sec", bench_e2e_platform)
            res = results["e2e_platform_records_per_sec"]
        except Exception as e:
            print(f"# e2e_platform skipped: {e}", file=sys.stderr)
        if res is not None and res.get("latency_ms_p50") is not None:
            results["e2e_latency_ms"] = dict(
                value=res.get("latency_ms_p50"),
                p95_ms=res.get("latency_ms_p95"),
                n_markers=res.get("n_latency_markers"),
                definition="publish→prediction flow completion")
    finally:
        for metric, unit, baseline in order:
            res = results.get(metric)
            if res is None:
                continue
            v = res.pop("value")
            _emit(metric, v, unit,
                  (v / baseline) if baseline else 0.0, **res)
        print(f"# total_bench_wall={time.perf_counter() - t_all:.1f}s",
              file=sys.stderr)


if __name__ == "__main__":
    main()
