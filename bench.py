"""Headline benchmark: the reference's streaming-train workload on one chip.

Reference baseline (BASELINE.md): the autoencoder training job consumes
10,000 car-sensor records from Kafka (batch 100 × take 100) for 20 epochs
and takes ~10 minutes on an n1-standard-8 pod ⇒ ≈16.7 distinct records/sec.

This bench runs the *same* job end-to-end on this framework: fleet generator
→ framed-Avro broker log → consume → decode → normalize → filter → batch →
20 jit-compiled training epochs, then reports distinct-records/sec over the
whole job wall-clock (prep + ingest + train), the reference's own accounting.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

BASELINE_RECORDS_PER_SEC = 10_000 / 600.0  # reference: 10k records / ~10 min


def main():
    t_start = time.perf_counter()

    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.train.loop import Trainer

    n_records = 10_000
    epochs = 20
    batch_size = 100

    def run_job():
        """The full reference train job: generate → publish framed Avro →
        consume → decode (C++ engine) → normalize → filter → batch →
        20 scanned epochs on chip."""
        broker = Broker()
        gen = FleetGenerator(FleetScenario(num_cars=100, failure_rate=0.01))
        gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=n_records // 100)
        consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                                  group="cardata-autoencoder")
        batches = SensorBatches(consumer, batch_size=batch_size,
                                only_normal=True)
        trainer = Trainer(CAR_AUTOENCODER)
        t0 = time.perf_counter()
        history = trainer.fit_compiled(batches, epochs=epochs)
        return time.perf_counter() - t0, history

    # Cold pass pays the one-time XLA compile (10-50s over the TPU tunnel,
    # high variance); warm passes are the sustained streaming rate — the
    # steady-state number a long-lived trainer delivers, and the honest
    # analogue of the reference's repeated 10-minute train jobs.  The
    # tunnel's per-dispatch latency is noisy, so report the median of
    # three warm passes.
    cold_wall, history = run_job()
    from iotml.obs.profile import maybe_trace
    import os
    warm_walls = []
    with maybe_trace(os.environ.get("IOTML_PROFILE")):
        for _ in range(3):
            wall, _ = run_job()
            warm_walls.append(wall)
    warm_wall = sorted(warm_walls)[1]
    value = n_records / warm_wall

    print(json.dumps({
        "metric": "streaming_train_records_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "records/s",
        "vs_baseline": round(value / BASELINE_RECORDS_PER_SEC, 2),
    }))
    print(f"# warm_walls={[round(w, 2) for w in warm_walls]}s (median used) "
          f"cold_wall={cold_wall:.2f}s (cold includes one-time XLA compile) "
          f"epochs={epochs} final_loss={history['loss'][-1]:.6f} "
          f"records_per_epoch={history['records'][0]}", file=sys.stderr)


if __name__ == "__main__":
    main()
