"""Benchmark suite: the reference's streaming workloads on one chip.

Reference baselines (BASELINE.md):
- train: the autoencoder job consumes 10,000 car-sensor records from Kafka
  (batch 100 × take 100) for 20 epochs in ~10 min on an n1-standard-8 pod
  ⇒ ≈16.7 distinct records/sec (python-scripts/README.md:20).
- fleet ingest: the full scenario is 100k MQTT clients at 1 msg/10 s ⇒
  ≈10,000 msgs/s fleet-wide steady state (scenario.xml:13-14,48-49).

One JSON line per metric on stdout (the headline metric is printed LAST so
line-oriented consumers keep finding it):

  fleet_ingest_msgs_per_sec        raw-socket MQTT fleet → epoll listener →
                                   Kafka bridge → stream topic (L1→L3)
  fleet_ingest_native_msgs_per_sec the same fleet through the C++ ingest
                                   engine (cpp/mqtt_ingest.cc)
  fleet_ingest_multiproc_msgs_per_sec
                                   15,000 connections from separate load-
                                   generator processes into the C++ engine
                                   (server fd budget only — the scale path)
  wire_train_records_per_sec_per_chip
                                   the SAME train job as the headline, but
                                   over the TCP Kafka wire protocol with the
                                   native C++ client's fused fetch+decode —
                                   the networked path the reference's
                                   KafkaDataset consumer actually exercises
                                   (cardata-v3.py:46-47), SASL/PLAIN on
  flash_attention_fwd_bwd_tokens_per_sec
                                   the long-context capability (65,536-token
                                   causal step) as a recorded number
  serve_rows_per_sec               long-lived scorer drain incl. ordered
                                   write-back to the predictions topic
  ksql_pipeline_records_per_sec    the four-object KSQL pipeline's pump rate
  streaming_train_records_per_sec_per_chip
                                   in-process upper bound (no network hop)
  e2e_platform_records_per_sec     EVERY stage live at once (fleet → MQTT →
                                   bridge → KSQL → train + serve →
                                   predictions) at a paced 12k msgs/s
  e2e_latency_ms                   publish→prediction flow-completion
                                   latency (p50; p95 alongside)

Statistics: every timed bench runs `IOTML_BENCH_PASSES` warm passes
(default 7) after one cold pass (XLA compile); the reported value is the
p50 and each line carries p50/p95/n_passes.
"""

import json
import os
import resource
import socket
import sys
import threading
import time
from typing import Optional

TRAIN_BASELINE_RPS = 10_000 / 600.0   # reference: 10k records / ~10 min
FLEET_BASELINE_MPS = 10_000.0         # reference scenario fleet rate
PASSES = int(os.environ.get("IOTML_BENCH_PASSES", "7"))

N_RECORDS = 10_000
EPOCHS = 20
BATCH = 100


def _percentiles(walls):
    xs = sorted(walls)
    p50 = xs[len(xs) // 2]
    p95 = xs[min(len(xs) - 1, int(round(0.95 * (len(xs) - 1))))]
    return p50, p95


def _emit(metric, value, unit, vs_baseline, **extra):
    line = {"metric": metric, "value": round(value, 2), "unit": unit,
            "vs_baseline": round(vs_baseline, 2)}
    line.update(extra)
    print(json.dumps(line), flush=True)


def _fill_broker(broker, n_records, num_cars=100, failure_rate=0.01):
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    gen = FleetGenerator(FleetScenario(num_cars=num_cars,
                                       failure_rate=failure_rate))
    gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=n_records // num_cars)
    return broker


# --------------------------------------------------------------- train
def bench_train_inproc():
    """Headline: generate → framed-Avro broker log → consume → decode →
    normalize → filter → batch → 20 jit epochs, all in-process (the
    no-network upper bound)."""
    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.train.loop import Trainer

    def run_job():
        broker = _fill_broker(Broker(), N_RECORDS)
        consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                                  group="cardata-autoencoder")
        batches = SensorBatches(consumer, batch_size=BATCH, only_normal=True)
        trainer = Trainer(CAR_AUTOENCODER)
        t0 = time.perf_counter()
        history = trainer.fit_compiled(batches, epochs=EPOCHS)
        return time.perf_counter() - t0, history

    cold_wall, history = run_job()
    from iotml.obs.profile import maybe_trace
    walls = []
    with maybe_trace(os.environ.get("IOTML_PROFILE")):
        for _ in range(PASSES):
            wall, _ = run_job()
            walls.append(wall)
    p50, p95 = _percentiles(walls)
    # decomposition for cross-round comparability: the host pipeline
    # (decode/normalize/filter/batch) is CPU-bound and box-day stable;
    # the remainder is device + tunnel dispatch, where the measured
    # ~2x session-to-session spread lives.  Cross-round ratios should
    # compare host_pipeline_s and device_plus_dispatch_s separately,
    # never the single wall (VERDICT r4 weak #5).
    broker = _fill_broker(Broker(), N_RECORDS)
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                              group="cardata-decomp")
    t0 = time.perf_counter()
    for _ in SensorBatches(consumer, batch_size=BATCH, only_normal=True):
        pass
    host_s = time.perf_counter() - t0
    return dict(value=N_RECORDS / p50, cold_wall_s=round(cold_wall, 2),
                p50_s=round(p50, 3), p95_s=round(p95, 3),
                n_passes=len(walls),
                host_pipeline_s=round(host_s, 3),
                device_plus_dispatch_s=round(max(p50 - host_s, 0.0), 3),
                final_loss=round(float(history["loss"][-1]), 6))


def bench_train_wire():
    """The identical train job over TCP: KafkaWireServer front, native C++
    client (fused fetch + framing strip + Avro decode in one call per
    partition), SASL/PLAIN on — the reference consumer's actual shape
    (cardata-v3.py:7-15,46-47)."""
    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.kafka_wire import KafkaWireServer
    from iotml.stream.native_kafka import NativeKafkaBroker
    from iotml.train.loop import Trainer

    backing = _fill_broker(Broker(), N_RECORDS)

    def run_job(srv):
        client = NativeKafkaBroker(f"127.0.0.1:{srv.port}",
                                   sasl_username="svc", sasl_password="pw")
        try:
            consumer = StreamConsumer(client, ["SENSOR_DATA_S_AVRO:0:0"],
                                      group="cardata-autoencoder")
            batches = SensorBatches(consumer, batch_size=BATCH,
                                    only_normal=True)
            trainer = Trainer(CAR_AUTOENCODER)
            t0 = time.perf_counter()
            history = trainer.fit_compiled(batches, epochs=EPOCHS)
            return time.perf_counter() - t0, history
        finally:
            client.close()

    with KafkaWireServer(backing, credentials=("svc", "pw")) as srv:
        cold_wall, history = run_job(srv)
        walls = []
        for _ in range(PASSES):
            wall, _ = run_job(srv)
            walls.append(wall)
        # host-pipeline decomposition over the wire (see bench_train_inproc)
        client = NativeKafkaBroker(f"127.0.0.1:{srv.port}",
                                   sasl_username="svc", sasl_password="pw")
        try:
            consumer = StreamConsumer(client, ["SENSOR_DATA_S_AVRO:0:0"],
                                      group="cardata-decomp-wire")
            t0 = time.perf_counter()
            for _ in SensorBatches(consumer, batch_size=BATCH,
                                   only_normal=True):
                pass
            host_s = time.perf_counter() - t0
        finally:
            client.close()
    p50, p95 = _percentiles(walls)
    return dict(value=N_RECORDS / p50, cold_wall_s=round(cold_wall, 2),
                p50_s=round(p50, 3), p95_s=round(p95, 3),
                n_passes=len(walls),
                host_pipeline_s=round(host_s, 3),
                device_plus_dispatch_s=round(max(p50 - host_s, 0.0), 3),
                final_loss=round(float(history["loss"][-1]), 6))


# --------------------------------------------------------------- serve
def bench_serve():
    """Long-lived scorer: drain the stream through the jit eval in bounded
    super-batches and write predictions back in order (np.array2string
    payload parity) — the reference's predict Deployment without the
    restart churn (python-scripts/README.md:24)."""
    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.serve.scorer import StreamScorer
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.producer import OutputSequence
    from iotml.train.loop import Trainer

    broker = _fill_broker(Broker(), N_RECORDS)
    broker.create_topic("model-predictions")
    consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
    trainer = Trainer(CAR_AUTOENCODER)
    trainer.fit(SensorBatches(consumer, batch_size=BATCH, only_normal=True),
                epochs=1)

    def run_drain():
        c = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"])
        out = OutputSequence(broker, "model-predictions", partition=0)
        scorer = StreamScorer(CAR_AUTOENCODER, trainer.state.params,
                              SensorBatches(c, batch_size=BATCH), out,
                              threshold=5.0)
        t0 = time.perf_counter()
        n = scorer.score_available()
        return time.perf_counter() - t0, n

    cold_wall, n_rows = run_drain()
    walls = []
    for _ in range(PASSES):
        wall, n = run_drain()
        assert n == n_rows
        walls.append(wall)
    p50, p95 = _percentiles(walls)
    return dict(value=n_rows / p50, cold_wall_s=round(cold_wall, 2),
                p50_s=round(p50, 3), p95_s=round(p95, 3),
                n_passes=len(walls), rows_per_drain=n_rows)


# ---------------------------------------------------------------- ksql
def bench_store_log():
    """Durable segmented-log micro-bench (iotml.store): append MB/s and
    replay MB/s through the broker-shaped path (CRC32C framing, sparse
    index maintenance, segment rolls), plus crash-recovery wall time
    over the same data with a torn tail — the costs the --durable
    platform pays over the in-memory broker."""
    import shutil
    import tempfile

    from iotml.store import SegmentedLog, StorePolicy

    n_records = int(os.environ.get("IOTML_BENCH_STORE_RECORDS", "20000"))
    value = b"x" * 256  # ~ a framed Avro sensor row
    mb = n_records * len(value) / 1e6

    def one_pass():
        d = tempfile.mkdtemp(prefix="iotml_bench_store_")
        try:
            log = SegmentedLog(d, StorePolicy(
                fsync="interval", fsync_interval_s=0.05,
                segment_bytes=4 * 1024 * 1024))
            t0 = time.perf_counter()
            for i in range(n_records):
                log.append(None, value, i, sync=False)
            log.sync_batch()
            append_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            off, seen = 0, 0
            while seen < n_records:
                chunk = log.read_from(off, 4096)
                if not chunk:
                    break
                seen += len(chunk)
                off = chunk[-1][0] + 1
            replay_s = time.perf_counter() - t0
            log.simulate_torn_write()
            log.close()
            t0 = time.perf_counter()
            recovered = SegmentedLog(d, StorePolicy(segment_bytes=4 * 1024 * 1024))
            recovery_s = time.perf_counter() - t0
            assert recovered.end_offset == n_records
            assert recovered.recovered_truncated_bytes > 0
            recovered.close()
            return append_s, replay_s, recovery_s
        finally:
            shutil.rmtree(d, ignore_errors=True)

    one_pass()  # warm the page cache / allocator
    walls = [one_pass() for _ in range(max(3, PASSES // 2))]
    ap50, _ = _percentiles([w[0] for w in walls])
    rp50, _ = _percentiles([w[1] for w in walls])
    cp50, _ = _percentiles([w[2] for w in walls])
    return dict(value=mb / ap50,
                replay_mb_per_sec=round(mb / rp50, 2),
                recovery_ms=round(cp50 * 1e3, 2),
                n_records=n_records, payload_bytes=len(value),
                n_passes=len(walls))


def bench_tiered():
    """Tiered-store replay ladder (ISSUE 18): records/s replayed from
    the local hot tier vs through the remote tier with a cold cache
    (blob fetch + CRC verify + read-only mount, amortised over the
    batch), plus time-to-first-batch for a cold backfill — an empty
    local dir over the committed remote tier, the follower-bootstrap /
    historical-trainer cold-start cost.  Same records, same frame
    decoder on both legs; three prices."""
    import shutil
    import tempfile

    from iotml.store import RemoteTier, StorePolicy, TieredLog, TierPolicy
    from iotml.train.artifacts import ArtifactStore

    n_records = int(os.environ.get("IOTML_BENCH_TIERED_RECORDS", "50000"))
    value = b"x" * 256
    root = tempfile.mkdtemp(prefix="iotml_bench_tiered_")
    try:
        store = ArtifactStore(os.path.join(root, "bucket"))
        bucket = os.path.join(root, "bucket")
        log = TieredLog(os.path.join(root, "local"),
                        policy=StorePolicy(fsync="never",
                                           segment_bytes=4 * 1024 * 1024),
                        remote=RemoteTier(store, prefix="tiered/bench/0"),
                        tier=TierPolicy(uri=bucket))
        for i in range(n_records):
            log.append(None, value, i, sync=False)
        log.roll()

        def replay(lg):
            t0 = time.perf_counter()
            off, seen = lg.base_offset, 0
            while seen < n_records:
                chunk = lg.read_from(off, 4096)
                if not chunk:
                    break
                seen += len(chunk)
                off = chunk[-1][0] + 1
            return seen, time.perf_counter() - t0

        passes = max(3, PASSES // 2)
        local_walls = []
        for _ in range(passes + 1):  # first pass warms the page cache
            seen, w = replay(log)
            assert seen == n_records
            local_walls.append(w)
        l50, _ = _percentiles(local_walls[1:])

        log.tier_sync()
        log.evict_hot(budget_bytes=0)
        assert log.local_base_offset >= n_records  # hot tier fully out
        remote_walls = []
        for _ in range(passes):
            log.cache.clear()  # every pass pays the full cold fetch
            seen, w = replay(log)
            assert seen == n_records
            remote_walls.append(w)
        r50, _ = _percentiles(remote_walls)

        ttfb = []
        for i in range(passes):
            cold_dir = os.path.join(root, f"cold{i}")
            t0 = time.perf_counter()
            cold = TieredLog(cold_dir, policy=StorePolicy(fsync="never"),
                             remote=RemoteTier(store,
                                               prefix="tiered/bench/0"),
                             tier=TierPolicy(uri=bucket))
            first = cold.read_from(cold.base_offset, 4096)
            ttfb.append(time.perf_counter() - t0)
            assert first
            cold.close()
            shutil.rmtree(cold_dir, ignore_errors=True)
        t50, _ = _percentiles(ttfb)

        log.close()
        return dict(value=n_records / r50,
                    local_replay_records_per_sec=round(n_records / l50, 1),
                    cold_backfill_first_batch_ms=round(t50 * 1e3, 2),
                    remote_vs_local_pct=round(100.0 * (r50 / l50 - 1.0), 1),
                    n_records=n_records, payload_bytes=len(value),
                    n_passes=passes)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_replication():
    """Quorum replication costs (ISSUE 14): acks=all vs acks=1 produce
    throughput through a live leader + 2 ISR followers (background
    sync threads — the ack latency floor is the followers' fetch
    cadence), and reassignment catch-up MB/s: a brand-new replica
    bootstrapping a pre-filled durable leader's segment log over
    zero-copy RAW_FETCH mirroring until it joins the ISR."""
    import shutil
    import tempfile

    from iotml.replication import ReplicaSet
    from iotml.stream.broker import Broker
    from iotml.stream.kafka_wire import KafkaWireBroker, KafkaWireServer

    n_records = int(os.environ.get("IOTML_BENCH_REPL_RECORDS", "20000"))
    batch = 500
    value = b"x" * 256

    def produce_leg(acks):
        leader = Broker()
        leader.create_topic("bench-repl", partitions=1)
        srv = KafkaWireServer(leader).start()
        rs = ReplicaSet(leader_broker=leader, leader_server=srv,
                        n_followers=2, min_isr=2, max_lag_s=2.0,
                        topics=["bench-repl"],
                        poll_interval_s=0.001).start(sync="thread")
        client = KafkaWireBroker(f"127.0.0.1:{srv.port}")
        try:
            assert rs.await_isr(3, "bench-repl", timeout_s=15)
            entries = [(None, value, 0)] * batch
            t0 = time.perf_counter()
            for _ in range(n_records // batch):
                client.produce_many("bench-repl", entries, partition=0,
                                    acks=acks, timeout_ms=30_000)
            return n_records / (time.perf_counter() - t0)
        finally:
            client.close()
            rs.stop()
            srv.shutdown()
            srv.server_close()

    acks1 = max(produce_leg(1) for _ in range(3))
    acks_all = max(produce_leg(-1) for _ in range(3))

    # catch-up: a fresh replica mirrors a pre-filled DURABLE leader
    d = tempfile.mkdtemp(prefix="iotml_bench_repl_")
    try:
        leader = Broker(store_dir=os.path.join(d, "leader"))
        leader.create_topic("bench-repl", partitions=1)
        # bounded produce batches (the RawBatchProducer shape): the
        # sparse index gets batch-granular entries, so one giant fused
        # append would force the mirror's alignment fallback — real
        # ingest never writes 2.9 MB in one append
        for _ in range(n_records // batch):
            leader.produce_many("bench-repl", [(None, value, 0)] * batch,
                                partition=0)
        leader.flush()
        mb = n_records * len(value) / 1e6
        srv = KafkaWireServer(leader).start()
        rs = ReplicaSet(leader_broker=leader, leader_server=srv,
                        n_followers=0, min_isr=1, max_lag_s=2.0,
                        topics=["bench-repl"], poll_interval_s=0.001)
        try:
            t0 = time.perf_counter()
            rid = rs.add_follower(sync="thread")
            deadline = time.monotonic() + 120
            while rid not in rs.state.isr_follower_ids():
                if time.monotonic() > deadline:
                    raise RuntimeError("catch-up never joined the ISR")
                time.sleep(0.002)
            catch_up_s = time.perf_counter() - t0
            raw = rs.followers[rid].raw_mirrored
        finally:
            rs.stop()
            srv.shutdown()
            srv.server_close()
            leader.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)

    return dict(value=acks_all,
                acks1_records_per_sec=round(acks1, 1),
                acks_all_overhead_pct=round(
                    (acks1 - acks_all) / acks1 * 100.0, 1),
                catchup_mb_per_sec=round(mb / catch_up_s, 2),
                catchup_s=round(catch_up_s, 3),
                catchup_raw_mirrored=raw,
                n_records=n_records, batch=batch,
                payload_bytes=len(value))


def bench_pipeline():
    """Zero-copy columnar data plane (ISSUE 10): the consume path's
    decode rate through its three legs over the SAME durable topic —

      python:   pure-codec decode of fetched Message lists (the oracle
                path; per-record Python objects everywhere),
      fused:    native batch Avro decode of fetched Message lists (the
                pre-ISSUE-10 fast path: per-record Message objects, one
                C decode call per chunk),
      columnar: raw frame batches (Broker.fetch_raw) decoded by the ONE
                FrameDecoder straight into ring buffers (zero
                per-record Python objects end to end),

    plus the wire leg (RAW_FETCH through a KafkaWireServer) — the
    host-pipeline ceiling the e2e saturation knee inherits.  Reported:
    records/s and decode MB/s per leg, and the columnar/python speedup
    the acceptance gate reads (target >= 2x)."""
    import shutil
    import tempfile

    from iotml.data.dataset import SensorBatches
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer

    n_records = int(os.environ.get("IOTML_BENCH_PIPELINE_RECORDS",
                                   "20000"))
    d = tempfile.mkdtemp(prefix="iotml_bench_pipeline_")
    try:
        broker = Broker(store_dir=d)
        _fill_broker(broker, n_records, num_cars=100)
        total = broker.end_offset("SENSOR_DATA_S_AVRO", 0)
        sample = broker.fetch("SENSOR_DATA_S_AVRO", 0, 0, 4096)
        payload_mb = (sum(len(m.value) for m in sample)
                      / max(len(sample), 1)) * total / 1e6

        def drain(mode: str) -> float:
            consumer = StreamConsumer(broker,
                                      ["SENSOR_DATA_S_AVRO:0:0"],
                                      group=f"bench-{mode}")
            sb = SensorBatches(consumer, batch_size=100,
                               keep_labels=True, poll_chunk=4096)
            if mode == "python":
                sb._native = None
                sb._ring = False
            elif mode == "fused":
                sb._ring = False  # native decode over Message lists
            t0 = time.perf_counter()
            rows = sum(b.n_valid for b in sb)
            wall = time.perf_counter() - t0
            assert rows == total, (mode, rows, total)
            if mode == "columnar":
                assert sb._ring not in (None, False), \
                    "columnar path did not engage"
            return wall

        def drain_wire() -> float:
            from iotml.stream.kafka_wire import (KafkaWireBroker,
                                                 KafkaWireServer)

            with KafkaWireServer(broker) as srv:
                wb = KafkaWireBroker(f"127.0.0.1:{srv.port}")
                consumer = StreamConsumer(wb, ["SENSOR_DATA_S_AVRO:0:0"],
                                          group="bench-wire")
                sb = SensorBatches(consumer, batch_size=100,
                                   keep_labels=True, poll_chunk=4096)
                t0 = time.perf_counter()
                rows = sum(b.n_valid for b in sb)
                wall = time.perf_counter() - t0
                assert rows == total
                assert sb._ring not in (None, False)
                wb.close()
                return wall

        legs = {}
        for mode in ("python", "fused", "columnar"):
            drain(mode)  # warm (page cache, ring alloc, codec builds)
            walls = [drain(mode) for _ in range(max(3, PASSES // 2))]
            legs[mode], _ = _percentiles(walls)
        drain_wire()
        wire_walls = [drain_wire() for _ in range(3)]
        legs["wire_columnar"], _ = _percentiles(wire_walls)

        # obs v2 overhead gate (ISSUE 13): the wire columnar leg with
        # fleet observability ARMED (watermarks + sampled wire traces)
        # vs obs-off, as paired interleaved passes so drift cancels —
        # the acceptance gate pins armed within 5% of off.  The wire
        # leg is the deployment shape (consumers cross a socket) and
        # the one where the columnar path stays engaged under tracing.
        from iotml.obs import tracing as _tracing
        from iotml.obs import watermark as _wm

        def drain_obs(armed: bool) -> float:
            _wm.configure(enabled=armed)
            _tracing.configure(enabled=armed, sample=0.01, path="")
            try:
                return drain_wire()
            finally:
                _wm.configure(enabled=True)
                _tracing.configure(enabled=False, sample=1.0, path="")
        drain_obs(False)
        drain_obs(True)  # warm both paths
        obs_off, obs_on = [], []
        for _ in range(max(4, PASSES // 2)):
            obs_off.append(drain_obs(False))
            obs_on.append(drain_obs(True))
        # MINIMA, not medians: on a noisy shared box the run-to-run
        # drift of a ~30 ms drain exceeds the armed delta, and the
        # minimum of interleaved passes is the stable cost floor the
        # 5% gate can honestly compare
        t_off, t_on = min(obs_off), min(obs_on)
        out = _bench_produce_legs(broker, total)
        out.update(
            obs_off_records_per_sec=round(total / t_off, 1),
            obs_armed_records_per_sec=round(total / t_on, 1),
            obs_overhead_pct=round((t_on - t_off) / t_off * 100.0, 2))
        broker.close()
        rps = {m: total / w for m, w in legs.items()}
        out.update(
            value=rps["columnar"],
            python_records_per_sec=round(rps["python"], 1),
            fused_records_per_sec=round(rps["fused"], 1),
            wire_columnar_records_per_sec=round(rps["wire_columnar"], 1),
            speedup_vs_python=round(rps["columnar"] / rps["python"], 2),
            speedup_vs_fused=round(rps["columnar"] / rps["fused"], 2),
            decode_mb_per_sec_python=round(payload_mb / legs["python"], 2),
            decode_mb_per_sec_columnar=round(
                payload_mb / legs["columnar"], 2),
            host_pipeline_s_python=round(legs["python"], 3),
            host_pipeline_s_fused=round(legs["fused"], 3),
            host_pipeline_s_columnar=round(legs["columnar"], 3),
            n_records=total)
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_tsdb():
    """Telemetry-plane overhead gate (ISSUE 17): the columnar consume
    leg with the WHOLE self-hosted telemetry plane armed — federated
    scrape (render → parse) → TsdbAppender into the same durable broker
    → SloEngine burn-rate evaluation over the incremental TsdbTail —
    vs the plane off, as paired interleaved passes.  The acceptance
    gate pins armed within 5% of off (the r12 obs-gate protocol:
    MINIMA of interleaved passes, because on a noisy shared box
    run-to-run drift exceeds the armed delta).

    Micro legs alongside: scrape-append ingest rate, cold read_series +
    rate() query wall, incremental-tail evaluation wall, and the
    compaction-boundedness record counts."""
    import shutil
    import tempfile

    from iotml.data.dataset import SensorBatches
    from iotml.obs import federate as _federate
    from iotml.obs import metrics as _obs_metrics
    from iotml.obs import slo as _slo
    from iotml.obs import tsdb as _tsdb
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer

    n_records = int(os.environ.get("IOTML_BENCH_TSDB_RECORDS", "20000"))
    scrape_interval_s = 0.25  # the drill/fleet-server production cadence
    d = tempfile.mkdtemp(prefix="iotml_bench_tsdb_")
    try:
        broker = Broker(store_dir=d)
        _fill_broker(broker, n_records, num_cars=100)
        total = broker.end_offset("SENSOR_DATA_S_AVRO", 0)

        appender = _tsdb.TsdbAppender(broker, chunk_ms=2_000)
        # a rule over a family the drain actually grows, threshold high
        # enough to never fire: realistic evaluation cost, no alert spam
        engine = _slo.SloEngine(
            broker,
            [{"name": "bench-consume", "objective": 0.99,
              "indicator": {"kind": "ratio",
                            "bad": "iotml_records_consumed_total",
                            "total": "iotml_records_consumed_total"},
              "windows": (("fast", 5_000, 30_000, 1e12),)}],
            interval_s=scrape_interval_s)

        def scrape_once():
            _t, samples = _federate.parse_prom_text(
                _obs_metrics.default_registry.render())
            appender.append(samples, process="bench")
            engine.evaluate()

        def one_drain() -> int:
            # ONE group for every drain: per-group consumer metrics mean
            # a fresh group per pass would snowball the registry (and
            # the scrape cost with it) far past any production shape —
            # a real scorer keeps its group for life
            consumer = StreamConsumer(
                broker, ["SENSOR_DATA_S_AVRO:0:0"], group="bench-tsdb")
            sb = SensorBatches(consumer, batch_size=100, poll_chunk=4096)
            return sum(b.n_valid for b in sb)

        # size the timed pass to span several scrape ticks: a ~30 ms
        # drain would see at most one tick and measure nothing
        t0 = time.perf_counter()
        assert one_drain() == total
        repeats = max(3, int(round(
            1.5 / max(time.perf_counter() - t0, 1e-3))))

        def timed_pass(armed: bool) -> float:
            stop = threading.Event()
            th = None
            if armed:
                def plane():
                    while not stop.is_set():
                        scrape_once()
                        stop.wait(scrape_interval_s)
                th = threading.Thread(target=plane, daemon=True,
                                      name="bench-tsdb-plane")
                th.start()
            t0 = time.perf_counter()
            rows = 0
            for _ in range(repeats):
                rows += one_drain()
            wall = time.perf_counter() - t0
            if armed:
                stop.set()
                th.join()
            assert rows == repeats * total, (rows, repeats, total)
            return wall

        timed_pass(False)
        timed_pass(True)  # warm both paths (ring alloc, tail cursor)
        off, on = [], []
        for _ in range(max(4, PASSES // 2)):
            off.append(timed_pass(False))
            on.append(timed_pass(True))
        t_off, t_on = min(off), min(on)

        # ---- micro legs over the TSDB the armed passes just populated
        n_scrapes = 25
        t0 = time.perf_counter()
        n_samples = 0
        for _ in range(n_scrapes):
            _t, samples = _federate.parse_prom_text(
                _obs_metrics.default_registry.render())
            appender.append(samples, process="bench")
            n_samples += len(samples)
        scrape_wall = time.perf_counter() - t0

        q_walls = []
        for _ in range(5):
            t0 = time.perf_counter()
            series = _tsdb.read_series(broker)
            _tsdb.query(series,
                        "rate(iotml_records_consumed_total[30s])")
            q_walls.append(time.perf_counter() - t0)
        query_ms, _p95 = _percentiles(q_walls)

        e_walls = []
        for _ in range(5):
            t0 = time.perf_counter()
            engine.evaluate()
            e_walls.append(time.perf_counter() - t0)
        eval_ms, _p95 = _percentiles(e_walls)

        pre_records = (broker.end_offset(_tsdb.TSDB_TOPIC, 0)
                       - broker.begin_offset(_tsdb.TSDB_TOPIC, 0))
        broker.store.log_for(_tsdb.TSDB_TOPIC, 0).roll()
        broker.run_compaction(force=True)
        post = 0
        off_c = broker.begin_offset(_tsdb.TSDB_TOPIC, 0)
        end_c = broker.end_offset(_tsdb.TSDB_TOPIC, 0)
        while off_c < end_c:
            batch = broker.fetch(_tsdb.TSDB_TOPIC, 0, off_c, 4096)
            if not batch:
                break
            for m in batch:
                off_c = m.offset + 1
                post += 1

        n_drained = repeats * total
        broker.close()
        return dict(
            value=n_drained / t_on,
            tsdb_off_records_per_sec=round(n_drained / t_off, 1),
            tsdb_armed_records_per_sec=round(n_drained / t_on, 1),
            tsdb_overhead_pct=round((t_on - t_off) / t_off * 100.0, 2),
            scrape_append_samples_per_sec=round(
                n_samples / scrape_wall, 1),
            scrape_append_ms=round(scrape_wall / n_scrapes * 1e3, 3),
            query_rate_p50_ms=round(query_ms * 1e3, 3),
            slo_eval_p50_ms=round(eval_ms * 1e3, 3),
            n_series=len(series),
            tsdb_records_precompact=pre_records,
            tsdb_records_postcompact=post,
            scrape_interval_s=scrape_interval_s,
            n_records=n_drained)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _bench_produce_legs(broker, n_records):
    """The WRITE-path legs of the zero-copy plane (ISSUE 12), measured
    over the same durable broker as the consume legs:

      produce_python:   per-record python Avro encode + frame + append
                        (IOTML_RAW_PRODUCE=off — the pre-ISSUE-12 path),
      produce_fused:    native batch Avro encode, classic per-record
                        framing/append,
      produce_columnar: ONE native convert+frame call per batch
                        (NativeCodec.encode_frames) + Broker.produce_raw
                        appending segment-verbatim,
      produce_wire:     the columnar leg through RAW_PRODUCE over a
                        KafkaWireServer socket,

    plus the convert+frame vs append split of the columnar leg (the
    produce-leg breakdown the e2e bench publishes beside its knee)."""
    import numpy as np

    from iotml.core.schema import KSQL_CAR_SCHEMA
    from iotml.ops.avro import AvroCodec
    from iotml.ops.framing import frame
    from iotml.stream import native as native_mod

    n = min(int(n_records), 20_000)
    if not native_mod.available():
        return {"produce_legs": "skipped (native engine unavailable)"}
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    codec = AvroCodec(KSQL_CAR_SCHEMA)
    rng = np.random.default_rng(11)
    numeric = rng.normal(size=(n, nc.n_numeric)).astype(np.float64)
    labels = np.full((n, nc.n_strings), b"false", "S16")
    ts = np.arange(n, dtype=np.int64)
    keys = np.asarray([b"vehicles/sensor/data/car-%05d" % (i % 100)
                       for i in range(n)], "S64")
    numerics = [f.name for f in KSQL_CAR_SCHEMA.fields
                if f.avro_type != "string"]
    rows = [dict(zip(numerics, map(float, numeric[i])),
                 FAILURE_OCCURRED="false") for i in range(n)]
    key_list = [bytes(k) for k in keys]
    topic_i = [0]

    def fresh_topic():
        topic_i[0] += 1
        name = f"BENCH_PRODUCE_{topic_i[0]}"
        broker.create_topic(name, partitions=1)
        return name

    import contextlib

    @contextlib.contextmanager
    def classic_plane():
        # force the per-record write path, RESTORING the caller's knob
        # (an operator running `IOTML_RAW_PRODUCE=on python bench.py`
        # must keep the CI-parity mode for every later bench)
        prev = os.environ.get("IOTML_RAW_PRODUCE")
        os.environ["IOTML_RAW_PRODUCE"] = "off"
        try:
            yield
        finally:
            if prev is None:
                os.environ.pop("IOTML_RAW_PRODUCE", None)
            else:
                os.environ["IOTML_RAW_PRODUCE"] = prev

    def leg_python():
        with classic_plane():
            t = fresh_topic()
            t0 = time.perf_counter()
            broker.produce_many(
                t, [(key_list[i], frame(codec.encode(rows[i]), 1),
                     int(ts[i])) for i in range(n)], partition=0)
            return time.perf_counter() - t0

    def leg_fused():
        with classic_plane():
            t = fresh_topic()
            t0 = time.perf_counter()
            vals = nc.encode_batch(numeric, labels, schema_id=1)
            broker.produce_many(
                t, list(zip(key_list, vals, ts.tolist())), partition=0)
            return time.perf_counter() - t0

    split = {}

    def leg_columnar():
        t = fresh_topic()
        t0 = time.perf_counter()
        blob = nc.encode_frames(numeric, labels, ts, keys=keys,
                                schema_id=1)
        t1 = time.perf_counter()
        broker.produce_raw(t, 0, blob)
        t2 = time.perf_counter()
        split["convert_frame_s"] = round(t1 - t0, 4)
        split["append_s"] = round(t2 - t1, 4)
        return t2 - t0

    def leg_wire():
        from iotml.stream.kafka_wire import (KafkaWireBroker,
                                             KafkaWireServer)

        t = fresh_topic()
        with KafkaWireServer(broker) as srv:
            wb = KafkaWireBroker(f"127.0.0.1:{srv.port}")
            t0 = time.perf_counter()
            blob = nc.encode_frames(numeric, labels, ts, keys=keys,
                                    schema_id=1)
            # one unsplit request: the upper bound of the wire leg
            # (production riders split at IOTML_PRODUCE_BATCH_BYTES —
            # per-request overhead there is measured by this leg's
            # delta against produce_columnar)
            wb.produce_raw(t, 0, blob)
            wall = time.perf_counter() - t0
            wb.close()
        return wall

    walls = {}
    for name, fn in (("python", leg_python), ("fused", leg_fused),
                     ("columnar", leg_columnar), ("wire", leg_wire)):
        fn()  # warm
        walls[name], _ = _percentiles([fn() for _ in
                                       range(max(3, PASSES // 2))])
    rps = {m: n / w for m, w in walls.items()}
    return dict(
        produce_python_records_per_sec=round(rps["python"], 1),
        produce_fused_records_per_sec=round(rps["fused"], 1),
        produce_columnar_records_per_sec=round(rps["columnar"], 1),
        produce_wire_columnar_records_per_sec=round(rps["wire"], 1),
        produce_speedup_vs_python=round(
            rps["columnar"] / rps["python"], 2),
        produce_breakdown_s=split,
        produce_n_records=n)


def bench_twin():
    """Digital-twin + compaction costs (iotml.twin / store.compact):
    twin apply rate (sensor records folded into per-car state per
    second, changelog emission included), compaction throughput over
    the changelog (MB/s reclaimed, dirty -> clean), and the REST query
    path's GET /twin/<car_id> latency — the feature-store freshness and
    queryability story as numbers."""
    import shutil
    import tempfile
    import urllib.request

    from iotml.connect import ConnectServer, ConnectWorker
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.store import StorePolicy
    from iotml.stream.broker import Broker
    from iotml.twin import CHANGELOG_TOPIC, TwinService

    cars = 100
    # publish emits n_ticks * cars records — round the knob down to a
    # whole number of ticks so the applied == published assert holds
    # for any IOTML_BENCH_TWIN_RECORDS value
    n_records = int(os.environ.get("IOTML_BENCH_TWIN_RECORDS", "10000"))
    n_records = max(1, n_records // cars) * cars
    d = tempfile.mkdtemp(prefix="iotml_bench_twin_")
    try:
        broker = Broker(store_dir=d, store_policy=StorePolicy(
            fsync="interval", fsync_interval_s=0.05,
            segment_bytes=256 * 1024, compact_grace_ms=10 ** 9))
        broker.create_topic("SENSOR_DATA_S_AVRO", partitions=2)
        gen = FleetGenerator(FleetScenario(num_cars=cars))
        gen.publish(broker, "SENSOR_DATA_S_AVRO",
                    n_ticks=n_records // cars, partitions=2)
        svc = TwinService(broker)
        t0 = time.perf_counter()
        while svc.pump_once():
            pass
        apply_s = time.perf_counter() - t0
        assert svc.applied == n_records

        # a second wave after the timed apply pass: every car's wave-1
        # changelog entry is now shadowed, so the compaction leg always
        # has bytes to reclaim (a small records knob can otherwise fit
        # one pump — one coalesced record per car, already clean)
        gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=1, partitions=2)
        while svc.pump_once():
            pass

        # compaction throughput: seal the changelog, one forced pass
        for p in range(2):
            broker.store.log_for(CHANGELOG_TOPIC, p).roll()
        t0 = time.perf_counter()
        stats = broker.run_compaction(force=True)
        compact_s = time.perf_counter() - t0
        reclaimed = sum(s.bytes_reclaimed for s in stats.values())
        assert reclaimed > 0

        # query latency: GET /twin/<car_id> over the live connect REST
        srv = ConnectServer(ConnectWorker(broker)).start()
        try:
            srv.attach_twin(svc)
            ids = svc.cars()
            urllib.request.urlopen(f"{srv.url}/twin/{ids[0]}",
                                   timeout=5).read()  # warm
            lats = []
            for i in range(200):
                car = ids[i % len(ids)]
                t0 = time.perf_counter()
                urllib.request.urlopen(f"{srv.url}/twin/{car}",
                                       timeout=5).read()
                lats.append(time.perf_counter() - t0)
        finally:
            srv.stop()
        broker.close()
        q50, q95 = _percentiles(lats)
        return dict(value=n_records / apply_s,
                    compaction_mb_per_sec_reclaimed=round(
                        reclaimed / 1e6 / compact_s, 2),
                    compaction_reclaimed_mb=round(reclaimed / 1e6, 2),
                    twin_query_ms_p50=round(q50 * 1e3, 3),
                    twin_query_ms_p95=round(q95 * 1e3, 3),
                    cars=cars, n_records=n_records)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_gateway():
    """Sharded scatter-gather twin serving (iotml.gateway, ISSUE 20):
    aggregate point-lookup throughput through the smart client's
    pipelined per-shard mget scatter (each key's latency is its batch's
    round trip), measured WHILE keyed ingest keeps folding, a second
    client runs feature-join matrix scatters (the StreamScorer shape),
    and one primary shard is killed and its warm standby promoted
    mid-storm.  The ISSUE gate (>=50k lookups/s aggregate, p99 < 10 ms)
    assumes the multi-core serving box the subsystem shards FOR;
    ``gate_applicable`` records whether this box qualifies."""
    import random

    import numpy as np

    from iotml.gateway import GatewayClient, GatewayCluster
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.stream.broker import Broker
    from iotml.supervise.registry import register_thread

    cars = 512
    partitions = 8
    batch = 128
    n_lookups = int(os.environ.get("IOTML_BENCH_GATEWAY_LOOKUPS",
                                   "200000"))
    n_lookups = max(batch, n_lookups // batch * batch)
    broker = Broker()
    broker.create_topic("SENSOR_DATA_S_AVRO", partitions=partitions)
    gen = FleetGenerator(FleetScenario(num_cars=cars, seed=20))
    published = gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=4,
                            partitions=partitions)
    cluster = GatewayCluster(broker, n_shards=2).start()
    client = GatewayClient(cluster)
    deadline = time.monotonic() + 120
    while client.aggregate()["records"] < published:
        if time.monotonic() >= deadline:
            raise RuntimeError("gateway shards did not drain the seed")
        time.sleep(0.05)
    ids = client.cars(limit=cars)
    assert len(ids) == cars
    keys = [i.encode() for i in ids]

    stop = threading.Event()
    half = threading.Event()
    joined = [0]
    promote_s = [None]

    # the concurrent workloads run at PACED stream-shaped rates (a
    # fleet tick of ingest ~2.5k rec/s, a scorer join batch every
    # 100 ms), not CPU-max — free-running antagonists on a small box
    # would measure GIL starvation, not serving capacity
    def _ingest():
        while not stop.is_set():
            gen.publish(broker, "SENSOR_DATA_S_AVRO", n_ticks=1,
                        partitions=partitions)
            stop.wait(0.2)

    def _score():
        sc = GatewayClient(cluster)
        i = 0
        while not stop.is_set():
            ks = [keys[(i + j) % cars] for j in range(batch)]
            sc.matrix(ks, batch)
            joined[0] += batch
            i += batch
            stop.wait(0.1)
        sc.close()

    def _failover():
        half.wait(timeout=600)
        if stop.is_set():
            return
        # make sure the standby is warm before the crash (the drill
        # asserts the SLO; here the point is serving THROUGH it)
        t_end = time.monotonic() + 30
        while cluster.standbys[0].lag() > 0 and time.monotonic() < t_end:
            time.sleep(0.02)
        cluster.kill_shard(0)
        promote_s[0] = cluster.promote(0)

    threads = [register_thread(threading.Thread(
        target=fn, daemon=True, name=f"iotml-bench-gw-{nm}"))
        for nm, fn in (("ingest", _ingest), ("score", _score),
                       ("failover", _failover))]
    for t in threads:
        t.start()

    rng = random.Random(20)
    rtts = []  # (seconds, keys answered) per scatter round trip
    done = 0
    t0 = time.perf_counter()
    while done < n_lookups:
        ks = [ids[rng.randrange(cars)] for _ in range(batch)]
        t1 = time.perf_counter()
        docs = client.mget(ks)
        rtts.append((time.perf_counter() - t1, batch))
        assert all(d is not None and d["car"] == k
                   for k, d in zip(ks, docs))
        done += batch
        if done >= n_lookups // 2:
            half.set()
    elapsed = time.perf_counter() - t0
    stop.set()
    half.set()
    for t in threads:
        t.join(timeout=30)
    # a small unpipelined sample: what ONE key costs end to end
    point = []
    for i in range(200):
        t1 = time.perf_counter()
        client.get(ids[i % cars])
        point.append(time.perf_counter() - t1)
    client.close()
    cluster.stop()

    per_key = np.repeat([t for t, _ in rtts], [k for _, k in rtts])
    lookups_per_sec = done / elapsed
    p50 = float(np.percentile(per_key, 50)) * 1e3
    p99 = float(np.percentile(per_key, 99)) * 1e3
    pp50, pp95 = _percentiles(point)
    gate_applicable = (os.cpu_count() or 1) >= 4
    gate_passed = bool(lookups_per_sec >= 50_000 and p99 < 10.0)
    return dict(value=lookups_per_sec,
                lookup_p50_ms=round(p50, 3),
                lookup_p99_ms=round(p99, 3),
                point_get_p50_ms=round(pp50 * 1e3, 3),
                point_get_p95_ms=round(pp95 * 1e3, 3),
                n_lookups=done, batch_keys=batch, cars=cars,
                n_shards=2, partitions=partitions,
                scorer_joins=joined[0],
                failover_promote_s=(round(promote_s[0], 4)
                                    if promote_s[0] is not None else None),
                gate_applicable=gate_applicable,
                gate_passed=(gate_passed if gate_applicable else None))


def bench_checkpoint():
    """Async-checkpointing overhead on the streaming train loop
    (iotml.mlops): the same ContinuousTrainer rounds run three ways —
    publication OFF (the do-nothing upper bound), ASYNC registry
    checkpointing (snapshot on the train thread, serialize+fsync on
    the writer thread), and the legacy SYNC h5-export-per-round.  The
    ISSUE 7 claim is async-vs-off within 10%; the sync column shows
    what the hot loop used to pay.  Also measured: the train-thread
    snapshot cost (the ONLY part async adds to the hot path) and the
    off-thread serialize+publish cost it moved away."""
    import shutil
    import tempfile

    from iotml.mlops import AsyncCheckpointer, ModelRegistry
    from iotml.stream.broker import Broker
    from iotml.train.artifacts import ArtifactStore
    from iotml.train.live import ContinuousTrainer

    import statistics

    # enough rounds that each timed pass spans several checkpoint
    # cadence periods — an 8-round (~60ms) window would charge one
    # whole 35ms write against it and measure the ratio of two
    # accidents, not the steady-state overhead.  Passes are
    # INTERLEAVED across modes (off pass, async pass, sync pass,
    # repeat) and the overhead is the median of PAIRED off/async
    # ratios: this box's available CPU drifts by 2-3x across a bench
    # run (shared 2-core host), so back-to-back pairs see the same
    # machine and the ratio cancels the drift a sequential
    # mode-at-a-time comparison would book as checkpoint cost.
    # each pass must span >= 2 checkpoint-cadence periods, or writes
    # get charged at an inflated effective rate (a 0.27s window books
    # its ~1.3 writes as one per 200ms against a 500ms cadence)
    rounds = int(os.environ.get("IOTML_BENCH_CKPT_ROUNDS", "120"))
    n_passes = int(os.environ.get("IOTML_BENCH_CKPT_PASSES", "3"))
    take, batch = 10, 100
    per_round = take * batch
    n_records = (n_passes * (rounds + 1) + 2) * per_round
    modes = ("off", "async", "sync_store")

    def make_mode(mode):
        broker = _fill_broker(Broker(), n_records)
        tmp = tempfile.mkdtemp(prefix="iotml_bench_ckpt_")
        ck = None
        if mode == "async":
            # production cadence (cli defaults): at most ~2
            # versions/s — sub-second rounds coalesce, a slow round
            # still checkpoints every round
            ck = AsyncCheckpointer(ModelRegistry(tmp), min_interval_s=0.5)
            tr = ContinuousTrainer(broker, "SENSOR_DATA_S_AVRO", None,
                                   checkpointer=ck, take_batches=take,
                                   batch_size=batch, group=f"b-{mode}")
            ck.start()
        else:
            tr = ContinuousTrainer(broker, "SENSOR_DATA_S_AVRO",
                                   ArtifactStore(tmp),
                                   take_batches=take, batch_size=batch,
                                   group=f"b-{mode}")
            if mode == "off":
                tr.publish = lambda: "off"  # rounds pay zero
                # publication cost: the do-nothing upper bound
        return tr, ck, tmp

    setups = {m: make_mode(m) for m in modes}
    passes = {m: [] for m in modes}
    written = 0
    try:
        for m in modes:
            setups[m][0].train_round()  # compile warm-up, off-window
        # drain the warm-up checkpoint BEFORE the window: the first
        # write of a process pays the h5py import + allocator warmup on
        # the writer thread — one-time cost, not steady-state overhead
        setups["async"][1].flush(timeout_s=30.0)
        for _ in range(n_passes):
            for m in modes:
                tr = setups[m][0]
                t0 = time.perf_counter()
                for _ in range(rounds):
                    tr.train_round()
                passes[m].append(rounds * per_round
                                 / (time.perf_counter() - t0))
        ck = setups["async"][1]
        ck.stop(flush=True)
        written = ck.written
        assert written >= 1
    finally:
        for tr, ck, tmp in setups.values():
            if ck is not None:
                ck.stop(flush=False)
            shutil.rmtree(tmp, ignore_errors=True)

    rps = {m: statistics.median(passes[m]) for m in modes}
    # paired per-pass overhead: each async pass vs the off pass run
    # seconds before it on the same machine state
    pair_overheads = [100.0 * (o - a) / o
                      for o, a in zip(passes["off"], passes["async"])]
    # the two costs the split separates: what stayed on the train
    # thread (device->host snapshot) vs what moved off it
    import jax
    import numpy as np

    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.train.loop import Trainer

    trn = Trainer(CAR_AUTOENCODER)
    trn._ensure_state(np.zeros((batch, 18), np.float32))
    tmp = tempfile.mkdtemp(prefix="iotml_bench_ckpt_")
    try:
        ck = AsyncCheckpointer(ModelRegistry(tmp), queue_depth=64)
        snaps = []
        for _ in range(16):
            t0 = time.perf_counter()
            ck.snapshot(trn.state, [("SENSOR_DATA_S_AVRO", 0, 1)])
            snaps.append(time.perf_counter() - t0)
        writes = []
        while ck.pending():
            t0 = time.perf_counter()
            ck.write_once()
            writes.append(time.perf_counter() - t0)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    overhead = statistics.median(pair_overheads)
    return dict(value=rps["async"],
                rps_checkpoint_off=round(rps["off"], 1),
                rps_sync_store=round(rps["sync_store"], 1),
                async_overhead_pct=round(overhead, 2),
                checkpoints_written=written,
                passes_off=[round(p, 1) for p in passes["off"]],
                passes_async=[round(p, 1) for p in passes["async"]],
                snapshot_ms_p50=round(
                    1e3 * _percentiles(snaps)[0], 3),
                offthread_write_ms_p50=round(
                    1e3 * _percentiles(writes)[0], 3),
                rounds=rounds, n_passes=n_passes,
                records_per_round=per_round)


# ----------------------------------------------------------- online
def bench_online():
    """Online-vs-micro-batch adaptation after a seeded regional drift
    (iotml.online), plus the adversarial fleet scenario suite scored
    with the r04 detection-quality + saturation harnesses.

    The headline: after a seeded drift, how many records does each
    training mode need before live detection AUC is back within 0.05
    of the deployed model's pre-drift AUC?  Both modes start from the
    SAME pre-trained model over the SAME byte-identical stream; the
    micro-batch baseline is this repo's own ContinuousTrainer (2000-
    record rounds through the registry — a far stronger baseline than
    the reference's 10k-record retrain-then-redeploy cycle), so the
    measured gap is what drift DETECTION + adaptation buys, not a
    strawman.  Riding along: the throughput guard (incremental updates
    >= 80% of micro-batch train rate, measured in-run on the same
    box) and one bounded detection-quality + throughput pass per
    adversarial scenario."""
    import shutil
    import tempfile

    import jax
    import numpy as np

    from iotml.data.dataset import SensorBatches
    from iotml.gen.scenarios import AdversarialFleet, condition
    from iotml.gen.simulator import FleetScenario
    from iotml.mlops import ModelRegistry, RegistryWatcher
    from iotml.mlops.checkpoint import params_to_h5_bytes
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.online.learner import OnlineLearner
    from iotml.serve.scorer import StreamScorer, hist_auc
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.producer import OutputSequence
    from iotml.train.live import ContinuousTrainer
    from iotml.train.loop import Trainer

    TOPIC = "SENSOR_DATA_S_AVRO"
    CARS = 50
    # seed 11's failure draw has 5 VISIBLE failing cars (vibration /
    # tire modes) — battery-mode failures live in columns the PARITY
    # normalizer zeroes, and a fleet of invisible anomalies measures
    # label noise, not detection (the drill walks seeds for the same
    # reason)
    SEED = 11
    PRE_TICKS = 120        # 6000-record pre-drift pretrain slice
    LIVE_PRE_TICKS = 60    # 3000 live pre-drift records (baseline)
    POST_TICKS = int(os.environ.get("IOTML_BENCH_ONLINE_POST_TICKS",
                                    "360"))  # 18k post-drift records
    CHUNK_TICKS = 20       # 1000-record AUC trajectory windows
    EPS = 0.05

    def fresh_fleet():
        return AdversarialFleet(
            FleetScenario(num_cars=CARS, failure_rate=0.12, seed=SEED),
            condition("regional-drift",
                      drift_tick=PRE_TICKS + LIVE_PRE_TICKS))

    # ---- the deployed model: pre-trained on the pre-drift slice
    b0 = Broker()
    f0 = fresh_fleet()
    f0.publish_stream(b0, TOPIC, n_ticks=PRE_TICKS)
    pre = Trainer(CAR_AUTOENCODER)
    pre.fit_compiled(
        SensorBatches(StreamConsumer(b0, [f"{TOPIC}:0:0"], group="pt"),
                      batch_size=100, only_normal=True, cache=True),
        epochs=12)
    params0 = jax.device_get(pre.state.params)
    # held-out pre-drift AUC of the deployed model — the recovery
    # target both modes chase (fixed weights, fresh pre-drift records)
    f0.publish_stream(b0, TOPIC, n_ticks=LIVE_PRE_TICKS)
    sc0 = StreamScorer(
        CAR_AUTOENCODER, params0,
        SensorBatches(StreamConsumer(b0, [f"{TOPIC}:{0}:"
                                          f"{PRE_TICKS * CARS}"],
                                     group="pt-auc"),
                      batch_size=100, keep_labels=True),
        OutputSequence(b0, "preds-pt", partition=0), threshold=5.0)
    sc0.score_available()
    auc_pre = hist_auc(sc0.err_hist["true"], sc0.err_hist["false"])

    def trajectory(mode):
        """Drive one mode over the byte-identical stream; return the
        per-window AUC trajectory + records-to-recover."""
        broker = Broker()
        fleet = fresh_fleet()
        fleet.publish_stream(broker, TOPIC, n_ticks=PRE_TICKS)
        root = tempfile.mkdtemp(prefix=f"iotml_bench_online_{mode}_")
        reg = ModelRegistry(root)
        mark = broker.end_offset(TOPIC, 0)
        reg.promote(reg.publish(
            {"model.h5": params_to_h5_bytes(params0)},
            offsets=[(TOPIC, 0, mark)]).version)
        if mode == "online":
            trainer = OnlineLearner(broker, TOPIC, registry=reg,
                                    group=f"bench-{mode}", window=100,
                                    publish_every=10)

            def pump_trainer():
                while trainer.process_available(max_updates=64):
                    trainer.write_published()
                    watcher.poll_once()
        else:
            trainer = ContinuousTrainer(
                broker, TOPIC, None, registry=reg,
                group=f"bench-{mode}", batch_size=100, take_batches=20)

            def pump_trainer():
                while trainer.available() >= trainer.min_available:
                    trainer.train_round()
                    trainer.checkpointer.write_once()
                    watcher.poll_once()
        cons = StreamConsumer.from_committed(
            broker, TOPIC, [0], group=f"bench-{mode}-scorer", eof=True)
        cons.seek(TOPIC, 0, mark)
        scorer = StreamScorer(
            CAR_AUTOENCODER, None,
            SensorBatches(cons, batch_size=100, keep_labels=True),
            OutputSequence(broker, f"preds-{mode}", partition=0),
            threshold=5.0)
        watcher = RegistryWatcher(reg, scorers=[scorer])
        watcher.poll_once()

        aucs = []
        hist = {k: v.copy() for k, v in scorer.err_hist.items()}
        post_windows = []
        marks = {}

        def run_chunks(n_ticks, collect):
            nonlocal hist
            for _ in range(n_ticks // CHUNK_TICKS):
                fleet.publish_stream(broker, TOPIC,
                                     n_ticks=CHUNK_TICKS)
                pump_trainer()
                scorer.score_available()
                h2 = {k: v.copy() for k, v in scorer.err_hist.items()}
                a = hist_auc(h2["true"] - hist["true"],
                             h2["false"] - hist["false"])
                hist = h2
                collect.append(a)

        run_chunks(LIVE_PRE_TICKS, aucs)       # live pre-drift
        # capture the update counter AT drift onset (the drill's
        # protocol): deriving it from record counts mis-states the
        # latency because only_normal filtering makes update windows
        # slightly sparser than raw records
        marks["updates_at_drift"] = getattr(trainer, "updates", 0)
        run_chunks(POST_TICKS, post_windows)   # drifted
        shutil.rmtree(root, ignore_errors=True)
        recover = None
        target = (auc_pre or 0.0) - EPS
        for i in range(len(post_windows) - 1):
            w0, w1 = post_windows[i], post_windows[i + 1]
            if w0 is not None and w1 is not None \
                    and w0 >= target and w1 >= target:
                recover = (i + 1) * CHUNK_TICKS * CARS
                break
        detect = None
        if mode == "online":
            post_adapt = [a for a in trainer.adaptations
                          if a[0] > marks["updates_at_drift"]]
            if post_adapt:
                # updates are 100-record windows past the live marker
                detect = (post_adapt[0][0]
                          - marks["updates_at_drift"]) * 100
        return dict(recover=recover, detect=detect,
                    auc_first_post=post_windows[0] if post_windows
                    else None,
                    auc_final=post_windows[-1] if post_windows
                    else None,
                    windows=[None if a is None else round(a, 4)
                             for a in post_windows])

    online = trajectory("online")
    micro = trajectory("microbatch")

    # ---- throughput guard: incremental updates vs micro-batch rounds
    # on the same prefilled stream (same box, same minute)
    def throughput_online():
        broker = Broker()
        fleet = AdversarialFleet(
            FleetScenario(num_cars=100, failure_rate=0.01, seed=SEED),
            condition("baseline"))
        fleet.publish_stream(broker, TOPIC, n_ticks=400)
        lrn = OnlineLearner(broker, TOPIC, window=100,
                            publish_every=10**9)
        for k in (8, 4, 2, 1, 8):   # warm every fuse variant
            lrn.process_available(max_updates=k)
        t0 = time.perf_counter()
        got = lrn.process_available()
        return got * 100 / (time.perf_counter() - t0)

    def throughput_micro():
        broker = Broker()
        fleet = AdversarialFleet(
            FleetScenario(num_cars=100, failure_rate=0.01, seed=SEED),
            condition("baseline"))
        fleet.publish_stream(broker, TOPIC, n_ticks=400)
        from iotml.train.artifacts import ArtifactStore

        tmp = tempfile.mkdtemp(prefix="iotml_bench_online_tp_")
        tr = ContinuousTrainer(broker, TOPIC, ArtifactStore(tmp),
                               batch_size=100, take_batches=20,
                               group="bench-tp")
        tr.train_round()  # compile warmup
        t0 = time.perf_counter()
        recs = 0
        while tr.available() >= tr.min_available:
            recs += tr.train_round().get("records", 0)
        dt = time.perf_counter() - t0
        shutil.rmtree(tmp, ignore_errors=True)
        return recs / dt
    # interleaved passes, paired ratio (the shared-box discipline of
    # bench_checkpoint): this 2-core host's available CPU drifts
    rps_on, rps_mb = [], []
    for _ in range(3):
        rps_on.append(throughput_online())
        rps_mb.append(throughput_micro())
    import statistics

    online_rps = statistics.median(rps_on)
    micro_rps = statistics.median(rps_mb)
    ratio = online_rps / micro_rps if micro_rps else 0.0

    # ---- the adversarial scenario suite: one bounded pass each,
    # detection quality + pipeline rate (the r04 + saturation
    # harnesses applied to every condition, not just the benign fleet)
    def scenario_pass(name, ticks=60, mqtt_path=False):
        broker = Broker()
        fleet = AdversarialFleet(
            FleetScenario(num_cars=CARS, failure_rate=0.12, seed=SEED),
            condition(name, **({"drift_tick": ticks // 2}
                               if name == "regional-drift" else {})))
        t0 = time.perf_counter()
        if mqtt_path:
            from iotml.mqtt.bridge import KafkaBridge
            from iotml.mqtt.broker import MqttBroker
            from iotml.streamproc.tasks import JsonToAvro

            mqtt = MqttBroker()
            KafkaBridge(mqtt, broker, partitions=1)
            conv = JsonToAvro(broker, src="sensor-data", dst=TOPIC,
                              partitions=1)
            published = fleet.publish_mqtt(mqtt, n_ticks=ticks)
            conv.process_available()
        else:
            published = fleet.publish_stream(broker, TOPIC,
                                             n_ticks=ticks)
        scorer = StreamScorer(
            CAR_AUTOENCODER, params0,
            SensorBatches(StreamConsumer(broker, [f"{TOPIC}:0:0"],
                                         group=f"sc-{name}"),
                          batch_size=100, keep_labels=True),
            OutputSequence(broker, f"preds-{name}", partition=0),
            threshold=5.0)
        scorer.score_available()
        dt = time.perf_counter() - t0
        auc = hist_auc(scorer.err_hist["true"],
                       scorer.err_hist["false"])
        out = {"records_per_sec": round(published / dt, 1),
               "auc": None if auc is None else round(auc, 4),
               "published": published}
        if mqtt_path:
            out["deferred"] = fleet.deferred_total
            out["flap_buffered"] = fleet.flap_buffered_total
        return out

    scenarios = {
        "rush-hour": scenario_pass("rush-hour", mqtt_path=True),
        "flapping-links": scenario_pass("flapping-links",
                                        mqtt_path=True),
        "regional-drift": scenario_pass("regional-drift"),
        "schema-mix": scenario_pass("schema-mix"),
    }

    return dict(
        value=float(online["recover"] or POST_TICKS * CARS),
        microbatch_records_to_recover=micro["recover"],
        online_detect_records=online["detect"],
        speedup_x=round(micro["recover"] / online["recover"], 2)
        if online["recover"] and micro["recover"] else None,
        auc_pre_drift=round(auc_pre, 4) if auc_pre else None,
        online_auc_first_post=online["auc_first_post"],
        online_auc_final=online["auc_final"],
        microbatch_auc_final=micro["auc_final"],
        online_windows=online["windows"],
        microbatch_windows=micro["windows"],
        online_train_records_per_sec=round(online_rps, 1),
        microbatch_train_records_per_sec=round(micro_rps, 1),
        throughput_ratio=round(ratio, 3),
        scenarios=scenarios,
        n_passes=1,
        definition="records after the seeded drift until live "
                   "detection AUC holds within 0.05 of the deployed "
                   "model's pre-drift AUC for 2 consecutive 1000-"
                   "record windows; online = incremental + drift-"
                   "triggered adaptation, microbatch = "
                   "ContinuousTrainer rounds through the registry")


# ------------------------------------------------------ cluster saturation
_CLUSTER_NODE_SRC = r"""
import sys

shard = int(sys.argv[1])
n = int(sys.argv[2])
ports = [int(x) for x in sys.argv[3].split(",")]

from iotml.cluster.shard import ShardBroker
from iotml.stream.kafka_wire import KafkaWireServer


class View:
    node_id = shard

    def brokers(self):
        return [(i, "127.0.0.1", pt) for i, pt in enumerate(ports)]

    def leader_node(self, t, p):
        return p % n

    def coordinator(self):
        return (0, "127.0.0.1", ports[0])


broker = ShardBroker(lambda t, p: p % n == shard, shard_id=shard)
srv = KafkaWireServer(broker, port=ports[shard], cluster=View())
srv.start()
print("READY", flush=True)
sys.stdin.read()  # parent closes stdin -> exit
"""

_CLUSTER_PRODUCER_SRC = r"""
import sys
import time

boot, topic = sys.argv[1], sys.argv[2]
parts, dur, size = int(sys.argv[3]), float(sys.argv[4]), int(sys.argv[5])
start = int(sys.argv[6])

from iotml.cluster import ClusterClient

c = ClusterClient(bootstrap=boot, client_id="bench-prod")
batch = [(None, b"x" * size, 0)] * 256
t0 = time.perf_counter()
n = 0
p = start % parts
while time.perf_counter() - t0 < dur:
    c.produce_many(topic, batch, partition=p)
    n += len(batch)
    p = (p + 1) % parts
print(n, flush=True)
"""

_CLUSTER_CONSUMER_SRC = r"""
import sys
import time

boot, topic = sys.argv[1], sys.argv[2]
parts = [int(x) for x in sys.argv[3].split(",")]
dur = float(sys.argv[4])

from iotml.cluster import ClusterClient

c = ClusterClient(bootstrap=boot, client_id="bench-cons")
offs = {p: 0 for p in parts}
n = 0
t0 = time.perf_counter()
while time.perf_counter() - t0 < dur:
    moved = 0
    for p in parts:
        msgs = c.fetch(topic, p, offs[p], 2000)
        if msgs:
            offs[p] = msgs[-1].offset + 1
            n += len(msgs)
            moved += len(msgs)
    if not moved:
        time.sleep(0.002)
print(n, flush=True)
"""


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _cluster_saturation_once(n_brokers, partitions, duration,
                             n_producers, payload_bytes=120):
    """Overdriven produce+consume through N broker PROCESSES; returns
    (consumed_records_per_sec, produced_records_per_sec).  Separate
    processes per broker / producer / consumer — the point is whether
    the data plane scales past one core, which threads under one GIL
    cannot show."""
    import subprocess

    ports = _free_ports(n_brokers)
    csv = ",".join(str(p) for p in ports)
    boot = ",".join(f"127.0.0.1:{p}" for p in ports)
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "JAX_"))}
    nodes = []
    procs = []
    try:
        for i in range(n_brokers):
            nodes.append(subprocess.Popen(
                [sys.executable, "-c", _CLUSTER_NODE_SRC, str(i),
                 str(n_brokers), csv],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__))))
        for node in nodes:
            assert node.stdout.readline().strip() == b"READY"
        from iotml.cluster import ClusterClient

        admin = ClusterClient(bootstrap=boot, client_id="bench-admin")
        admin.create_topic("bench", partitions=partitions)
        admin.close()
        for i in range(n_producers):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _CLUSTER_PRODUCER_SRC, boot,
                 "bench", str(partitions), str(duration),
                 str(payload_bytes), str(i)],
                stdout=subprocess.PIPE, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__))))
        # one consumer process per BROKER, draining that shard's
        # partitions — process count stays bounded on small CI boxes
        for shard in range(n_brokers):
            mine = ",".join(str(p) for p in range(partitions)
                            if p % n_brokers == shard)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _CLUSTER_CONSUMER_SRC, boot,
                 "bench", mine, str(duration + 1.0)],
                stdout=subprocess.PIPE, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__))))
        counts = [int(p.stdout.readline() or 0) for p in procs]
        produced = sum(counts[:n_producers])
        consumed = sum(counts[n_producers:])
        return consumed / (duration + 1.0), produced / duration
    finally:
        for p in procs:
            p.wait(timeout=30)
        for node in nodes:
            try:
                node.stdin.close()
            except OSError:
                pass
            node.wait(timeout=10)


def bench_cluster_saturation():
    """The iotml.cluster headline: the e2e data-plane saturation knee at
    1 broker vs 3 brokers (same 6 partitions, same overdriving producer
    fleet).  The single-leader knee was the platform ceiling (~13.3k
    rec/s, BENCH_r05); sharding must move it with broker count or the
    subsystem is decoration.  Pure wire path — no model, no MQTT — so
    the number isolates exactly what the cluster changes."""
    duration = float(os.environ.get("IOTML_BENCH_CLUSTER_SECONDS", "6"))
    partitions = 6
    n_producers = 3
    single, single_prod = _cluster_saturation_once(
        1, partitions, duration, n_producers)
    triple, triple_prod = _cluster_saturation_once(
        3, partitions, duration, n_producers)
    # the platform's measured single-LEADER e2e knee before this
    # subsystem existed (BENCH_r05: p95 ~2s when overdriven at 15k/s) —
    # the ceiling the cluster had to move
    r05_knee = 13_300.0
    return dict(value=round(triple, 1),
                single_broker_records_per_sec=round(single, 1),
                produced_per_sec_1b=round(single_prod, 1),
                produced_per_sec_3b=round(triple_prod, 1),
                scaling_x=round(triple / single, 2) if single else 0.0,
                vs_r05_single_leader_knee=round(triple / r05_knee, 2),
                r05_single_leader_knee=r05_knee,
                brokers=3, partitions=partitions,
                n_producers=n_producers, duration_s=duration,
                cores=os.cpu_count())


# ----------------------------------------------------------- multichip
_MULTICHIP_WORKER = r"""
import json, os, sys
n, records, warmup, batch, passes = (int(x) for x in sys.argv[1:6])
import jax
jax.config.update("jax_platforms", "cpu")
from iotml.parallel.streaming import bench_leg
best = None
for _ in range(passes):
    leg = bench_leg(n, records=records, warmup_records=warmup,
                    batch_size=batch)
    if best is None or leg["records_per_sec"] > best["records_per_sec"]:
        best = leg
best["passes"] = passes
print("MULTICHIP_LEG " + json.dumps(best), flush=True)
"""


def bench_multichip():
    """Multi-chip streaming training 1→N chips (ISSUE 15): each leg is
    a CHILD process pinned to N emulated devices
    (``XLA_FLAGS=--xla_force_host_platform_device_count=N`` — real
    chips when present make the flag a no-op), running the full
    streaming path: durable columnar broker → partition-parallel feeds
    (one consumer + decode ring per device) → per-device ``device_put``
    → sharded jitted step with device-side normalization and the
    gradient all-reduce over the mesh.

    Legs share the `parallel.streaming.leg_record` schema with the
    driver's MULTICHIP_r* harness so curves are comparable across
    rounds.  HONESTY CAVEAT, recorded in the output: on a host with
    fewer cores than devices the emulated chips SERIALIZE on the same
    silicon — the curve then measures dispatch amortization only, and
    ``gate_applicable`` goes false (the CI gate runs on a >= 4-core
    runner, where 4 emulated devices genuinely parallelize)."""
    import subprocess
    import tempfile

    records = int(os.environ.get("IOTML_BENCH_MULTICHIP_RECORDS",
                                 "40000"))
    warmup = int(os.environ.get("IOTML_BENCH_MULTICHIP_WARMUP", "8000"))
    passes = int(os.environ.get("IOTML_BENCH_MULTICHIP_PASSES", "3"))
    batch = 100  # the reference's per-chip batch
    cores = os.cpu_count() or 1
    device_counts = [1, 2, 4] + ([8] if cores >= 8 else [])

    repo = os.path.dirname(os.path.abspath(__file__))
    env_base = dict(os.environ)
    env_base["PYTHONPATH"] = repo
    env_base["JAX_PLATFORMS"] = "cpu"
    # the TPU-tunnel sitecustomize registers its backend at interpreter
    # start and would override the forced CPU device count
    for k in list(env_base):
        if k.startswith(("PALLAS_AXON", "AXON_", "JAX_COORDINATOR",
                         "JAX_NUM_PROCESSES", "JAX_PROCESS_ID")):
            env_base.pop(k)

    legs = []
    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as fh:
        fh.write(_MULTICHIP_WORKER)
        script = fh.name
    try:
        for n in device_counts:
            env = dict(env_base)
            env["XLA_FLAGS"] = \
                f"--xla_force_host_platform_device_count={n}"
            out = subprocess.run(
                [sys.executable, script, str(n), str(records),
                 str(warmup), str(batch), str(passes)],
                env=env, cwd=repo, capture_output=True, text=True,
                timeout=900)
            if out.returncode != 0:
                raise RuntimeError(f"multichip leg n={n} failed:\n"
                                   f"{out.stdout}\n{out.stderr}")
            line = next(l for l in out.stdout.splitlines()
                        if l.startswith("MULTICHIP_LEG "))
            legs.append(json.loads(line[len("MULTICHIP_LEG "):]))
    finally:
        os.unlink(script)

    by_dev = {leg["devices"]: leg["records_per_sec"] for leg in legs}
    base = by_dev.get(1, 0.0)
    scaling = {str(n): round(by_dev[n] / base, 2) if base else 0.0
               for n in device_counts if n != 1}
    top = max(device_counts)
    return dict(value=by_dev.get(top, 0.0), legs=legs,
                scaling_x=scaling,
                scaling_x_4dev=scaling.get("4", 0.0),
                cores=cores, emulated=True,
                # cores < devices: the emulation serializes device
                # compute on shared silicon; the 1.6x gate belongs to
                # hosts where the chips actually run in parallel
                gate_applicable=cores >= 4,
                per_device_batch=batch, records_per_leg=records,
                passes=passes,
                definition="full streaming path per leg: durable "
                           "columnar broker -> partition-parallel "
                           "feeds -> per-device device_put -> sharded "
                           "step (device-side normalization, grad "
                           "all-reduce); best of passes")


def bench_ksql_pipeline():
    """The reference's four-object KSQL pipeline (JSON stream → AVRO CSAS →
    rekey CSAS → 5-min CTAS) pumped over a seeded sensor-data topic — the
    stream-preprocessing stage's sustained rate (input records/s through
    ALL FOUR queries).  Native-codec batch encode/decode carries the Avro
    legs; vs_baseline is the 10k msgs/s fleet rate the stage must keep up
    with."""
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.stream.broker import Broker
    from iotml.streamproc import SqlEngine, install_reference_pipeline

    walls = []
    n = 0
    for _ in range(max(3, PASSES // 2)):
        broker = Broker()
        gen = FleetGenerator(FleetScenario(num_cars=100, failure_rate=0.01))
        n = gen.publish(broker, "sensor-data", n_ticks=200,
                        encoding="json", partitions=2)
        engine = SqlEngine(broker)
        install_reference_pipeline(engine)
        t0 = time.perf_counter()
        engine.pump()
        walls.append(time.perf_counter() - t0)
    p50, p95 = _percentiles(walls)
    return dict(value=n / p50, records_in=n, p50_s=round(p50, 3),
                p95_s=round(p95, 3), n_passes=len(walls))


# ---------------------------------------------------------- lstm/mnist
def bench_lstm_train():
    """The reference's SECOND model family as a captured number: the
    supervised LSTM next-step predictor (LSTM-TensorFlow-IO-Kafka/
    cardata-v1.py:165-200 — window(look_back=1, shift=1) + skip, MSE, 5
    epochs), re-batched from the reference's pathological batch=1 to
    [64, T, F] windows for the MXU (cli/lstm.py keeps the CLI contract).

    Volume: 10,000 windows per job (the reference job is 1,000 train
    steps at batch 1 = 1,000 windows; the 10× volume makes the number a
    throughput, not a dispatch-latency echo — per-window semantics are
    identical)."""
    from iotml.cli.lstm import BATCH_SIZE, LOOK_BACK, NB_EPOCH
    from iotml.data.dataset import SensorBatches
    from iotml.models.lstm import LSTMSeq2Seq
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.train.loop import Trainer

    n_windows = 10_000
    take = n_windows // BATCH_SIZE
    broker = _fill_broker(Broker(), n_windows + BATCH_SIZE + LOOK_BACK)
    model = LSTMSeq2Seq(features=18, look_back=LOOK_BACK)

    def run_job():
        consumer = StreamConsumer(broker, ["SENSOR_DATA_S_AVRO:0:0"],
                                  group="cardata-lstm")
        batches = SensorBatches(consumer, batch_size=BATCH_SIZE,
                                window=LOOK_BACK, take=take)
        trainer = Trainer(model, supervised=True)
        t0 = time.perf_counter()
        history = trainer.fit_compiled(batches, epochs=NB_EPOCH)
        return time.perf_counter() - t0, history

    cold_wall, history = run_job()
    walls = []
    for _ in range(PASSES):
        wall, h = run_job()
        walls.append(wall)
    p50, p95 = _percentiles(walls)
    records = history["records"][-1]
    return dict(value=records / p50, cold_wall_s=round(cold_wall, 2),
                p50_s=round(p50, 3), p95_s=round(p95, 3),
                n_passes=len(walls), windows_per_job=records,
                epochs=NB_EPOCH, batch_size=BATCH_SIZE,
                look_back=LOOK_BACK,
                reference_config="1000 steps @ batch 1, 5 epochs "
                                 "(cardata-v1.py:165-200)",
                final_loss=round(float(history["loss"][-1]), 6))


def bench_mnist_smoke():
    """The MNIST-over-Kafka smoke config (confluent-tensorflow-io-kafka
    .py:44-58): images/labels produced to paired topics, zip-consumed,
    classifier trained — plus the no-Kafka control model on identical
    data.  The captured value is the streamed path's end-to-end rate
    (produce → consume → decode → scanned fit); `ingestion_intact` pins
    that the streamed tensors are byte-identical to the in-memory ones."""
    from iotml.cli.mnist_smoke import classifier_fit
    from iotml.data.mnist_stream import MnistBatches, produce_mnist, \
        synth_mnist
    from iotml.models.mnist import MNISTBaseline, MNISTClassifier
    from iotml.stream.broker import Broker

    import numpy as _np

    n, epochs, batch_size = 10_000, 2, 32
    images, labels = synth_mnist(n)

    def run_job():
        broker = Broker()
        t0 = time.perf_counter()
        produced = produce_mnist(broker, images, labels)
        batches = list(MnistBatches(broker, batch_size=batch_size))
        sx = _np.concatenate([b.x[: b.n_valid] for b in batches])
        sy = _np.concatenate([b.y[: b.n_valid] for b in batches])
        streamed = classifier_fit(MNISTClassifier(), sx, sy,
                                  batch_size, epochs)
        wall = time.perf_counter() - t0
        intact = bool(len(sx) == produced
                      and _np.array_equal(sx, images.astype(_np.float32))
                      and _np.array_equal(sy, labels))
        return wall, streamed, intact

    cold_wall, streamed, intact = run_job()
    control = classifier_fit(MNISTBaseline(), images.astype(_np.float32),
                             labels, batch_size, epochs)
    walls = []
    for _ in range(max(3, PASSES // 2)):
        wall, streamed, ok = run_job()
        intact = intact and ok
        walls.append(wall)
    p50, p95 = _percentiles(walls)
    return dict(value=n / p50, cold_wall_s=round(cold_wall, 2),
                p50_s=round(p50, 3), p95_s=round(p95, 3),
                n_passes=len(walls), n_images=n, epochs=epochs,
                batch_size=batch_size, ingestion_intact=intact,
                final_loss=round(float(streamed["loss"][-1]), 6),
                final_accuracy=round(float(streamed["accuracy"][-1]), 4),
                control_final_loss=round(float(control["loss"][-1]), 6),
                reference_config="mnist images+labels over paired Kafka "
                                 "topics (confluent-tensorflow-io-kafka"
                                 ".py:44-58)")


# ------------------------------------------------------------- longctx
def bench_long_context():
    """Flash attention at 65,536 tokens, forward+backward — the long-
    context claim (PARITY) as a recorded number instead of prose, with a
    defensible efficiency figure alongside.  On CPU (no TPU attached) the
    shape drops to something the reference kernel in interpret mode can
    stomach, and the line says so.

    On-device time is separated from the tunnel wall with the K-step
    trick: a jitted fori_loop of K data-dependent steps costs
    (dispatch + K·step), so per-step = (wall(K) − wall(1)) / (K − 1) —
    no profiler plumbing, immune to the tunnel's per-dispatch latency.
    MFU uses the conventional algorithmic count (7 causal matmuls:
    2 fwd + 5 bwd = 7·T²·D·B·H FLOPs) over the v5e bf16 peak."""
    import jax
    import jax.numpy as jnp

    from iotml.ops.attention import flash_attention

    on_tpu = jax.default_backend() not in ("cpu",)
    T = 65_536 if on_tpu else 2_048
    # head_dim 128 is the MXU-native head shape (the systolic array is
    # 128 wide: a D=64 head half-fills the QK contraction and the PV
    # output dims and CAPS the kernel near 25% MFU — measured, see the
    # ARCHITECTURE.md roofline; D=128 at the same total width nearly
    # doubles it).  Modern long-context stacks standardize on 128.
    B, H, D = 1, 2, 128
    interpret = not on_tpu
    # 1024² blocks: the measured sweet spot on v5e (the 128² default is
    # grid-overhead-bound at this T — ~8× slower)
    bq = bk = 1024 if on_tpu else 256
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, T, H, D),
                                 jnp.bfloat16) for i in range(3))

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=bq, block_k=bk,
                                       interpret=interpret).astype(
                                           jnp.float32))

    # all three grads, reduced into the timed output: with dq only, XLA
    # could dead-code-eliminate the dk/dv halves of the backward and the
    # "fwd+bwd" number would overstate the kernel
    grad = jax.value_and_grad(loss, argnums=(0, 1, 2))

    def make_multi(n):
        @jax.jit
        def f(q, k, v):
            def body(_, acc):
                # data dependency on acc so XLA cannot hoist or CSE the
                # step out of the loop (grads are consumed, not DCE'd)
                l, (dq, dk, dv) = grad(q + acc.astype(jnp.bfloat16) * 0,
                                       k, v)
                return (acc + l + jnp.sum(dq.astype(jnp.float32))
                        + jnp.sum(dk.astype(jnp.float32))
                        + jnp.sum(dv.astype(jnp.float32)))
            return jax.lax.fori_loop(0, n, body, jnp.float32(0))
        return f

    step1, step9 = make_multi(1), make_multi(9)

    def timed(f):
        # a host read of the reduced scalar is the sync point: over the
        # experimental TPU tunnel, block_until_ready alone has been seen
        # returning before the step finished
        t0 = time.perf_counter()
        float(f(q, k, v))
        return time.perf_counter() - t0

    cold = timed(step1)
    n_passes = max(3, PASSES // 2)
    walls = [timed(step1) for _ in range(n_passes)]
    p50, p95 = _percentiles(walls)
    out = dict(value=T / p50, tokens=T, cold_wall_s=round(cold, 2),
               p50_s=round(p50, 4), p95_s=round(p95, 4),
               n_passes=n_passes, backend=jax.default_backend())
    if on_tpu:
        # K=9 loop: the K-step subtraction divides dispatch jitter by
        # K-1, and an 8× on-device term dwarfs a ±0.1 s dispatch swing
        # (a K=5 run once measured an impossible 99% "MFU" when w1's min
        # caught a slow dispatch and wK's min a fast one)
        timed(step9)  # compile
        w9 = min(timed(step9) for _ in range(4))
        w1 = min(walls)
        on_device = (w9 - w1) / 8
        if on_device > 0.001:  # degenerate (tunnel jitter): omit, don't lie
            flops = 7.0 * T * T * D * B * H  # 2 fwd + 5 bwd causal matmuls
            kind = jax.devices()[0].device_kind
            # bf16 peaks per chip; unknown generations report achieved
            # FLOP/s but no MFU claim
            peaks = {"TPU v5 lite": 197e12, "TPU v5e": 197e12,
                     "TPU v5": 459e12, "TPU v5p": 459e12,
                     "TPU v4": 275e12, "TPU v6 lite": 918e12,
                     "TPU v6e": 918e12}
            peak = next((p for k, p in peaks.items()
                         if kind.startswith(k)), None)
            mfu = (100.0 * flops / on_device / peak) if peak else None
            if mfu is not None and mfu > 80.0:
                # physically impossible for this kernel (VPU overlap
                # alone bounds it well under 80%): dispatch jitter
                # swamped the subtraction — say so instead of lying
                out["mfu_suspect"] = round(mfu, 1)
            else:
                out.update(on_device_step_s=round(on_device, 4),
                           achieved_tflops=round(
                               flops / on_device / 1e12, 1),
                           device_kind=kind)
                if mfu is not None:
                    out["mfu_pct"] = round(mfu, 1)
    return out


# --------------------------------------------------------------- fleet
def _fleet_worker(port, conn_ids, payload, stop, counts, idx, barrier,
                  errors):
    """One worker thread owning a slice of the fleet's sockets: connect
    them all, then round-robin qos-0 publishes until stop.

    Failure containment: any connect/CONNACK failure aborts the shared
    barrier so the main thread fails fast (BrokenBarrierError) instead of
    blocking forever on a worker that died pre-barrier."""
    from iotml.mqtt.wire import CONNACK, connect_packet, publish_packet

    socks = []
    try:
        for cid in conn_ids:
            s = socket.create_connection(("127.0.0.1", port), timeout=30)
            s.sendall(connect_packet(cid))
            buf = b""
            while len(buf) < 4:
                chunk = s.recv(4 - len(buf))
                if not chunk:
                    raise ConnectionError(f"EOF before CONNACK for {cid}")
                buf += chunk
            if buf[0] >> 4 != CONNACK:
                raise ConnectionError(f"expected CONNACK, got {buf[0] >> 4}")
            socks.append((s, publish_packet(
                f"vehicles/sensor/data/{cid}", payload, qos=0)))
    except Exception:
        barrier.abort()
        raise
    barrier.wait(timeout=120)
    # burst of frames per syscall: the benched quantity is SERVER capacity,
    # and on a box co-hosting load generators and server (the reference ran
    # its simulator fleet on separate nodes), per-frame sendall costs would
    # measure the publisher's Python loop instead
    burst = 8
    socks = [(s, pkt * burst) for s, pkt in socks]
    sent = 0
    try:
        while not stop.is_set():
            for s, pkt in socks:
                s.sendall(pkt)
                sent += burst
            counts[idx] = sent
    except OSError as e:
        # a worker dying mid-frame leaves a truncated stream + an
        # undercounted `sent` — surface it instead of silently skewing
        # delivered_pct
        errors.append(f"worker {idx}: {e!r}")
    counts[idx] = sent
    for s, _ in socks:
        try:
            s.close()
        except OSError:
            pass


def _car_payload() -> bytes:
    """A real car record as the fleet's message payload (JSON over MQTT →
    bridge → sensor-data, the platform fleet's shape, cli/up.py)."""
    from iotml.core.schema import KSQL_CAR_SCHEMA
    from iotml.gen.simulator import FleetGenerator, FleetScenario

    gen = FleetGenerator(FleetScenario(num_cars=1))
    return json.dumps(
        gen.row_record(gen.step_columns(), 0, KSQL_CAR_SCHEMA)).encode()


def _drive_fleet(port, n_conns, duration, payload, forwarded_fn, conns_fn,
                 stream, partitions=10):
    """Shared fleet driver: N raw sockets publish qos-0 for `duration`
    seconds against whatever MQTT front listens on `port`; counts only
    messages that reached the stream broker."""
    n_workers = min(16, max(2, 2 * (os.cpu_count() or 4)))
    ids = [f"electric-vehicle-{i:05d}" for i in range(n_conns)]
    slices = [ids[w::n_workers] for w in range(n_workers)]
    stop = threading.Event()
    counts = [0] * n_workers
    errors: list = []
    barrier = threading.Barrier(n_workers + 1)
    threads = [threading.Thread(
        target=_fleet_worker,
        args=(port, slices[w], payload, stop, counts, w, barrier, errors),
        daemon=True) for w in range(n_workers)]

    # ru_maxrss is a LIFETIME high-water mark — after the compute benches
    # it is already at peak and the delta would read ~0.  Sample current
    # VmRSS during THIS window instead.
    def _vm_rss_kb() -> int:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS"):
                        return int(line.split()[1])
        except OSError:
            pass
        return 0

    rss0 = _vm_rss_kb()
    rss_peak = [rss0]
    rss_stop = threading.Event()

    def _rss_sampler():
        while not rss_stop.is_set():
            rss_peak[0] = max(rss_peak[0], _vm_rss_kb())
            time.sleep(0.1)

    rss_thread = threading.Thread(target=_rss_sampler, daemon=True)
    rss_thread.start()
    t_setup = time.perf_counter()
    for t in threads:
        t.start()
    barrier.wait(timeout=180)   # all sockets connected (or fail fast)
    setup_s = time.perf_counter() - t_setup
    live_conns = conns_fn()
    t0 = time.perf_counter()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    # a worker that failed to join is still publishing: its count would be
    # snapshotted below while forwarded keeps growing, corrupting
    # delivered_pct — record stragglers so the line is self-describing
    stragglers = sum(1 for t in threads if t.is_alive())
    if stragglers:
        errors.append(f"{stragglers} worker(s) failed to join in 30s")
    elapsed = time.perf_counter() - t0
    # drain: the front keeps parsing the kernel-buffered backlog after the
    # publishers stop; the drain time COUNTS toward the rate (forwarded
    # messages divided by publish window alone would overstate throughput)
    t_drain = time.perf_counter()
    deadline = time.time() + 120
    sent = sum(counts)
    last, last_t = -1, time.time()
    while forwarded_fn() < sent and time.time() < deadline:
        f = forwarded_fn()
        if f != last:
            last, last_t = f, time.time()
        elif time.time() - last_t > 5:
            break  # no forward progress: stragglers are not coming
        time.sleep(0.05)
    drain_s = time.perf_counter() - t_drain
    forwarded = forwarded_fn()
    rss_stop.set()
    rss_thread.join(timeout=2)
    rss1 = rss_peak[0]
    in_stream = sum(stream.end_offset("sensor-data", p)
                    for p in range(partitions))
    out = dict(value=forwarded / (elapsed + drain_s), n_conns=live_conns,
               duration_s=round(elapsed, 2), setup_s=round(setup_s, 2),
               drain_s=round(drain_s, 2),
               sent=sent, forwarded=forwarded, in_stream_topic=in_stream,
               delivered_pct=round(100.0 * forwarded / max(sent, 1), 2),
               broker_rss_delta_mb=round((rss1 - rss0) / 1024.0, 1))
    if errors:
        out["worker_errors"] = errors[:4]
    return out


FLEET_PARTITIONS = 10  # the reference provisions sensor-data with 10


def _fleet_stream():
    """Stream broker with the reference's retention bound: sensor-data is
    capped the way retention.ms=100000 caps it (~100 s of the 10k msgs/s
    fleet), keeping broker memory bounded under the firehose."""
    from iotml.stream.broker import Broker

    stream = Broker()
    stream.create_topic("sensor-data", partitions=FLEET_PARTITIONS,
                        retention_messages=10_000)  # × partitions ≈ 100k
    return stream


def bench_fleet_ingest():
    """The 100k-car scenario shape at reduced scale: N real TCP
    connections (default 9,000 — both socket ends share one process's fd
    limit) publishing car-record qos-0 payloads into the epoll MQTT
    listener, bridged to the Kafka topic — counting only messages that
    arrived in the stream broker (L1→L2→L3 complete)."""
    from iotml.mqtt.bridge import KafkaBridge
    from iotml.mqtt.broker import MqttBroker
    from iotml.mqtt.eventserver import MqttEventServer

    n_conns = int(os.environ.get("IOTML_BENCH_FLEET_CONNS", "9000"))
    duration = float(os.environ.get("IOTML_BENCH_FLEET_SECONDS", "8"))
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))

    payload = _car_payload()
    mqtt_broker = MqttBroker()
    stream = _fleet_stream()
    bridge = KafkaBridge(mqtt_broker, stream, partitions=FLEET_PARTITIONS)
    with MqttEventServer(mqtt_broker) as srv:
        return _drive_fleet(srv.port, n_conns, duration, payload,
                            bridge.forwarded,
                            lambda: srv.connection_count, stream,
                            partitions=FLEET_PARTITIONS)


def bench_fleet_ingest_native():
    """Same fleet, same payloads, but through the C++ ingest engine
    (cpp/mqtt_ingest.cc): frame parsing and acking in native code, Python
    only sees bulk drains — the HiveMQ-native analogue of the ingest
    edge."""
    from iotml.mqtt.native_ingest import NativeIngestBridge

    n_conns = int(os.environ.get("IOTML_BENCH_FLEET_CONNS", "9000"))
    duration = float(os.environ.get("IOTML_BENCH_FLEET_SECONDS", "8"))
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))

    payload = _car_payload()
    stream = _fleet_stream()
    with NativeIngestBridge(stream, partitions=FLEET_PARTITIONS) as bridge:
        return _drive_fleet(bridge.port, n_conns, duration, payload,
                            bridge.forwarded,
                            lambda: bridge.ingest.connection_count, stream,
                            partitions=FLEET_PARTITIONS)


# Self-contained load-generator child: stdlib only (run with -S: no site,
# no sitecustomize, no jax — a child is sockets and bytes).  Owns its slice
# of the fleet's client sockets so the SERVER process's fd table is the
# only fd budget that binds, the way the reference's simulator nodes are
# separate from its HiveMQ nodes (scenario.xml runs the fleet elsewhere).
_FLEET_CHILD_SRC = r"""
import base64, resource, socket, struct, sys, time
port, n, prefix, duration, payload_b64 = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], float(sys.argv[4]),
    sys.argv[5])
payload = base64.b64decode(payload_b64)
soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))


def varlen(x):
    out = bytearray()
    while True:
        b = x % 128
        x //= 128
        out.append(b | 0x80 if x else b)
        if not x:
            return bytes(out)


def mstr(s):
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def connect_packet(cid):
    body = mstr("MQTT") + bytes([4, 2]) + struct.pack(">H", 60) + mstr(cid)
    return b"\x10" + varlen(len(body)) + body


def publish_packet(topic, pl):
    body = mstr(topic) + pl
    return b"\x30" + varlen(len(body)) + body


socks = []
for i in range(n):
    cid = f"{prefix}-{i:05d}"
    s = socket.create_connection(("127.0.0.1", port), timeout=120)
    s.sendall(connect_packet(cid))
    buf = b""
    while len(buf) < 4:
        chunk = s.recv(4 - len(buf))
        if not chunk:
            raise SystemExit(f"EOF before CONNACK for {cid}")
        buf += chunk
    assert buf[0] >> 4 == 2, "expected CONNACK"
    socks.append((s, publish_packet(f"vehicles/sensor/data/{cid}",
                                    payload) * 8))
sys.stdout.write("READY\n")
sys.stdout.flush()
sys.stdin.readline()  # GO
t0 = time.time()
sent = 0
try:
    while time.time() - t0 < duration:
        for s, pkt in socks:
            s.sendall(pkt)
            sent += 8
except OSError as e:
    sys.stdout.write(f"ERR {e!r}\n")
sys.stdout.write(f"SENT {sent}\n")
sys.stdout.flush()
for s, _ in socks:
    try:
        s.close()
    except OSError:
        pass
"""


def bench_fleet_ingest_multiproc():
    """Fleet scale past one process's fd table: load-generator SUBPROCESSES
    each own a slice of the client sockets (the reference runs its 100k-car
    simulator on separate nodes, scenario.xml:13-14), so only the server's
    fd budget binds.  18,000 connections into the C++ ingest engine (the
    practical ceiling under this box's 20,000-fd cap; 100k cannot be
    opened here — PARITY.md holds the measured per-connection scaling
    that grounds the extrapolation); delivered_pct counts only messages
    that reached the stream topic.

    broker_rss_delta_mb here is honest in a way the in-process bench
    cannot be: the publishers live in other processes, so the sampled RSS
    is the SERVER's alone."""
    n_conns = int(os.environ.get("IOTML_BENCH_FLEET_MP_CONNS", "18000"))
    duration = float(os.environ.get("IOTML_BENCH_FLEET_SECONDS", "8"))
    return _fleet_multiproc(n_conns, duration)


# Fresh-process host for the per-connection memory measurement: the
# in-run `rss_per_conn_kb` sampled inside the long-lived bench process is
# capture-order-dependent (an allocator warmed by earlier benches absorbs
# 18k connections into already-mapped pages and reports ~0).  This child
# owns NOTHING but the ingest engine; the parent opens staged connection
# counts against it and reads the child's own VmRSS between stages.
_CONN_MEM_CHILD = r"""
import json, sys


def rss_kb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1])
    return 0


from iotml.stream.broker import Broker
from iotml.mqtt.native_ingest import NativeIngestBridge

broker = Broker()
bridge = NativeIngestBridge(broker, partitions=10).start()
print(json.dumps({"port": bridge.port, "rss_kb": rss_kb()}), flush=True)
for line in sys.stdin:
    cmd = line.strip()
    if cmd == "RSS":
        print(json.dumps({"rss_kb": rss_kb(),
                          "conns": bridge.ingest.connection_count}),
              flush=True)
    elif cmd == "QUIT":
        break
bridge.stop()
"""


def bench_fleet_conn_memory():
    """Per-connection server memory, capture-order-independent: a FRESH
    child process hosts the C++ ingest engine, the parent connects
    staged fleet sizes (6k/12k/18k idle MQTT sessions), and the value is
    the SLOPE of the child's own RSS over the staged counts — base
    effects and allocator history cancel in the slope (VERDICT r4 weak
    #6: the in-run sample reproduced as 0.0 when earlier benches had
    warmed the allocator).  Grounds PARITY.md's 100k-connection
    extrapolation."""
    import subprocess

    import numpy as np

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    stages = [int(s) for s in os.environ.get(
        "IOTML_BENCH_CONN_MEM_STAGES", "6000,12000,18000").split(",")]
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_", "JAX_"))}
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": os.path.dirname(os.path.abspath(__file__))})
    child = subprocess.Popen([sys.executable, "-c", _CONN_MEM_CHILD],
                             stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                             env=env, text=True, bufsize=1)
    socks = []
    points = []
    try:
        hello = json.loads(child.stdout.readline())
        port = hello["port"]

        def ask_rss():
            child.stdin.write("RSS\n")
            child.stdin.flush()
            return json.loads(child.stdout.readline())

        from iotml.mqtt.wire import connect_packet

        base = ask_rss()["rss_kb"]
        for target in stages:
            while len(socks) < target:
                cid = f"mem-{len(socks):05d}"
                s = socket.create_connection(("127.0.0.1", port),
                                             timeout=30)
                s.sendall(connect_packet(cid))
                buf = b""
                while len(buf) < 4:
                    chunk = s.recv(4 - len(buf))
                    if not chunk:
                        raise ConnectionError(f"EOF before CONNACK {cid}")
                    buf += chunk
                socks.append(s)
            time.sleep(1.0)  # settle: registrations + kernel accounting
            r = ask_rss()
            points.append((r["conns"], r["rss_kb"]))
        xs = np.array([c for c, _ in points], float)
        ys = np.array([k for _, k in points], float)
        slope_kb = float(np.polyfit(xs, ys, 1)[0])
        return dict(
            value=round(slope_kb, 3),
            points=[{"conns": c, "rss_delta_mb": round((k - base) / 1024.0,
                                                       1)}
                    for c, k in points],
            method="fresh child process hosts the ingest engine; value = "
                   "d(RSS)/d(connections) fitted over staged idle fleets "
                   "(allocator history cancels in the slope)")
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        try:
            child.stdin.write("QUIT\n")
            child.stdin.flush()
            child.wait(timeout=15)
        except (OSError, subprocess.TimeoutExpired):
            child.kill()


def bench_fleet_soak():
    """Sustained-load proof: the multi-process fleet held for ≥60 s with
    the server's RSS sampled once per second.  The reference's brokers
    run for days behind overload-protection panels
    (infrastructure/hivemq/hivemq.json); an 8-second burst cannot show a
    leak — a soak with a flat post-warmup RSS slope can.  Reported:
    rss_slope_mb_per_min fitted over the post-warmup samples (first 10 s
    excluded: connection setup + buffer growth), delivered_pct, and the
    full per-second series' min/max."""
    n_conns = int(os.environ.get("IOTML_BENCH_FLEET_SOAK_CONNS", "15000"))
    duration = float(os.environ.get("IOTML_BENCH_FLEET_SOAK_SECONDS", "60"))
    out = _fleet_multiproc(n_conns, duration, rss_series=True)
    series = out.pop("rss_series_mb")
    warm = [s for t, s in series if t >= 10.0]
    if len(warm) >= 2:
        import numpy as _np

        ts = _np.array([t for t, s in series if t >= 10.0])
        ys = _np.array(warm)
        slope_per_s = float(_np.polyfit(ts, ys, 1)[0])
        out["rss_slope_mb_per_min"] = round(slope_per_s * 60.0, 3)
        out["rss_warmup_mb"] = round(series[min(len(series) - 1, 10)][1], 1)
        out["rss_final_mb"] = round(ys[-1], 1)
        out["rss_min_mb"] = round(float(ys.min()), 1)
        out["rss_max_mb"] = round(float(ys.max()), 1)
        out["n_rss_samples"] = len(series)
    return out


def _fleet_multiproc(n_conns, duration, n_children: int = 5,
                     rss_series: bool = False):
    import base64
    import subprocess

    from iotml.mqtt.native_ingest import NativeIngestBridge

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))

    payload_b64 = base64.b64encode(_car_payload()).decode()
    stream = _fleet_stream()

    def _vm_rss_kb():
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS"):
                        return int(line.split()[1])
        except OSError:
            pass
        return 0

    per = n_conns // n_children
    with NativeIngestBridge(stream, partitions=FLEET_PARTITIONS) as bridge:
        rss0 = _vm_rss_kb()
        rss_peak = [rss0]
        rss_stop = threading.Event()
        series: list = []  # (seconds since window start, rss MB)
        t_series0 = [None]

        def _rss_sampler():
            next_sample = time.perf_counter()
            while not rss_stop.is_set():
                rss = _vm_rss_kb()
                rss_peak[0] = max(rss_peak[0], rss)
                if rss_series and t_series0[0] is not None:
                    series.append(
                        (round(time.perf_counter() - t_series0[0], 1),
                         round((rss - rss0) / 1024.0, 1)))
                    next_sample += 1.0
                else:
                    next_sample += 0.1
                time.sleep(max(0.0, next_sample - time.perf_counter()))

        threading.Thread(target=_rss_sampler, daemon=True).start()
        t_setup = time.perf_counter()
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("PALLAS_AXON", "AXON_", "JAX_"))}
        children = [
            subprocess.Popen(
                [sys.executable, "-S", "-c", _FLEET_CHILD_SRC,
                 str(bridge.port), str(per), f"ev-{c}", str(duration),
                 payload_b64],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
                text=True)
            for c in range(n_children)
        ]
        try:
            for ch in children:
                line = ch.stdout.readline().strip()
                if line != "READY":
                    raise RuntimeError(f"load child failed: {line!r}")
            setup_s = time.perf_counter() - t_setup
            live_conns = bridge.ingest.connection_count
            # all sockets connected, no traffic yet: THIS delta is the
            # per-connection server memory (the firehose delta below is
            # dominated by parse/burst buffers, not connections)
            rss_connected = _vm_rss_kb()
            t0 = time.perf_counter()
            t_series0[0] = t0  # per-second RSS series starts with the load
            for ch in children:
                ch.stdin.write("GO\n")
                ch.stdin.flush()
            sent = 0
            errors = []
            for ch in children:
                for line in ch.stdout:
                    line = line.strip()
                    if line.startswith("SENT "):
                        sent += int(line.split()[1])
                        break
                    if line.startswith("ERR"):
                        errors.append(line)
                ch.wait(timeout=120)
            elapsed = time.perf_counter() - t0
            t_drain = time.perf_counter()
            deadline = time.time() + 180
            last, last_t = -1, time.time()
            while bridge.forwarded() < sent and time.time() < deadline:
                f = bridge.forwarded()
                if f != last:
                    last, last_t = f, time.time()
                elif time.time() - last_t > 10:
                    break  # no forward progress: stragglers are not coming
                time.sleep(0.05)
            drain_s = time.perf_counter() - t_drain
            forwarded = bridge.forwarded()
        finally:
            rss_stop.set()
            for ch in children:
                if ch.poll() is None:
                    ch.kill()
        in_stream = sum(stream.end_offset("sensor-data", p)
                        for p in range(FLEET_PARTITIONS))
        out = dict(value=forwarded / (elapsed + drain_s),
                   n_conns=live_conns, n_load_procs=n_children,
                   duration_s=round(elapsed, 2), setup_s=round(setup_s, 2),
                   drain_s=round(drain_s, 2), sent=sent,
                   forwarded=forwarded, in_stream_topic=in_stream,
                   delivered_pct=round(100.0 * forwarded / max(sent, 1), 2),
                   broker_rss_delta_mb=round(
                       (rss_peak[0] - rss0) / 1024.0, 1),
                   rss_connected_mb=round((rss_connected - rss0) / 1024.0,
                                          1),
                   rss_per_conn_kb=round((rss_connected - rss0)
                                         / max(live_conns, 1), 2))
        if rss_series:
            out["rss_series_mb"] = series
        if errors:
            out["worker_errors"] = errors[:4]
        return out


# Paced-publisher child for the e2e bench: owns a slice of the MQTT fleet
# in its OWN process (its own GIL — the r4 in-process publisher threads
# contended with the wire server + KSQL pump for the single core and
# depressed the measured saturation).  Speaks a line protocol: stdin takes
# "RATE <total_msgs_per_sec>" / "STOP"; stdout emits {"ready": n} once,
# then {"t": wall, "sent": cumulative} at ≥20 Hz (the main process builds
# flow-completion markers from these timestamped snapshots).
_E2E_PUB_SCRIPT = r"""
import json, pickle, socket, struct, sys, threading, time

port = int(sys.argv[1]); path = sys.argv[2]
w = int(sys.argv[3]); nw = int(sys.argv[4]); rate0 = float(sys.argv[5])
with open(path, "rb") as fh:
    tick_payloads = pickle.load(fh)   # [tick][conn] -> mqtt payload bytes
n_conns = len(tick_payloads[0])
per = n_conns // nw
burst = 4


def varlen(x):
    out = bytearray()
    while True:
        b = x % 128
        x //= 128
        out.append(b | 0x80 if x else b)
        if not x:
            return bytes(out)


def mstr(s):
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def connect_packet(cid):
    body = mstr("MQTT") + bytes([4, 2]) + struct.pack(">H", 60) + mstr(cid)
    return b"\x10" + varlen(len(body)) + body


def publish_packet(topic, pl):
    body = mstr(topic) + pl
    return b"\x30" + varlen(len(body)) + body


state = {"rate": rate0, "ver": 0, "stop": False}


def stdin_reader():
    for line in sys.stdin:
        line = line.strip()
        if line.startswith("RATE "):
            state["rate"] = float(line[5:])
            state["ver"] += 1
        elif line == "STOP":
            break
    state["stop"] = True


threading.Thread(target=stdin_reader, daemon=True).start()

conns = []
sent = grand = 0
try:
    for i in range(per):
        ci = w * per + i
        cid = f"electric-vehicle-{ci:05d}"
        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        s.sendall(connect_packet(cid))
        buf = b""
        while len(buf) < 4:
            chunk = s.recv(4 - len(buf))
            if not chunk:
                raise ConnectionError(f"EOF before CONNACK ({cid})")
            buf += chunk
        if buf[0] >> 4 != 2:
            raise ConnectionError(f"expected CONNACK, got {buf[0]}")
        pkts = [publish_packet(f"vehicles/sensor/data/{cid}",
                               tick_payloads[t][ci])
                for t in range(len(tick_payloads))]
        bursts = [b"".join(pkts[(t + j) % len(pkts)] for j in range(burst))
                  for t in range(0, len(pkts), burst)]
        conns.append((s, bursts))
    print(json.dumps({"ready": per}), flush=True)

    my_ver = -1
    rate = tick = 0
    last_rep = 0.0
    t0 = time.perf_counter()
    while not state["stop"]:
        if state["ver"] != my_ver:
            # rate switch: restart the pacing clock so the new rate
            # applies immediately instead of draining the old credit
            # (grand accumulates across epochs — reports are cumulative)
            my_ver = state["ver"]
            rate = max(state["rate"], 1.0) / nw
            t0 = time.perf_counter()
            grand += sent
            sent = 0
        for s, bursts in conns:
            s.sendall(bursts[tick % len(bursts)])
            sent += burst
            now = time.time()
            if now - last_rep >= 0.04:
                last_rep = now
                print(json.dumps({"t": now, "sent": grand + sent}),
                      flush=True)
        tick += 1
        ahead = sent / rate - (time.perf_counter() - t0)
        if ahead > 0:
            time.sleep(ahead)
except OSError as e:
    print(json.dumps({"err": repr(e)}), flush=True)
finally:
    print(json.dumps({"t": time.time(), "sent": grand + sent, "final": True}),
          flush=True)
    for s, _ in conns:
        try:
            s.close()
        except OSError:
            pass
"""


def _hist_sum(hist) -> float:
    """Total observed seconds across a metrics Histogram's series."""
    try:
        return float(sum(hist._sums.values()))
    except Exception:  # noqa: BLE001 - diagnostics only
        return 0.0


def _produce_leg_breakdown(ingest, durable: bool) -> dict:
    """The write path's per-leg seconds for the e2e run: bridge (MQTT→
    stream produce), convert+frame (the pump's fused native JSON→Avro→
    frame leg), append (RAW_PRODUCE ship+land)."""
    from iotml.stream.producer import (raw_produce_append_seconds,
                                       raw_produce_convert_seconds,
                                       raw_produce_fallbacks,
                                       raw_produce_records)

    return dict(
        value=float(raw_produce_records.value()),
        platform="durable-columnar" if durable else "in-memory",
        bridge_produce_s=round(ingest.produce_seconds, 2),
        convert_frame_s=round(_hist_sum(raw_produce_convert_seconds), 2),
        raw_append_s=round(_hist_sum(raw_produce_append_seconds), 2),
        raw_produce_records=int(raw_produce_records.value()),
        raw_produce_fallbacks=int(raw_produce_fallbacks.value()),
        definition="write-path seconds per leg over the whole e2e run "
                   "(value = records shipped as pre-framed raw batches)")


def bench_e2e_platform():
    """THE reference claim, measured: every layer live at once, with the
    model loop CLOSED.  The demo the reference actually runs is fleet →
    HiveMQ → Kafka → KSQL → training AND scoring concurrently, with the
    trained model handed from the train Job to the predict pods through a
    GCS bucket (cardata-v3.py:227-232,255-261, run.sh:16-91) — not one
    leg at a time, and not a frozen model.

    Process shape matches the repo's own deploy manifests
    (deploy/model-training.yaml / model-predictions.yaml): the main
    process hosts the platform (cli/up.py: MQTT epoll front + bridge,
    Kafka wire server, four-object KSQL pipeline) and the paced MQTT
    fleet; TRAINING runs in a separate OS process on the TPU
    (`iotml.cli.live train` — persistent consumer, fixed-shape rounds,
    h5 artifact + pointer flip per round); SCORING runs in another OS
    process on CPU like the reference's predict pods
    (`iotml.cli.live score` — hot-swaps weights off the artifact pointer
    between super-batches, writes np.array2string predictions).  Every
    prediction in the measured window therefore comes from a model
    trained on the live stream seconds earlier.

    The fleet publishes VARIED labeled records (failure_rate > 0, the
    scenario generator's injected failure modes), so detection quality is
    measured live: the scorer's threshold verdicts — the same verdicts
    written to the predictions topic — are scored against the stream's
    injected labels (precision/recall at the stated threshold + a
    histogram-derived AUC).

    Latency, two ways:
    - flow-completion (as before): markers of (published_count, t) every
      250 ms resolve when the predictions topic reaches that count —
      UPPER-bounds per-record latency (includes backlog drain).
    - per-record: the bridge stamps every sensor-data record with epoch-ms
      produce time, the KSQL legs propagate timestamps, a sampler records
      (partition, offset, timestamp) of SENSOR_DATA_S_AVRO log heads, and
      the scorer's per-drain consumed-positions (from its stats stream)
      bound each sampled record's prediction-write time to one drain.

    The headline window is SELF-PACING: the rate sweep
    (IOTML_BENCH_E2E_SWEEP) runs FIRST, the measured saturation (the max
    records/s any paced point achieved — overdriven points deliver the
    platform's capacity, held points deliver their own rate) is emitted as
    `e2e_saturation_records_per_sec`, and the headline window is paced at
    ~0.8× that knee.  The driver's number of record is therefore
    steady-state by construction on any box day — a fixed 16k pace on a
    day the box saturates at 11.5k would measure backlog drain, not the
    platform (round-4 driver capture did exactly that).
    IOTML_BENCH_E2E_RATE overrides the policy with a fixed pace.

    Since ISSUE 12 the platform under test is the DURABLE COLUMNAR
    platform (IOTML_BENCH_E2E_DURABLE=0 opts back to the in-memory
    emulator): every partition is a segmented log, the bridge and the
    KSQL pump's AVRO leg produce pre-framed raw batches appended
    segment-verbatim (RAW_PRODUCE / the fused produce_many framing),
    and the train/score children consume raw frame batches over
    RAW_FETCH through the one columnar decoder — the zero-copy plane
    end to end, write AND read.  The produce-leg breakdown
    (bridge / convert+frame / append) is published beside the knee."""
    import shutil
    import subprocess
    import tempfile

    from iotml.cli.up import Platform
    from iotml.core.schema import KSQL_CAR_SCHEMA
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.serve.scorer import hist_auc

    rate_env = os.environ.get("IOTML_BENCH_E2E_RATE", "")
    window_s = float(os.environ.get("IOTML_BENCH_E2E_SECONDS", "20"))
    # the sweep starts LOW enough for a held point to anchor on a
    # 1-core box (a first point that already overdrives measures thrash
    # capacity and breaks the sweep immediately) and climbs past the
    # 2-core knee band
    sweep = [float(r) for r in os.environ.get(
        "IOTML_BENCH_E2E_SWEEP",
        "8000,12000,16000,20000,24000").split(",") if r]
    sweep_window_s = float(os.environ.get("IOTML_BENCH_E2E_SWEEP_SECONDS",
                                          "8"))
    n_conns = 200
    failure_rate = 0.03
    # operating point from the offline threshold protocol
    # (evaluate/anomaly.py over a trained model's normal-error
    # distribution): ≈ p99 of normal reconstruction error.  The notebook's
    # "threshold 5" is the creditcard protocol on unscaled data; the car
    # stream is normalized — under the full-normalization model with the
    # parity-subset verdict mean (serve/scorer.py verdict_mask), normal
    # p99 measures ≈ 0.50.
    threshold = float(os.environ.get("IOTML_BENCH_E2E_THRESHOLD", "0.5"))

    durable = os.environ.get("IOTML_BENCH_E2E_DURABLE", "1").strip() \
        not in ("0", "false", "no", "off")
    store_dir = None
    store_policy = None
    if durable:
        from iotml.store import StorePolicy

        store_dir = tempfile.mkdtemp(prefix="iotml_e2e_store_")
        # fsync=never: the bench measures the pipeline, not the disk's
        # flush latency (crash durability is the store suite's job)
        store_policy = StorePolicy(fsync="never")
    platform = Platform(retention_messages=30_000, store_dir=store_dir,
                        store_policy=store_policy).start()
    # derived KSQL topics are created by the engine (partitions inherited
    # from sensor-data) with no retention bound; pre-create them bounded so
    # a ~90 s run cannot grow the log without limit.  The AVRO leg gets a
    # deeper log: both children cursor it, and the top sweep points
    # deliberately OVERDRIVE the platform (that is how the saturation
    # knee is measured) — an 8 s window + marker tail at 24k over a ~12k
    # capacity builds a six-figure record backlog that must never trim
    # offsets out from under the children's cursors.
    for t, keep in (("SENSOR_DATA_S", 60_000),
                    ("SENSOR_DATA_S_AVRO", 200_000),
                    ("SENSOR_DATA_S_AVRO_REKEY", 30_000)):
        platform.broker.create_topic(t, partitions=10,
                                     retention_messages=keep)
    # the fleet rides the C++ ingest edge (the scale path the fleet
    # benches establish): on a one-core box the Python epoll front would
    # spend ~20% of the core parsing 12k msgs/s that the native engine
    # parses for ~5%, starving the KSQL/train/serve stages
    from iotml.mqtt.native_ingest import NativeIngestBridge

    ingest = NativeIngestBridge(platform.broker,
                                partitions=10).start()
    stop = threading.Event()
    err: list = []

    pump_busy = [0.0, 0.0]  # [busy seconds, records]

    def ksql_pump():
        while not stop.is_set():
            try:
                t0 = time.perf_counter()
                n = platform.sql.pump()
                pump_busy[0] += time.perf_counter() - t0
                pump_busy[1] += n
                if n == 0:
                    time.sleep(0.02)
            except Exception as e:  # noqa: BLE001 - surfaced at the end
                err.append(f"ksql: {e!r}")
                return

    # ---- paced MQTT publishers: VARIED labeled payloads (pre-serialized
    # ticks of a failing-car fleet), rate switchable mid-run for the sweep
    gen = FleetGenerator(FleetScenario(num_cars=n_conns,
                                       failure_rate=failure_rate, seed=11))
    n_failing = int((gen.failing >= 0).sum())
    failing_keys = {f"vehicles/sensor/data/electric-vehicle-{i:05d}"
                    for i, m in enumerate(gen.failing) if m >= 0}
    strong_keys = {f"vehicles/sensor/data/electric-vehicle-{i:05d}"
                   for i, m in enumerate(gen.failing) if m == 1}
    tick_payloads = []  # [tick][conn] -> json bytes
    for _ in range(24):
        cols = gen.step_columns()
        tick_payloads.append([json.dumps(
            gen.row_record(cols, i, KSQL_CAR_SCHEMA)).encode()
            for i in range(n_conns)])
    # warmup runs at a LOW rate: the scorer idles until the trainer's
    # first artifact exists (TPU compile ~30-60 s over the tunnel), and a
    # full-rate fleet during that wait would build a backlog the
    # flow-completion markers could never resolve against.  The ramp to
    # the measured rate happens once the loop is closed and caught up.
    warmup_rate = float(os.environ.get("IOTML_BENCH_E2E_WARMUP_RATE",
                                       "3000"))
    # ---- paced publishers live in CHILD PROCESSES (their own GILs): the
    # round-4 in-process publisher threads contended with the wire server
    # + KSQL pump for the single core and depressed measured saturation.
    # Children take "RATE <total>"/"STOP" on stdin and report cumulative
    # {"t", "sent"} snapshots on stdout at ≥20 Hz (see _E2E_PUB_SCRIPT).
    n_pub_procs = int(os.environ.get("IOTML_BENCH_E2E_PUB_PROCS", "2"))
    pub_children: list = []
    pub_reports: dict = {}   # worker → (wall_t, cumulative_sent)
    pub_ready: list = []

    def set_rate(r: float) -> None:
        for ch in pub_children:
            try:
                ch.stdin.write(f"RATE {r}\n")
                ch.stdin.flush()
            except OSError:
                pass

    def sent_snapshot():
        """(count, t): fleet-cumulative publishes at a conservative wall
        time (min of the per-child report times: counts can only postdate
        it, so a flow-completion marker built from this snapshot measures
        an UPPER bound — the same direction the marker method already
        documents)."""
        if not pub_reports:
            return 0, time.time()
        vals = list(pub_reports.values())
        return (sum(s for _, s in vals), min(t for t, _ in vals))

    def pub_reader(w, proc):
        try:
            for line in proc.stdout:
                if not line.startswith("{"):
                    continue
                d = json.loads(line)
                if "err" in d:
                    err.append(f"publisher {w}: {d['err']}")
                elif "ready" in d:
                    pub_ready.append(w)
                elif d.get("sent") is not None:
                    pub_reports[w] = (d["t"], d["sent"])
        except Exception as e:  # noqa: BLE001
            err.append(f"pub reader {w}: {e!r}")

    # ---- per-record timestamp sampler: (partition, offset) → bridge
    # publish time, read off the AVRO topic's log heads (timestamps
    # propagate through the KSQL legs from the bridge's produce stamp)
    ts_samples: dict = {}

    def ts_sampler():
        while not stop.is_set():
            try:
                spec = platform.broker.topic("SENSOR_DATA_S_AVRO")
                break
            except KeyError:
                time.sleep(0.1)
        while not stop.is_set():
            for p in range(spec.partitions):
                off = platform.broker.end_offset("SENSOR_DATA_S_AVRO", p) - 1
                if off >= 0 and (p, off) not in ts_samples:
                    msgs = platform.broker.fetch("SENSOR_DATA_S_AVRO", p,
                                                 off, 1)
                    if msgs:
                        ts_samples[(p, off)] = msgs[0].timestamp_ms
            time.sleep(0.15)

    # ---- children: the deploy manifests' pod separation as real processes
    artifact_root = tempfile.mkdtemp(prefix="iotml_e2e_artifacts_")
    repo = os.path.dirname(os.path.abspath(__file__))
    addr = f"127.0.0.1:{platform.kafka.port}"
    train_env = dict(os.environ)  # keeps the TPU tunnel: training on chip
    # APPEND to PYTHONPATH: the tunnel's sitecustomize lives on it, and
    # replacing it would strand the child with JAX_PLATFORMS=axon but no
    # axon backend registered
    train_env["PYTHONPATH"] = repo + os.pathsep + \
        train_env.get("PYTHONPATH", "")
    score_env = {k: v for k, v in os.environ.items()
                 if not k.startswith(("PALLAS_AXON", "AXON_", "JAX_"))}
    score_env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo})

    train_rounds: list = []   # cumulative stats dicts from the train child
    drain_stats: list = []    # cumulative stats dicts from the score child

    def reader(proc, sink, tag):
        try:
            for line in proc.stdout:
                if line.startswith("{"):
                    sink.append(json.loads(line))
        except Exception as e:  # noqa: BLE001
            err.append(f"{tag} reader: {e!r}")

    def cum_at(entries, wall, key, default=0):
        """Last cumulative value at/before `wall` from a stats stream."""
        val = default
        for d in entries:
            if d["t"] <= wall:
                val = d[key]
            else:
                break
        return val

    def predictions_total():
        spec = platform.broker.topic("model-predictions")
        return sum(platform.broker.end_offset("model-predictions", p)
                   for p in range(spec.partitions))

    def measure_window(win_s):
        """One paced window: markers + deltas off the children's
        cumulative stats streams.  Markers are the publisher children's
        own timestamped (count, t) snapshots, so publisher staleness can
        only overstate the measured latency (see sent_snapshot).  Returns
        the raw point dict."""
        wall0 = time.time()
        t0 = time.perf_counter()
        sent0, _ = sent_snapshot()
        preds0 = predictions_total()
        lat: list = []
        pending: list = []
        next_marker = t0
        while time.perf_counter() - t0 < win_s:
            now = time.perf_counter()
            if now >= next_marker:
                pending.append(sent_snapshot())
                next_marker = now + 0.25
            done = predictions_total()
            wall = time.time()
            while pending and done >= pending[0][0]:
                lat.append(wall - pending[0][1])
                pending.pop(0)
            if err:
                raise RuntimeError(err[0])
            for child, tag in ((train_child, "train"),
                               (score_child, "score")):
                if child is not None and child.poll() is not None:
                    raise RuntimeError(
                        f"{tag} child exited rc={child.returncode} "
                        f"mid-window; stderr tail: {child_err_tail(child)}")
            for w, ch in enumerate(pub_children):
                if ch.poll() is not None:
                    raise RuntimeError(
                        f"publisher child {w} exited rc={ch.returncode} "
                        "mid-window")
            time.sleep(0.02)
        t_win = time.perf_counter() - t0
        wall1 = time.time()
        sent_win = sent_snapshot()[0] - sent0
        preds_win = predictions_total() - preds0
        # measurement over: drop the fleet to the warmup rate IMMEDIATELY
        # so an overdriven point's marker tail resolves against a
        # draining backlog instead of growing one for up to 30 more
        # seconds (the round-5 self-pacing run's headline inherited ~50k
        # standing records exactly this way)
        set_rate(warmup_rate)
        tail_deadline = time.time() + 30
        while pending and time.time() < tail_deadline:
            done = predictions_total()
            wall = time.time()
            while pending and done >= pending[0][0]:
                lat.append(wall - pending[0][1])
                pending.pop(0)
            time.sleep(0.02)
        lat_ms = sorted(x * 1000.0 for x in lat)
        p50, p95 = _percentiles(lat_ms) if lat_ms else (None, None)
        return dict(wall0=wall0, wall1=wall1, t_win=t_win,
                    sent_win=sent_win, preds_win=preds_win,
                    lat_p50=p50, lat_p95=p95, n_markers=len(lat_ms),
                    unresolved=len(pending))

    def window_deltas(w):
        """Train/quality deltas for a measured window, off the children's
        cumulative stats (entries are stamped with the child's wall
        clock; same box, same epoch)."""
        trained = sum(r["records"] for r in train_rounds
                      if w["wall0"] <= r["t"] <= w["wall1"])
        rounds = sum(1 for r in train_rounds
                     if w["wall0"] <= r["t"] <= w["wall1"])
        q0 = cum_at(drain_stats, w["wall0"], "quality", None)
        q1 = cum_at(drain_stats, w["wall1"], "quality", None)
        mu0 = cum_at(drain_stats, w["wall0"], "model_updates")
        mu1 = cum_at(drain_stats, w["wall1"], "model_updates")
        s0 = cum_at(drain_stats, w["wall0"], "scored")
        s1 = cum_at(drain_stats, w["wall1"], "scored")
        out = dict(records_trained=trained, train_rounds=rounds,
                   model_updates=mu1 - mu0, scored=s1 - s0)
        if q0 is not None and q1 is not None:
            q = {k: q1[k] - q0[k] for k in q1}
            out["quality"] = q
        h0 = cum_at(drain_stats, w["wall0"], "err_hist", None)
        h1 = cum_at(drain_stats, w["wall1"], "err_hist", None)
        if h0 is not None and h1 is not None:
            import numpy as _np

            anom = _np.array(h1["true"]) - _np.array(h0["true"])
            norm = _np.array(h1["false"]) - _np.array(h0["false"])
            auc = hist_auc(anom, norm)
            if auc is not None:
                out["auc"] = round(auc, 4)
        return out

    def per_record_latency(w):
        """Sampled (partition, offset, publish-ts) joined against the
        scorer's per-drain consumed positions: the first stats line whose
        positions cover a sampled record UPPER-bounds its prediction-write
        time (stats are emitted after the covering drain's flush, at a
        ≤10 Hz throttle — so the bound is one drain plus up to ~100 ms of
        stats cadence, still far tighter than flow completion)."""
        out = []
        for (p, off), ts in sorted(ts_samples.items()):
            t_pub = ts / 1000.0
            if not (w["wall0"] <= t_pub <= w["wall1"]):
                continue
            for d in drain_stats:
                # truncated-drain snapshots report positions ahead of the
                # flushed predictions: only complete drains upper-bound
                # the write time
                if not d.get("drain_complete", True):
                    continue
                pos = d.get("positions", {}).get(str(p))
                if pos is not None and pos > off:
                    out.append((d["t"] - t_pub) * 1000.0)
                    break
        return sorted(out)

    threads = [threading.Thread(target=ksql_pump, daemon=True),
               threading.Thread(target=ts_sampler, daemon=True)]
    train_child = score_child = None
    stderr_files = []
    payload_file = None
    try:
        stderr_of: dict = {}

        def spawn(cmd, env):
            f = tempfile.NamedTemporaryFile(mode="w+", prefix="iotml_e2e_",
                                            suffix=".err", delete=False)
            stderr_files.append(f)
            proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                    stdout=subprocess.PIPE, stderr=f,
                                    env=env, cwd=repo, text=True, bufsize=1)
            stderr_of[proc] = f.name
            return proc

        # ---- publisher children: ship the varied tick payloads via a
        # temp pickle, then spawn each worker with its slice parameters
        import pickle

        pf = tempfile.NamedTemporaryFile(prefix="iotml_e2e_payloads_",
                                         suffix=".pkl", delete=False)
        payload_file = pf.name
        pickle.dump(tick_payloads, pf)
        pf.close()
        pub_env = {k: v for k, v in os.environ.items()
                   if not k.startswith(("PALLAS_AXON", "AXON_", "JAX_"))}
        for w in range(n_pub_procs):
            ch = spawn([sys.executable, "-c", _E2E_PUB_SCRIPT,
                        str(ingest.port), payload_file, str(w),
                        str(n_pub_procs), str(warmup_rate)], pub_env)
            pub_children.append(ch)
            threads.append(threading.Thread(target=pub_reader, args=(w, ch),
                                            daemon=True))

        def child_err_tail(child) -> str:
            """Last ~2 KB of a child's captured stderr, for error text."""
            path = stderr_of.get(child)
            if path is None:
                return ""
            try:
                with open(path) as fh:
                    fh.seek(max(0, os.path.getsize(path) - 2048))
                    return fh.read().strip()[-2000:]
            except OSError:
                return ""

        # 200-batch rounds (20,000 records): the round cadence must keep
        # up with arrival, and per-round overhead (wire trips, h5 publish)
        # amortizes over the slice while the artifact pointer still flips
        # ~1/s — fresh weights reach the scorer many times per window
        train_child = spawn(
            [sys.executable, "-m", "iotml.cli.live", "train", addr,
             "SENSOR_DATA_S_AVRO", artifact_root, "--take-batches", "200",
             "--group", "cardata-autoencoder-e2e", "--stats",
             # FULL normalization (all 18 fields live): battery faults
             # are invisible under the reference's parity normalization
             # (its TODO fields zero the whole signature) — the live
             # detection path is detection-grade by default.  Train and
             # score must match.
             "--normalize", "full",
             "--max-seconds", "900"], train_env)
        score_child = spawn(
            [sys.executable, "-m", "iotml.cli.live", "score", addr,
             "SENSOR_DATA_S_AVRO", "model-predictions", artifact_root,
             "--threshold", str(threshold), "--group", "scorer-e2e",
             "--normalize", "full",
             # live-trained full-norm models carry a higher mean-error
             # noise floor than the offline envelope (~0.42 offline,
             # 1 epoch/round continuous): the mean-path alert bar sits
             # above the live healthy band; per-car detection rides the
             # feature heads (error z + value drift, serve/carhealth.py)
             "--car-threshold", "0.6", "--car-feature-heads",
             "--stats", "--max-seconds", "900",
             # the first artifact waits on the train child's TPU compile
             # (~30-60 s over the tunnel) + the first round's data: match
             # the bench's own 300 s warmup budget, not the CLI default
             "--wait-model-seconds", "280"], score_env)
        threads += [
            threading.Thread(target=reader, args=(train_child, train_rounds,
                                                  "train"), daemon=True),
            threading.Thread(target=reader, args=(score_child, drain_stats,
                                                  "score"), daemon=True)]
        for t in threads:
            t.start()

        # ---- warmup: the loop must be CLOSED before measuring (at least
        # one trained model published, downloaded, and the scorer caught
        # up to the live stream with it)
        warm_deadline = time.time() + 300
        while time.time() < warm_deadline:
            if err:
                raise RuntimeError(err[0])
            for child, tag in ((train_child, "train"),
                               (score_child, "score")):
                if child.poll() is not None:
                    raise RuntimeError(
                        f"{tag} child exited rc={child.returncode} during "
                        f"warmup; stderr tail: {child_err_tail(child)}")
            for w, ch in enumerate(pub_children):
                if ch.poll() is not None:
                    raise RuntimeError(
                        f"publisher child {w} exited rc={ch.returncode} "
                        f"during warmup; stderr tail: {child_err_tail(ch)}")
            # lag below a few seconds' worth of the warmup rate = the
            # scorer has caught the backlog and only the pipeline's
            # steady in-flight remains (KSQL pump cycles + drain cadence)
            lag = sent_snapshot()[0] - predictions_total()
            if train_rounds and drain_stats and \
                    len(pub_ready) == n_pub_procs and \
                    drain_stats[-1]["scored"] >= 2_000 and \
                    lag < max(10_000, 4 * warmup_rate):
                break
            time.sleep(0.1)
        else:
            raise RuntimeError(
                f"e2e warmup: loop not closed (train_rounds="
                f"{len(train_rounds)}, drains={len(drain_stats)}, "
                f"pub_ready={len(pub_ready)}/{n_pub_procs}, "
                f"lag={sent_snapshot()[0] - predictions_total()})")

        def trainer_lag() -> int:
            """Records between the train child's committed cursor and the
            log end (its per-round commits land in the broker's group
            table).  An overdriven point leaves the TRAINER lagging too —
            a headline window starting while it races to catch up would
            measure scorer-vs-trainer CPU contention, not steady state."""
            lag = 0
            try:
                spec = platform.broker.topic("SENSOR_DATA_S_AVRO")
            except KeyError:
                return 0
            for p in range(spec.partitions):
                end = platform.broker.end_offset("SENSOR_DATA_S_AVRO", p)
                off = platform.broker.committed(
                    "cardata-autoencoder-e2e", "SENSOR_DATA_S_AVRO", p)
                lag += end - (off or 0)
            return lag

        def drain_backlog(deadline_s: float = 90.0,
                          lag_bar: Optional[float] = None) -> None:
            """Let the pipeline catch up at the warmup rate so the next
            paced point is an independent measurement (a point starting
            on the previous window's backlog would measure backlog
            drain, not the paced rate).  Waits on BOTH children: the
            prediction count (scorer) and the trainer's committed cursor
            (one round slice ≈ 20k sits in flight by design; 30k =
            caught up to within a round and a half)."""
            set_rate(warmup_rate)
            bar = 1.5 * warmup_rate if lag_bar is None else lag_bar
            deadline = time.time() + deadline_s
            while time.time() < deadline and \
                    (sent_snapshot()[0] - predictions_total() > bar
                     or trainer_lag() > 30_000):
                time.sleep(0.1)

        # ---- SWEEP FIRST: measure the platform's saturation knee, then
        # pace the headline window at ~0.8× it (self-pacing — the
        # headline is steady-state by construction on any box day)
        sweep_points = []
        for r in sweep:
            drain_backlog()
            set_rate(r)
            time.sleep(2.0)  # settle: markers from the old rate resolve
            wpt = measure_window(sweep_window_s)
            d = window_deltas(wpt)
            point = dict(
                rate=r,
                records_per_sec=round(wpt["preds_win"] / wpt["t_win"], 1),
                publish_rate=round(wpt["sent_win"] / wpt["t_win"], 1),
                latency_ms_p50=round(wpt["lat_p50"], 1)
                if wpt["lat_p50"] is not None else None,
                latency_ms_p95=round(wpt["lat_p95"], 1)
                if wpt["lat_p95"] is not None else None,
                unresolved_markers=wpt["unresolved"],
                train_records_per_sec=round(
                    d["records_trained"] / wpt["t_win"], 1))
            sweep_points.append(point)
            if point["records_per_sec"] < 0.9 * point["publish_rate"]:
                # past the knee: deeper overdrive only LOWERS delivered
                # throughput (measured: 16k→16.0k, 20k→11.2k, 24k→7.8k —
                # thrash), cannot raise the max, and leaves both children
                # minutes of backlog that pollutes the headline
                break
        # saturation = the highest records/s any paced point delivered:
        # held points deliver their own rate, overdriven points deliver
        # the platform's capacity — the max is the knee either way
        saturation = (max(p["records_per_sec"] for p in sweep_points)
                      if sweep_points else None)
        if rate_env:
            headline_rate = float(rate_env)
            headline_policy = "env override (IOTML_BENCH_E2E_RATE)"
        elif saturation is not None:
            headline_rate = max(warmup_rate,
                                round(0.8 * saturation, -2))
            headline_policy = "0.8x measured saturation knee"
        else:
            headline_rate = 12_000.0
            headline_policy = "fallback (no sweep points)"
        # the headline must start CLEAN: drain to within one warmup-
        # second of the log end before pacing up (the sweep's bar of 4
        # warmup-seconds tolerates steady in-flight; the headline's
        # latency figures are the round's record and a standing backlog
        # would shift every percentile)
        drain_backlog(deadline_s=120.0, lag_bar=1.5 * warmup_rate)
        set_rate(headline_rate)
        time.sleep(2.0)
        headline = measure_window(window_s)
        headline_rate_actual = headline_rate

        # ---- clean shutdown: quiesce the fleet/KSQL first (a top-sweep
        # backlog must drain, not grow, while the children wind down),
        # then stop the children so they flush their final stats lines
        stop.set()
        for ch in pub_children:
            try:
                ch.stdin.write("STOP\n")
                ch.stdin.flush()
            except OSError:
                pass
        for child in (train_child, score_child):
            try:
                child.stdin.write("STOP\n")
                child.stdin.flush()
            except OSError:
                pass
        for child, tag in ((train_child, "train"), (score_child, "score")):
            try:
                child.wait(timeout=30)
            except subprocess.TimeoutExpired:
                err.append(f"{tag} child failed to stop in 30s")
        for w, ch in enumerate(pub_children):
            try:
                ch.wait(timeout=10)
            except subprocess.TimeoutExpired:
                err.append(f"publisher child {w} failed to stop in 10s")
    finally:
        stop.set()
        try:
            for t in threads:
                if t.ident is not None:
                    t.join(timeout=15)
        finally:
            for child in (train_child, score_child, *pub_children):
                if child is not None and child.poll() is None:
                    child.kill()
            ingest.stop()
            platform.stop()  # ALWAYS: a leaked platform would outlive the
            #                  bench and mask the original error
            if store_dir is not None:
                shutil.rmtree(store_dir, ignore_errors=True)
            if payload_file is not None:
                try:
                    os.unlink(payload_file)
                except OSError:
                    pass
            for f in stderr_files:
                # diagnostics already embedded in any raised error text;
                # leaving the files behind would accumulate per run
                f.close()
                try:
                    os.unlink(f.name)
                except OSError:
                    pass
    if err:
        raise RuntimeError("; ".join(err[:3]))

    d = window_deltas(headline)
    pr = per_record_latency(headline)
    q = d.get("quality")
    out = dict(
        value=headline["preds_win"] / headline["t_win"],
        window_s=round(headline["t_win"], 2),
        publish_rate_msgs_per_sec=round(
            headline["sent_win"] / headline["t_win"], 1),
        target_rate=headline_rate_actual,
        headline_rate_policy=headline_policy,
        predictions_in_window=headline["preds_win"],
        unresolved_markers=headline["unresolved"],
        latency_ms_p50=round(headline["lat_p50"], 1)
        if headline["lat_p50"] is not None else None,
        latency_ms_p95=round(headline["lat_p95"], 1)
        if headline["lat_p95"] is not None else None,
        n_latency_markers=headline["n_markers"],
        train_rounds=d["train_rounds"],
        records_trained=d["records_trained"],
        train_records_per_sec=round(
            d["records_trained"] / headline["t_win"], 1),
        model_updates=d["model_updates"],
        n_failing_cars=n_failing,
        stages="fleet+mqtt+bridge+ksql(main) | train(tpu proc) | "
               "serve(cpu proc), model loop closed via artifact store",
        # diagnostics: the KSQL pump's share of the main process (its
        # busy seconds over the whole e2e wall — the saturation-ceiling
        # work reads this to see where the shared core goes)
        ksql_pump_busy_s=round(pump_busy[0], 1),
        ksql_pump_records=int(pump_busy[1]),
        # the produce-leg breakdown (ISSUE 12): where write-path time
        # went over the whole run — MQTT→stream bridge produce, the
        # pump's native convert+frame, and the raw append/ship leg
        _produce_legs=_produce_leg_breakdown(ingest, durable),
    )
    if pr:
        pr50, pr95 = _percentiles(pr)
        out["per_record_latency_ms_p50"] = round(pr50, 1)
        out["per_record_latency_ms_p95"] = round(pr95, 1)
        out["n_per_record_samples"] = len(pr)
    if q is not None:
        prec = q["tp"] / max(q["tp"] + q["fp"], 1)
        rec = q["tp"] / max(q["tp"] + q["fn"], 1)
        out["_quality"] = dict(
            value=d.get("auc", 0.0) or 0.0,
            threshold=threshold,
            precision=round(prec, 4), recall=round(rec, 4),
            f1=round(2 * prec * rec / max(prec + rec, 1e-9), 4),
            tp=q["tp"], fp=q["fp"], fn=q["fn"], tn=q["tn"],
            anomalies_in_window=q["tp"] + q["fn"],
            n_failing_cars=n_failing,
            definition="live per-record verdicts (written to the "
                       "predictions topic) vs injected labels; value=AUC "
                       "from live error histograms")
        # car-LEVEL detection: which injected failing cars the live
        # CarHealthDetector named (serve/carhealth.py; strong modes are
        # the documented detection envelope, precision must be 1.0)
        ch = cum_at(drain_stats, headline["wall1"], "carhealth", None)
        if ch is not None:
            alerted = set(ch.get("cars_alerted", []))
            # stdout lines stay compact (driver captures truncate long
            # tails): first 12 names + the counts tell the whole story
            out["_quality"].update(
                cars_alerted=sorted(alerted)[:12],
                n_cars_alerted=len(alerted),
                car_threshold=ch.get("threshold"),
                alert_sources={k.rsplit("-", 1)[-1]: v for k, v in
                               sorted(ch.get("alert_sources",
                                             {}).items())[:12]},
                car_true_alerts=len(alerted & failing_keys),
                car_false_alerts=len(alerted - failing_keys),
                strong_mode_cars=len(strong_keys),
                strong_mode_detected=len(alerted & strong_keys))
    if saturation is not None:
        out["_saturation"] = dict(
            value=saturation,
            points=sweep_points,
            headline_rate=headline_rate_actual,
            headline_rate_policy=headline_policy,
            definition="max records/s delivered across the paced sweep "
                       "(held points deliver their rate, overdriven "
                       "points deliver platform capacity); the headline "
                       "window paces at ~0.8x this knee")
    return out


# The one (metric, unit, baseline) table — main() prints from it and
# run_named() resolves units/baselines from it (single source of
# truth; print order here, execution order in main()).
METRIC_ORDER = [
    ("fleet_ingest_msgs_per_sec", "msgs/s", FLEET_BASELINE_MPS),
    ("fleet_ingest_native_msgs_per_sec", "msgs/s", FLEET_BASELINE_MPS),
    # 18k connections from SEPARATE load-generator processes (only the
    # server's fd table binds — the reference's simulator-on-its-own-
    # nodes shape; 18k ≈ this box's 20k-fd practical ceiling)
    ("fleet_ingest_multiproc_msgs_per_sec", "msgs/s",
     FLEET_BASELINE_MPS),
    # the same fleet held for ≥60 s with per-second server RSS: the
    # sustained-load story behind the reference's overload panels
    # (hivemq.json) as a captured slope instead of prose
    ("fleet_soak_msgs_per_sec", "msgs/s", FLEET_BASELINE_MPS),
    # per-connection server memory as a fitted slope in a fresh child
    # process (capture-order-independent; grounds the 100k-connection
    # extrapolation in PARITY.md)
    ("fleet_conn_memory_kb_per_conn", "KB/conn", None),
    ("wire_train_records_per_sec_per_chip", "records/s",
     TRAIN_BASELINE_RPS),
    # the reference's second model family: supervised LSTM windows
    # (cardata-v1.py) and the MNIST-over-Kafka smoke — no published
    # reference rates for either (vs_baseline 0), final-loss fields
    # carry the quality evidence
    ("lstm_train_windows_per_sec_per_chip", "windows/s", None),
    ("mnist_stream_images_per_sec", "images/s", None),
    # no reference twin for long context (its only sequence mechanism
    # is an LSTM at look_back=1): vs_baseline deliberately 0
    ("flash_attention_fwd_bwd_tokens_per_sec", "tokens/s", None),
    # serve compares against the same measured reference job rate —
    # its predict pod scores the identical 10k-record slice per cycle
    # (cardata-v3.py:269-274)
    ("serve_rows_per_sec", "rows/s", TRAIN_BASELINE_RPS),
    # the preprocessing stage must keep pace with fleet ingest
    ("ksql_pipeline_records_per_sec", "records/s", FLEET_BASELINE_MPS),
    # durable-store costs (iotml.store): append/replay MB/s + crash-
    # recovery wall time; no reference twin (its retention lived in
    # managed Kafka), so vs_baseline deliberately 0
    ("store_append_mb_per_sec", "MB/s", None),
    # tiered-store replay ladder (ISSUE 18): remote-tier replay rate
    # with a cold cache vs the local hot tier, + cold-backfill
    # time-to-first-batch; no reference twin (its history ended at
    # broker disk × retention.ms), so vs_baseline deliberately 0
    ("tiered_remote_replay_records_per_sec", "records/s", None),
    # zero-copy columnar consume path (ISSUE 10): python vs fused vs
    # columnar decode rate over one durable topic + the RAW_FETCH
    # wire leg — the host-pipeline ceiling behind the e2e knee.
    # Baseline: the reference's measured train-consume rate
    ("pipeline_columnar_records_per_sec", "records/s",
     TRAIN_BASELINE_RPS),
    # self-hosted telemetry plane (ISSUE 17): the columnar consume
    # leg with scrape → TSDB-append → SLO burn-rate evaluation armed
    # vs off (acceptance: armed within 5% of off), plus the TSDB's
    # own ingest/query/eval walls and compaction boundedness
    ("tsdb_pipeline_records_per_sec", "records/s",
     TRAIN_BASELINE_RPS),
    # digital-twin materialisation (iotml.twin): fold rate into the
    # per-car feature store, changelog-compaction MB/s reclaimed,
    # and GET /twin/<id> REST latency; the reference's twin lived
    # in managed MongoDB (no published rates), so vs_baseline 0
    ("twin_apply_records_per_sec", "records/s", None),
    # sharded scatter-gather twin serving (ISSUE 20): aggregate point-
    # lookup rate through the smart client's pipelined per-shard mget
    # while ingest + feature-join scoring run and one shard fails over
    # mid-storm; the reference served its twin from managed MongoDB
    # (no published query rates), so vs_baseline deliberately 0
    ("gateway_lookups_per_sec", "lookups/s", None),
    # async-checkpointing overhead (iotml.mlops): train throughput
    # with async registry checkpoints vs publication-off vs the
    # legacy sync h5 export — the "no training stall" claim as a
    # measured percentage (ISSUE 7: async within 10% of off)
    ("train_ckpt_async_records_per_sec", "records/s",
     TRAIN_BASELINE_RPS),
    # true online learning (iotml.online): records to recover
    # detection AUC after a seeded regional drift — online
    # (incremental + drift-triggered adaptation) vs the micro-batch
    # ContinuousTrainer baseline, same model, byte-identical
    # stream; plus the adversarial scenario suite's quality/rate
    # passes and the incremental-throughput guard.  No reference
    # twin (its README disclaims online learning), vs_baseline 0
    ("online_adapt_records", "records", None),
    # quorum replication (iotml.replication): acks=all throughput
    # vs acks=1 through a live leader + 2 ISR followers, and the
    # reassignment catch-up rate over zero-copy RAW_FETCH — the
    # reference ran RF 3 on managed Kafka (no published overhead
    # numbers), so vs_baseline deliberately 0
    ("replication_acks_all_records_per_sec", "records/s", None),
    # the partitioned data plane's saturation knee at 3 brokers
    # (separate processes), vs the r05 single-LEADER platform knee
    # it exists to move; on >=8-core hosts scaling_x also shows the
    # per-broker parallelism directly
    ("cluster_saturation_records_per_sec", "records/s", None),
    # multi-chip streaming training (ISSUE 15): the 1→N emulated-
    # chip scaling curve of partition-parallel columnar feeds into
    # the sharded train step; legs share the MULTICHIP_r* harness
    # schema.  vs_baseline: the reference's measured train rate
    ("multichip_train_records_per_sec", "records/s",
     TRAIN_BASELINE_RPS),
    # the whole platform live at once: fleet → MQTT → bridge → KSQL
    # in the main process, training in a TPU child process, scoring in
    # a CPU child process (the deploy manifests' pod separation), the
    # model loop closed through the artifact store — the reference's
    # actual demo shape, with publish→prediction latency, live
    # detection quality, and a paced-rate sweep riding along
    ("e2e_platform_records_per_sec", "records/s", FLEET_BASELINE_MPS),
    # live anomaly-detection quality: the scorer's threshold verdicts
    # (the ones written to the predictions topic) scored against the
    # generator's injected failure labels; value is the live AUC
    ("e2e_detection_quality", "auc", None),
    # the measured saturation knee (max records/s across the paced
    # sweep) — the self-pacing headline window targets 0.8× this
    ("e2e_saturation_records_per_sec", "records/s",
     FLEET_BASELINE_MPS),
    # write-path breakdown for the run above: records shipped as
    # pre-framed raw batches + per-leg seconds (bridge produce,
    # native convert+frame, raw append) — ISSUE 12's produce legs
    ("e2e_produce_leg_records", "records", None),
    ("e2e_latency_ms", "ms", None),
    # the headline stays the LAST printed line (the driver parses the
    # final JSON line as the headline metric)
    ("streaming_train_records_per_sec_per_chip", "records/s",
     TRAIN_BASELINE_RPS),
]

# metric emitted by each directly-runnable bench function — the
# `python bench.py bench_<name>` entry point; a bench missing here
# fails loudly instead of emitting under a bare function name
SINGLE_BENCH = {
    "bench_train_inproc": "streaming_train_records_per_sec_per_chip",
    "bench_train_wire": "wire_train_records_per_sec_per_chip",
    "bench_lstm_train": "lstm_train_windows_per_sec_per_chip",
    "bench_mnist_smoke": "mnist_stream_images_per_sec",
    "bench_long_context": "flash_attention_fwd_bwd_tokens_per_sec",
    "bench_serve": "serve_rows_per_sec",
    "bench_ksql_pipeline": "ksql_pipeline_records_per_sec",
    "bench_store_log": "store_append_mb_per_sec",
    "bench_tiered": "tiered_remote_replay_records_per_sec",
    "bench_pipeline": "pipeline_columnar_records_per_sec",
    "bench_tsdb": "tsdb_pipeline_records_per_sec",
    "bench_twin": "twin_apply_records_per_sec",
    "bench_gateway": "gateway_lookups_per_sec",
    "bench_checkpoint": "train_ckpt_async_records_per_sec",
    "bench_online": "online_adapt_records",
    "bench_replication": "replication_acks_all_records_per_sec",
    "bench_cluster_saturation": "cluster_saturation_records_per_sec",
    "bench_multichip": "multichip_train_records_per_sec",
}


def main():
    t_all = time.perf_counter()

    # Execution order ≠ print order: the compute benches run FIRST (clean
    # allocator/process state — the fleet benches churn GBs of message
    # objects that fragment the heap and depress later timings), but the
    # headline metric still PRINTS last for line-oriented consumers.
    # Results are recorded as each bench completes and flushed in the
    # finally block, so a late bench failure cannot discard the metrics
    # already measured.
    results = {}
    order = METRIC_ORDER
    import gc

    def run(name, fn):
        # a full collection between benches: each bench churns millions of
        # objects, and leftover garbage measurably depresses the next
        # bench's timings on this single-core box
        gc.collect()
        results[name] = fn()

    try:
        run("streaming_train_records_per_sec_per_chip", bench_train_inproc)
        run("wire_train_records_per_sec_per_chip", bench_train_wire)
        run("lstm_train_windows_per_sec_per_chip", bench_lstm_train)
        run("mnist_stream_images_per_sec", bench_mnist_smoke)
        run("flash_attention_fwd_bwd_tokens_per_sec", bench_long_context)
        run("serve_rows_per_sec", bench_serve)
        run("ksql_pipeline_records_per_sec", bench_ksql_pipeline)
        run("store_append_mb_per_sec", bench_store_log)
        run("tiered_remote_replay_records_per_sec", bench_tiered)
        run("pipeline_columnar_records_per_sec", bench_pipeline)
        run("tsdb_pipeline_records_per_sec", bench_tsdb)
        run("twin_apply_records_per_sec", bench_twin)
        try:
            run("gateway_lookups_per_sec", bench_gateway)
        except Exception as e:
            print(f"# gateway skipped: {e}", file=sys.stderr)
        run("train_ckpt_async_records_per_sec", bench_checkpoint)
        run("online_adapt_records", bench_online)
        try:
            run("replication_acks_all_records_per_sec",
                bench_replication)
        except Exception as e:
            print(f"# replication skipped: {e}", file=sys.stderr)
        try:
            run("cluster_saturation_records_per_sec",
                bench_cluster_saturation)
        except Exception as e:  # subprocess-hostile sandboxes: skip
            print(f"# cluster_saturation skipped: {e}", file=sys.stderr)
        try:
            run("multichip_train_records_per_sec", bench_multichip)
        except Exception as e:  # subprocess-hostile sandboxes: skip
            print(f"# multichip skipped: {e}", file=sys.stderr)
        run("fleet_ingest_msgs_per_sec", bench_fleet_ingest)
        try:
            run("fleet_ingest_native_msgs_per_sec",
                bench_fleet_ingest_native)
        except Exception as e:  # no toolchain: the Python front remains
            print(f"# fleet_ingest_native skipped: {e}", file=sys.stderr)
        try:
            run("fleet_ingest_multiproc_msgs_per_sec",
                bench_fleet_ingest_multiproc)
        except Exception as e:
            print(f"# fleet_ingest_multiproc skipped: {e}", file=sys.stderr)
        try:
            run("fleet_soak_msgs_per_sec", bench_fleet_soak)
        except Exception as e:
            print(f"# fleet_soak skipped: {e}", file=sys.stderr)
        try:
            run("fleet_conn_memory_kb_per_conn", bench_fleet_conn_memory)
        except Exception as e:
            print(f"# fleet_conn_memory skipped: {e}", file=sys.stderr)
        res = None
        try:
            run("e2e_platform_records_per_sec", bench_e2e_platform)
            res = results["e2e_platform_records_per_sec"]
        except Exception as e:
            print(f"# e2e_platform skipped: {e}", file=sys.stderr)
        if res is not None:
            quality = res.pop("_quality", None)
            if quality is not None:
                results["e2e_detection_quality"] = quality
            sat_res = res.pop("_saturation", None)
            if sat_res is not None:
                results["e2e_saturation_records_per_sec"] = sat_res
            legs = res.pop("_produce_legs", None)
            if legs is not None:
                results["e2e_produce_leg_records"] = legs
        if res is not None and res.get("latency_ms_p50") is not None:
            lat_line = dict(
                value=res.get("latency_ms_p50"),
                p95_ms=res.get("latency_ms_p95"),
                n_markers=res.get("n_latency_markers"),
                definition="publish→prediction flow completion; "
                           "per_record_* = sampled true per-record "
                           "latency (bridge stamp → prediction drain)")
            for k in ("per_record_latency_ms_p50",
                      "per_record_latency_ms_p95", "n_per_record_samples"):
                if res.get(k) is not None:
                    lat_line[k] = res[k]
            results["e2e_latency_ms"] = lat_line
    finally:
        for metric, unit, baseline in order:
            res = results.get(metric)
            if res is None:
                continue
            v = res.pop("value")
            _emit(metric, v, unit,
                  (v / baseline) if baseline else 0.0, **res)
        print(f"# total_bench_wall={time.perf_counter() - t_all:.1f}s",
              file=sys.stderr)


def run_named(names):
    """``python bench.py <bench_fn> [...]`` — run just the named
    benches (e.g. ``bench_multichip``) and print their metric lines in
    the same JSON schema ``main()`` emits.  Metric names come from
    SINGLE_BENCH and units/baselines from METRIC_ORDER — the same
    tables main() prints from, so the two entry points cannot drift."""
    units = {metric: (unit, baseline)
             for metric, unit, baseline in METRIC_ORDER}
    rc = 0
    for name in names:
        fn = globals().get(name)
        metric = SINGLE_BENCH.get(name)
        if metric is None or fn is None or not callable(fn):
            print(f"# unknown bench {name!r} (choose from "
                  f"{sorted(SINGLE_BENCH)})", file=sys.stderr)
            rc = 2
            continue
        unit, baseline = units[metric]
        res = fn()
        v = res.pop("value")
        _emit(metric, v, unit, (v / baseline) if baseline else 0.0, **res)
    return rc


if __name__ == "__main__":
    if len(sys.argv) > 1:
        sys.exit(run_named(sys.argv[1:]))
    main()
