#!/usr/bin/env python3
"""Fleet-scope observability drill (ISSUE 13 acceptance run).

A LIVE 3-broker cluster with four OS processes — the cluster harness
(this process), a RAW_PRODUCE producer, a columnar scorer, and a
continuous trainer — all tracing into ONE span log and serving
/metrics into one endpoints manifest.  Asserts:

- ``iotml_watermark_lag_seconds`` published for the score AND train
  stages (the columnar plane's event-time watermarks, per process);
- the federation collector serves merged cluster metrics from >= 4
  processes and snapshots fleet state into the compacted
  ``_IOTML_METRICS`` changelog;
- ``python -m iotml.obs trace`` reconstructs at least one CLOSED e2e
  trace whose spans cross >= 3 processes (producer → shard → scorer:
  the wire-carried batch-trace leg, which PR 2's header-dropping wire
  clients could never do);
- the /healthz stage-liveness view reports the columnar consume stage
  LIVE (the false-dead regression this PR fixes).

    python deploy/fleet_obs_smoke.py [--records 6000] [--quick]

CI (obs.yml) runs this followed by the trace CLI assertions.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TOPIC = "SENSOR_DATA_S_AVRO"
PREDICTIONS = "model-predictions"
PARTITIONS = 3
BASE_PORT = 19412


def _env(role: str, workdir: str) -> dict:
    env = dict(os.environ)
    env.update(IOTML_PROC=role, IOTML_TRACE="1",
               IOTML_TRACE_SAMPLE="1.0",
               IOTML_TRACE_PATH=os.path.join(workdir, "spans.jsonl"),
               IOTML_OBS_ENDPOINTS=os.path.join(workdir,
                                                "endpoints.json"),
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    return env


def _bootstrap() -> str:
    return ",".join(f"127.0.0.1:{BASE_PORT + i}"
                    for i in range(3))


def _mark_done(workdir: str, role: str) -> None:
    with open(os.path.join(workdir, f"{role}.done"), "w") as fh:
        fh.write("done")


# ----------------------------------------------------------- child roles
def run_producer(args) -> int:
    import numpy as np

    from iotml.cluster.client import ClusterClient
    from iotml.core.schema import KSQL_CAR_SCHEMA
    from iotml.obs.metrics import start_http_server
    from iotml.stream import native as native_mod
    from iotml.stream.producer import RawBatchProducer

    start_http_server(0)
    client = ClusterClient(bootstrap=_bootstrap())
    nc = native_mod.NativeCodec(KSQL_CAR_SCHEMA)
    prod = [RawBatchProducer(client, TOPIC) for _ in range(PARTITIONS)]
    rng = np.random.default_rng(11)
    batch = 200
    sent = 0
    while sent < args.records:
        n = min(batch, args.records - sent)
        numeric = rng.normal(size=(n, nc.n_numeric))
        labels = np.full((n, nc.n_strings), b"false", "S16")
        now_ms = int(time.time() * 1000)  # wallclock-ok: record
        # timestamps ARE wall-domain event time (the watermark source)
        ts = np.full((n,), now_ms, np.int64)
        keys = np.asarray([b"car-%03d" % (i % 40) for i in range(n)],
                          "S64")
        frames = nc.encode_frames(numeric, labels, timestamps=ts,
                                  keys=keys, schema_id=1)
        p = (sent // batch) % PARTITIONS
        prod[p].produce_frames(p, frames, n)
        sent += n
        time.sleep(0.01)  # a paced fleet, not one burst
    print(f"producer: {sent} records over RAW_PRODUCE "
          f"(raw plane engaged: {prod[0].engaged})", flush=True)
    _mark_done(args.workdir, "producer")
    time.sleep(args.linger)  # stay scrapeable for the federation pass
    client.close()
    return 0


def run_scorer(args) -> int:
    import numpy as np

    from iotml.cluster.client import ClusterClient
    from iotml.data.dataset import SensorBatches
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.obs.metrics import start_http_server
    from iotml.serve.scorer import StreamScorer
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.producer import OutputSequence
    from iotml.train.loop import Trainer

    start_http_server(0)
    client = ClusterClient(bootstrap=_bootstrap())
    specs = [f"{TOPIC}:{p}:0" for p in range(PARTITIONS)]
    consumer = StreamConsumer(client, specs, group="fleet-obs-score",
                              eof=False)
    sb = SensorBatches(consumer, batch_size=100, keep_labels=True,
                       poll_chunk=2048)
    tr = Trainer(CAR_AUTOENCODER)
    tr._ensure_state(np.zeros((100, 18), np.float32))
    scorer = StreamScorer(CAR_AUTOENCODER, tr.state.params, sb,
                          OutputSequence(client, PREDICTIONS))
    deadline = time.monotonic() + args.timeout
    while scorer.scored < args.records and time.monotonic() < deadline:
        if scorer.score_available() == 0:
            time.sleep(0.1)
    print(f"scorer: {scorer.scored} records scored "
          f"(columnar ring: {sb._ring not in (None, False)})",
          flush=True)
    _mark_done(args.workdir, "scorer")
    time.sleep(args.linger)
    client.close()
    return 0 if scorer.scored >= args.records else 1


def run_trainer(args) -> int:
    import tempfile

    from iotml.cluster.client import ClusterClient
    from iotml.obs.metrics import start_http_server
    from iotml.train.artifacts import ArtifactStore
    from iotml.train.live import ContinuousTrainer

    start_http_server(0)
    client = ClusterClient(bootstrap=_bootstrap())
    with tempfile.TemporaryDirectory(prefix="iotml_fleet_obs_") as tmp:
        svc = ContinuousTrainer(client, TOPIC, ArtifactStore(tmp),
                                group="fleet-obs-train",
                                batch_size=50, take_batches=4)
        deadline = time.monotonic() + args.timeout
        rounds = 0
        while rounds < 2 and time.monotonic() < deadline:
            if svc.available() < svc.min_available:
                time.sleep(0.1)
                continue
            if svc.train_round():
                rounds += 1
    print(f"trainer: {rounds} rounds, loss {svc.last_loss}", flush=True)
    _mark_done(args.workdir, "trainer")
    time.sleep(args.linger)
    client.close()
    return 0 if rounds >= 2 else 1


# ------------------------------------------------------------- harness
def run_harness(args) -> int:
    workdir = args.workdir
    os.makedirs(workdir, exist_ok=True)
    env = _env("cluster", workdir)
    os.environ.update(env)

    from iotml.cluster import ClusterController
    from iotml.obs import federate, tracing
    from iotml.obs.metrics import start_http_server

    tracing.configure_from_env()
    ctl = ClusterController(brokers=3, base_port=BASE_PORT)
    ctl.start()
    ctl.create_topic(TOPIC, partitions=PARTITIONS)
    ctl.create_topic(PREDICTIONS, partitions=PARTITIONS)
    start_http_server(0)  # the cluster process joins the manifest too

    def spawn(role: str) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--role", role,
             "--records", str(args.records),
             "--timeout", str(args.timeout),
             "--linger", str(args.linger), "--workdir", workdir],
            env=_env(role, workdir))

    children = {r: spawn(r) for r in ("producer", "scorer", "trainer")}
    failures = []
    try:
        # wait for every child's done marker (they then linger, still
        # serving /metrics, so federation scrapes a LIVE fleet)
        deadline = time.monotonic() + args.timeout + 30
        want = set(children)
        while want and time.monotonic() < deadline:
            for role in list(want):
                if os.path.exists(os.path.join(workdir, f"{role}.done")):
                    want.discard(role)
                elif children[role].poll() not in (None, 0):
                    failures.append(f"{role} exited "
                                    f"{children[role].returncode}")
                    want.discard(role)
            time.sleep(0.2)
        if want:
            failures.append(f"children never finished: {sorted(want)}")

        # ---------------- federation: merged metrics from >= 4 procs
        manifest = os.path.join(workdir, "endpoints.json")
        col = federate.FleetCollector(manifest=manifest)
        snaps = col.collect()
        merged = col.render(snaps)
        hz = col.healthz(snaps)
        print(f"federation: {hz['up_count']}/{hz['process_count']} "
              f"processes up: {sorted(hz['processes'])}", flush=True)
        if hz["up_count"] < 4:
            failures.append(f"federation saw {hz['up_count']} live "
                            "processes, need >= 4")
        # watermarks for score AND train stages, from the live fleet
        for stage, proc in (("score", "scorer"), ("train", "trainer")):
            needle = f'stage="{stage}"'
            hit = any(needle in line and f'process="{proc}"' in line
                      for line in merged.splitlines()
                      if line.startswith("iotml_watermark_lag_seconds"))
            if not hit:
                failures.append(
                    f"no iotml_watermark_lag_seconds{{stage={stage}}} "
                    f"from process {proc} in the merged metrics")
        if "iotml_cluster_records_scored_total" not in merged:
            failures.append("cluster rollup families missing")
        # fleet state into the compacted changelog, replayed back
        client = ctl.client()
        col.snapshot_changelog(client, snaps)
        state = federate.read_fleet_state(client)
        if len(state) < 4:
            failures.append(f"_IOTML_METRICS replay has {len(state)} "
                            "processes, need >= 4")
        # columnar consume liveness (the false-dead fix): the scorer's
        # own /healthz must show a fresh consume stage
        scorer_addr = next(
            (e["address"] for e in federate.load_manifest(manifest)
             if e["name"] == "scorer"), None)
        if scorer_addr is None:
            failures.append("scorer endpoint missing from manifest")
        else:
            doc = json.loads(urllib.request.urlopen(
                f"http://{scorer_addr}/healthz", timeout=5).read())
            age = doc.get("stages", {}).get("consume",
                                            {}).get("last_span_age_s")
            if age is None or age > args.linger + args.timeout:
                failures.append(
                    f"scorer /healthz consume-stage age {age}: the "
                    "columnar session reads as stalled")
            wm = doc.get("watermarks", {})
            if not any(k.startswith("score:") for k in wm):
                failures.append(f"scorer /healthz watermarks: {wm}")
    finally:
        for p in children.values():
            p.terminate()
        for p in children.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        ctl.stop()
    tracing.flush()

    # ---------------- trace reconstruction across processes
    spans = os.path.join(workdir, "spans.jsonl")
    from iotml.obs.__main__ import main as obs_main

    rc = obs_main(["trace", spans, "--require-cross-process", "3",
                   "--show-trace"])
    if rc != 0:
        failures.append("trace CLI found no closed e2e trace spanning "
                        ">= 3 processes")
    for f in failures:
        print(f"FLEET OBS CHECK FAILED: {f}", file=sys.stderr)
    print("fleet obs drill:", "FAIL" if failures else "PASS",
          flush=True)
    return 1 if failures else 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", default="harness",
                    choices=("harness", "producer", "scorer", "trainer"))
    ap.add_argument("--records", type=int, default=6000)
    ap.add_argument("--timeout", type=float, default=90.0)
    ap.add_argument("--linger", type=float, default=25.0,
                    help="seconds a finished child stays scrapeable")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--quick", action="store_true",
                    help="small run for CI (2000 records)")
    args = ap.parse_args()
    if args.quick:
        args.records = min(args.records, 2000)
    if args.workdir is None:
        import tempfile

        args.workdir = tempfile.mkdtemp(prefix="iotml_fleet_obs_")
    if args.role == "producer":
        return run_producer(args)
    if args.role == "scorer":
        return run_scorer(args)
    if args.role == "trainer":
        return run_trainer(args)
    return run_harness(args)


if __name__ == "__main__":
    raise SystemExit(main())
