# Provision everything the deploy/ manifests need, from an empty GCP
# project, in one `terraform apply` — the role the reference's
# infrastructure/terraform-gcp/main.tf plays (GKE cluster + node pool +
# model bucket + service-account key handed to the install scripts,
# main.tf:8-163), re-designed for TPU:
#
#   - a standard CPU node pool carries the streaming platform
#     (deploy/platform.yaml — brokers, bridges, REST control planes);
#   - a TPU podslice node pool carries the train/score workloads
#     (deploy/model-training*.yaml select it via the same
#     gke-tpu-accelerator/topology labels written here);
#   - a GCS bucket is the model store (ArtifactStore gs:// root);
#   - a workload service account with objectAdmin on that bucket replaces
#     the reference's exported private key: GKE workload identity binds it
#     to the `default` KSA, so no key file ever exists — the
#     `google-application-credentials` Secret template stays empty.
#
# After apply, the kubectl steps in ../README.md run against the fresh
# cluster (credentials fetched by the kubeconfig output below).

terraform {
  required_providers {
    google = {
      source  = "hashicorp/google"
      version = ">= 5.0"
    }
  }
}

provider "google" {
  project = var.project
  region  = var.region
}

resource "google_container_cluster" "iotml" {
  name     = var.cluster_name
  location = var.zone

  # node pools are managed as separate resources below
  remove_default_node_pool = true
  initial_node_count       = 1

  workload_identity_config {
    workload_pool = "${var.project}.svc.id.goog"
  }

  release_channel {
    channel = "REGULAR"
  }
}

# ---- CPU pool: streaming platform, connectors, observability
resource "google_container_node_pool" "platform" {
  name     = "platform"
  cluster  = google_container_cluster.iotml.name
  location = var.zone

  node_count = var.platform_node_count

  autoscaling {
    min_node_count = 1
    max_node_count = var.platform_node_count
  }

  node_config {
    machine_type = var.platform_machine_type
    oauth_scopes = ["https://www.googleapis.com/auth/cloud-platform"]
    workload_metadata_config {
      mode = "GKE_METADATA"
    }
  }
}

# ---- TPU pool: the train Job + scorer Deployment land here through the
# nodeSelector labels GKE writes for TPU slices
resource "google_container_node_pool" "tpu" {
  name     = "tpu-ml"
  cluster  = google_container_cluster.iotml.name
  location = var.zone

  initial_node_count = 1

  autoscaling {
    min_node_count = 0 # scale to zero between training runs
    max_node_count = 2
  }

  node_config {
    machine_type = "ct5lp-hightpu-8t" # one v5e host (8 chips)
    spot         = var.tpu_spot
    oauth_scopes = ["https://www.googleapis.com/auth/cloud-platform"]
    workload_metadata_config {
      mode = "GKE_METADATA"
    }
    labels = {
      "cloud.google.com/gke-tpu-accelerator" = var.tpu_accelerator
      "cloud.google.com/gke-tpu-topology"    = var.tpu_topology
    }
  }
}

# ---- model store: the train→bucket→predict handoff target
resource "google_storage_bucket" "models" {
  name                        = "iotml-models-${var.project}-${var.cluster_name}"
  location                    = var.region
  uniform_bucket_level_access = true
  force_destroy               = true
}

# ---- workload identity instead of an exported key file
resource "google_service_account" "workload" {
  account_id   = "${var.cluster_name}-workload"
  display_name = "iotml workload (model store access)"
}

resource "google_storage_bucket_iam_member" "models_rw" {
  bucket = google_storage_bucket.models.name
  role   = "roles/storage.objectAdmin"
  member = "serviceAccount:${google_service_account.workload.email}"
}

resource "google_service_account_iam_member" "wi_binding" {
  service_account_id = google_service_account.workload.name
  role               = "roles/iam.workloadIdentityUser"
  member             = "serviceAccount:${var.project}.svc.id.goog[default/default]"
}
