output "kubeconfig_command" {
  description = "Fetch credentials for kubectl (the reference's 00_setup_GKE.sh role)"
  value       = "gcloud container clusters get-credentials ${google_container_cluster.iotml.name} --zone ${var.zone} --project ${var.project}"
}

output "model_bucket" {
  description = "gs:// root to pass as the manifests' <artifact-root>"
  value       = "gs://${google_storage_bucket.models.name}"
}

output "workload_service_account" {
  value = google_service_account.workload.email
}

output "ksa_annotate_command" {
  description = "REQUIRED after apply: workload identity needs the KSA annotated with the GSA in addition to the IAM binding, or pods get the node identity (no bucket access)"
  value       = "kubectl annotate serviceaccount default iam.gke.io/gcp-service-account=${google_service_account.workload.email}"
}
