output "kubeconfig_command" {
  description = "Fetch credentials for kubectl (the reference's 00_setup_GKE.sh role)"
  value       = "gcloud container clusters get-credentials ${google_container_cluster.iotml.name} --zone ${var.zone} --project ${var.project}"
}

output "model_bucket" {
  description = "gs:// root to pass as the manifests' <artifact-root>"
  value       = "gs://${google_storage_bucket.models.name}"
}

output "workload_service_account" {
  value = google_service_account.workload.email
}
