# Input variables for the iotml GKE+TPU provisioning.
# Counterpart of the reference's infrastructure/terraform-gcp/variables.tf
# (node_count/region/name/project), re-based for TPU slices.

variable "project" {
  description = "GCP project id (required)"
  type        = string
}

variable "region" {
  description = "Region for the cluster and bucket"
  type        = string
  default     = "us-central2"
}

variable "zone" {
  description = "Zone carrying the TPU slice node pool"
  type        = string
  default     = "us-central2-b"
}

variable "cluster_name" {
  description = "GKE cluster name"
  type        = string
  default     = "iotml-cluster"
}

variable "platform_node_count" {
  description = "CPU nodes for the streaming platform / brokers"
  type        = number
  default     = 3
}

variable "platform_machine_type" {
  description = "Machine type for the platform node pool"
  type        = string
  default     = "n2-standard-8"
}

variable "tpu_accelerator" {
  description = "TPU accelerator type label for the ML node pool"
  type        = string
  default     = "tpu-v5-lite-podslice"
}

variable "tpu_topology" {
  description = "TPU slice topology (chips layout)"
  type        = string
  default     = "2x4"
}

variable "tpu_spot" {
  description = "Run the TPU pool on spot capacity (cheap, preemptible — the reference's optional-preemptible knob, as accidental chaos testing)"
  type        = bool
  default     = false
}

variable "image" {
  description = "Container image the manifests run (built from the repo Dockerfile)"
  type        = string
  default     = "iotml:latest"
}
