"""Execute the training Job + predict Deployment manifests locally.

The no-cluster leg of deploy/smoke.sh: proves the *manifests* — their
commands, args, env contracts, and secret wiring — drive a working
pipeline, not just that the library works.  It stands up the platform the
way platform.yaml does (`iotml.cli.up` with SASL from secrets.yaml), then
runs the training Job's exact command/args (service DNS rewritten to
127.0.0.1, the gs:// artifact root redirected to a temp dir — the two
things only a cluster provides), then the predict Deployment's, and
checks predictions landed on the result topic.
"""

from __future__ import annotations

import os
import re
import sys
import tempfile
import threading

import yaml

DEPLOY_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(DEPLOY_DIR)


def _load(fname):
    with open(os.path.join(DEPLOY_DIR, fname)) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def _container(doc):
    return doc["spec"]["template"]["spec"]["containers"][0]


def _secret_values():
    out = {}
    for doc in _load("secrets.yaml"):
        if doc.get("kind") == "Secret":
            out[doc["metadata"]["name"]] = dict(doc.get("stringData", {}))
    return out


def _resolve_env(container, secrets):
    env = {}
    for e in container.get("env", []):
        if "value" in e:
            env[e["name"]] = e["value"]
        else:
            ref = e.get("valueFrom", {}).get("secretKeyRef", {})
            env[e["name"]] = secrets.get(ref.get("name"), {}).get(
                ref.get("key"), "")
    return env


def main() -> int:
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    secrets = _secret_values()
    # the committed secrets.yaml is a fill-in template (empty strings); the
    # smoke substitutes test credentials so the SASL leg is exercised the
    # way a filled-in secret would exercise it
    creds = secrets.setdefault("broker-credentials", {})
    creds["username"] = creds.get("username") or "smoke-user"
    creds["password"] = creds.get("password") or "smoke-pass"
    sasl = (creds["username"], creds["password"])

    # ---- platform.yaml: the one-process platform with SASL on
    from iotml.cli.up import Platform

    plat = Platform(sasl=sasl, partitions=10).start()
    try:
        # seed the stream the way devsim.yaml's fleet would
        plat.start_fleet(num_cars=25, rate_hz=20.0, failure_rate=0.02)
        import time

        time.sleep(3.0)
        plat.pump()
        plat.stop_fleet()
        plat.pump()

        artifact_root = tempfile.mkdtemp(prefix="iotml_smoke_store_")

        def run_manifest(fname):
            (doc,) = [d for d in _load(fname)
                      if d.get("kind") in ("Job", "Deployment")]
            c = _container(doc)
            assert c["command"][:2] == ["python", "-m"]
            module = c["command"][2]
            args = list(c.get("args", []))
            # cluster-only indirections, rewritten for local execution:
            args = [re.sub(r"^[a-z0-9.-]+\.svc\.cluster\.local:\d+$",
                           f"127.0.0.1:{plat.kafka.port}", a) for a in args]
            args = [artifact_root if a.startswith("gs://") else a
                    for a in args]
            env = _resolve_env(c, secrets)
            env.pop("IOTML_MESH_DATA", None)  # no 8-chip slice here
            # the smoke proves the contract, not the convergence: a short
            # fit keeps the no-accelerator leg fast (env layer override —
            # exactly how an operator would tune the same Job)
            env.setdefault("IOTML_TRAIN_EPOCHS", "3")
            old = {k: os.environ.get(k) for k in env}
            os.environ.update(env)
            try:
                import importlib

                mod = importlib.import_module(module)
                print(f"--- {fname}: python -m {module} {' '.join(args)}")
                # the scorer Deployment is a long-lived loop by design
                # (that's its whole point vs the reference's restart churn);
                # the smoke bounds it to a few drain rounds
                kwargs = {"max_rounds": 30} if module.endswith(".serve") \
                    else {}
                rc = mod.main(args, **kwargs)
                assert rc == 0, f"{fname}: {module} exited {rc}"
            finally:
                for k, v in old.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v

        run_manifest("model-training.yaml")
        run_manifest("model-predictions.yaml")

        n = plat.broker.end_offset("model-predictions", 0)
        assert n > 0, "predict wrote nothing to model-predictions"
        print(f"run_manifest_job: OK — {n} predictions on the result topic")
        return 0
    finally:
        plat.stop()


if __name__ == "__main__":
    raise SystemExit(main())
