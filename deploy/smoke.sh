#!/usr/bin/env bash
# Deploy smoke test — executes the deploy story top to bottom.
#
# With docker available:  builds iotml:latest from the repo Dockerfile and
# runs the manifest-driven pipeline inside the image.
# Without docker (CI/dev boxes like this repo's):  validates every manifest
# against the codebase and runs the SAME manifest commands against the
# local checkout — the documented dry-run the manifests are tested by.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 0/5 whole-program contract analysis (iotml.analysis: lint +"
echo "        protocol conformance + trace discipline + registry drift,"
echo "        one shared parse; then the static lock-order extraction)"
python -m iotml.analysis all
python -m iotml.analysis lockorder

echo "== 1/5 chaos drills: seeded failure scenarios, invariant-checked"
JAX_PLATFORMS=cpu python -m iotml.chaos run --scenario mqtt-flap \
  --seed 7 --records 500
JAX_PLATFORMS=cpu python -m iotml.chaos run --scenario broker-crash-recover \
  --seed 7 --records 500
JAX_PLATFORMS=cpu python -m iotml.chaos run --scenario rebalance-under-chaos \
  --seed 7 --records 500
JAX_PLATFORMS=cpu python -m iotml.chaos run --scenario compaction-under-crash \
  --seed 7 --records 500
JAX_PLATFORMS=cpu python -m iotml.chaos run --scenario drift-storm \
  --seed 7 --records 2000
JAX_PLATFORMS=cpu python -m iotml.chaos run --scenario double-fault \
  --seed 7 --records 500
echo "==      tier-upload-crash drill (iotml.store.tiered): the tier"
echo "        uploader killed between blob uploads and the manifest"
echo "        commit — torn upload never served, local authoritative,"
echo "        cold remote replay byte-identical, garbage swept"
JAX_PLATFORMS=cpu python -m iotml.chaos run --scenario tier-upload-crash \
  --seed 7 --records 500
echo "==      alert-burn drill (iotml.obs): sustained delivery delay"
echo "        must FIRE the fast burn-rate pair onto _IOTML_ALERTS +"
echo "        /healthz within budget, then RESOLVE on recovery"
JAX_PLATFORMS=cpu python -m iotml.chaos run --scenario alert-burn \
  --seed 7 --records 600

echo "== 2/5 supervised restart: live scorer-crash drill (the scorer"
echo "        thread dies twice; the supervisor must heal the pipeline)"
JAX_PLATFORMS=cpu python -m iotml.supervise drill --drill scorer-crash \
  --seed 7 --records 500
echo "==      live model rollout drill (iotml.mlops): 3 promotions"
echo "        hot-swap under load, every record scored exactly once"
JAX_PLATFORMS=cpu python -m iotml.mlops drill --drill rollout \
  --seed 7 --records 500
echo "==      live twin-rebuild drill (iotml.twin): kill the twin"
echo "        service, rebuild from the compacted changelog, state"
echo "        equals the pre-kill snapshot"
JAX_PLATFORMS=cpu python -m iotml.twin drill --seed 7 --records 1500
echo "==      live gateway shard-kill drill (iotml.gateway): standby"
echo "        promoted under a query storm — promote SLO, zero wrong"
echo "        answers, bounded staleness"
JAX_PLATFORMS=cpu python -m iotml.gateway drill --seed 7 --records 1500 \
  --cars 30
echo "==      live drift-adapt-swap drill (iotml.online): seeded"
echo "        regional drift detected within the SLO, adaptation"
echo "        published + hot-swapped, wrecked adaptation rolled back"
JAX_PLATFORMS=cpu python -m iotml.online drill --seed 7 \
  --slo-detect-records 1500

echo "== 3/5 validate manifests against the codebase"
python deploy/validate_manifests.py

if command -v docker >/dev/null 2>&1; then
  echo "== 4/5 docker build iotml:latest"
  docker build -t iotml:latest .
  echo "== 5/5 manifest-driven train+predict inside the image"
  docker run --rm -e JAX_PLATFORMS=cpu iotml:latest \
    deploy/run_manifest_job.py
else
  echo "== 4/5 docker not found — executing manifest commands locally"
  JAX_PLATFORMS=cpu python deploy/run_manifest_job.py
  echo "== 5/5 (image build skipped: no docker; Dockerfile is built by CI" \
       "or any docker host with: docker build -t iotml:latest .)"
fi
echo "deploy smoke: OK"
