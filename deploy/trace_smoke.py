#!/usr/bin/env python3
"""Traced end-to-end smoke: devsim fleet → MQTT → bridge → KSQL →
consumer → scorer with IOTML_TRACE=1, then assert the span log covers
the pipeline (ISSUE 2 acceptance run; .github/workflows/obs.yml runs
this followed by the `python -m iotml.obs trace` CLI checks).

    IOTML_TRACE=1 IOTML_TRACE_PATH=spans.jsonl python deploy/trace_smoke.py
    python -m iotml.obs trace spans.jsonl --min-stages 5 --require-e2e
"""

from __future__ import annotations

import json
import os
import sys

# runnable straight from a checkout: `python deploy/trace_smoke.py`
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    if os.environ.get("IOTML_TRACE") != "1":
        print("set IOTML_TRACE=1 (and IOTML_TRACE_PATH) for a traced run",
              file=sys.stderr)
        return 2

    import numpy as np

    from iotml.core.schema import CAR_SCHEMA
    from iotml.data.dataset import SensorBatches
    from iotml.gen.simulator import FleetGenerator, FleetScenario
    from iotml.mqtt.broker import MqttBroker
    from iotml.mqtt.bridge import KafkaBridge
    from iotml.models.autoencoder import CAR_AUTOENCODER
    from iotml.obs import tracing
    from iotml.obs import metrics as obs_metrics
    from iotml.serve.scorer import StreamScorer
    from iotml.stream.broker import Broker
    from iotml.stream.consumer import StreamConsumer
    from iotml.stream.producer import OutputSequence
    from iotml.streamproc.tasks import JsonToAvro, RekeyByCar
    from iotml.train.loop import Trainer

    # devsim fleet publishes JSON sensor records over the MQTT broker;
    # the bridge forwards into `sensor-data`; the KSQL-equivalent tasks
    # produce the framed-Avro ML input topic
    mqtt = MqttBroker()
    stream = Broker()
    bridge = KafkaBridge(mqtt, stream, partitions=2)
    gen = FleetGenerator(FleetScenario(num_cars=25, seed=7))
    n_ticks = 8
    for _ in range(n_ticks):
        cols = gen.step_columns()
        for i in range(len(cols["car"])):
            rec = gen.row_record(cols, i, schema=CAR_SCHEMA)
            rec["failure_occurred"] = str(cols["failure_occurred"][i])
            mqtt.publish(f"vehicles/sensor/data/{gen.scenario.car_id(i)}",
                         json.dumps(rec).encode(), qos=1)
    assert bridge.forwarded() == 25 * n_ticks
    JsonToAvro(stream, src="sensor-data",
               dst="SENSOR_DATA_S_AVRO").process_available()
    RekeyByCar(stream, src="SENSOR_DATA_S_AVRO",
               dst="SENSOR_DATA_S_AVRO_REKEY",
               partitions=2).process_available()

    # consumer → scorer closes every trace with its e2e span
    spec = stream.topic("SENSOR_DATA_S_AVRO")
    consumer = StreamConsumer(
        stream, [f"SENSOR_DATA_S_AVRO:{p}:0" for p in range(spec.partitions)],
        group="trace-smoke")
    batches = SensorBatches(consumer, batch_size=100)
    trainer = Trainer(CAR_AUTOENCODER)
    trainer._ensure_state(np.zeros((100, 18), np.float32))
    scorer = StreamScorer(CAR_AUTOENCODER, trainer.state.params, batches,
                          OutputSequence(stream, "model-predictions",
                                         partition=0))
    scored = scorer.score_available()
    counts = tracing.flush()
    render = obs_metrics.default_registry.render()
    ok_hist = ("iotml_stage_seconds_bucket" in render
               and "iotml_e2e_ingest_to_score_seconds_count" in render)
    print(json.dumps({"published": bridge.forwarded(), "scored": scored,
                      "spans_flushed": counts, "histograms": ok_hist}))
    if scored != 25 * n_ticks or not ok_hist:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
