"""Validate every manifest under deploy/ against the codebase.

`kubectl apply --dry-run` checks YAML against the K8s API; this checks it
against *this repo*: that every image is the one the Dockerfile builds,
every `python -m` entrypoint is an importable module with a main(), every
`IOTML_*` env var is one the config layer actually reads, and every
secretKeyRef points at a secret (and key) defined in secrets.yaml.  Run by
deploy/smoke.sh; exits non-zero with a per-manifest error list.
"""

from __future__ import annotations

import importlib
import os
import sys

import yaml

DEPLOY_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(DEPLOY_DIR)
IMAGE = "iotml:latest"


def _docs():
    for fname in sorted(os.listdir(DEPLOY_DIR)):
        if not fname.endswith(".yaml"):
            continue
        with open(os.path.join(DEPLOY_DIR, fname)) as f:
            for doc in yaml.safe_load_all(f):
                if doc:
                    yield fname, doc


def _containers(doc):
    spec = doc.get("spec", {})
    tmpl = spec.get("template", spec.get("jobTemplate", {}).get(
        "spec", {}).get("template", {}))
    pod = tmpl.get("spec", {})
    return pod.get("containers", []) + pod.get("initContainers", [])


def _known_env_keys():
    """IOTML_* names the config tree accepts (iotml.config)."""
    from iotml.config import Config, env_key_names

    return set(env_key_names(Config()))


def main() -> int:
    sys.path.insert(0, REPO)
    errors = []

    secrets = {}
    for fname, doc in _docs():
        if doc.get("kind") == "Secret":
            name = doc["metadata"]["name"]
            keys = set(doc.get("stringData", {})) | set(doc.get("data", {}))
            secrets[name] = keys

    try:
        known_env = _known_env_keys()
    except Exception as e:  # config helper missing → still check the rest
        known_env = None
        errors.append(f"config introspection failed: {e}")

    n_containers = 0
    for fname, doc in _docs():
        kind = doc.get("kind", "?")
        for c in _containers(doc):
            n_containers += 1
            where = f"{fname}/{kind}/{c.get('name')}"
            if c.get("image") != IMAGE:
                errors.append(f"{where}: image {c.get('image')!r} != "
                              f"{IMAGE!r} (what the Dockerfile builds)")
            cmd = list(c.get("command", []))
            if cmd[:2] == ["python", "-m"] and len(cmd) > 2:
                mod = cmd[2]
                try:
                    m = importlib.import_module(mod)
                    if not hasattr(m, "main"):
                        errors.append(f"{where}: module {mod} has no main()")
                except Exception as e:
                    errors.append(f"{where}: cannot import {mod}: {e}")
            for env in c.get("env", []):
                name = env.get("name", "")
                if name.startswith("IOTML_") and known_env is not None \
                        and name not in known_env:
                    errors.append(f"{where}: env {name} is not a key the "
                                  f"config layer reads")
                ref = env.get("valueFrom", {}).get("secretKeyRef")
                if ref:
                    sname, key = ref.get("name"), ref.get("key")
                    if sname not in secrets:
                        errors.append(f"{where}: secretKeyRef to undefined "
                                      f"secret {sname!r}")
                    elif key not in secrets[sname]:
                        errors.append(f"{where}: secret {sname!r} has no "
                                      f"key {key!r}")
        for vol in (doc.get("spec", {}).get("template", {})
                    .get("spec", {}).get("volumes", [])):
            s = vol.get("secret", {}).get("secretName")
            if s and s not in secrets:
                errors.append(f"{fname}/{kind}: volume secret {s!r} "
                              f"not defined in secrets.yaml")

    if errors:
        print(f"validate_manifests: {len(errors)} error(s)")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"validate_manifests: OK ({n_containers} containers across "
          f"{len(set(f for f, _ in _docs()))} files; image/entrypoint/env/"
          f"secret references all resolve)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
