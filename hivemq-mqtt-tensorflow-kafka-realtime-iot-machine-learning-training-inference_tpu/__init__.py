"""iotml — a TPU-native streaming-ML framework for IoT predictive maintenance.

Re-implements the capabilities of the reference system
`hivemq-mqtt-tensorflow-kafka-realtime-iot-machine-learning-training-inference`
(simulated car fleet → MQTT → Kafka → KSQL → TensorFlow train/score loop)
as an idiomatic JAX/XLA/Flax/Pallas stack:

- ``core``       typed record schemas + pure-jax normalization
- ``ops``        Avro wire codecs, windowing, Pallas kernels
- ``stream``     broker emulator, consumers/producers, CSV replay, MQTT bridge
- ``streamproc`` KSQL-equivalent stream transforms (convert / rekey / windowed aggs)
- ``data``       unbounded stream → fixed-shape device batches (static shapes for XLA)
- ``models``     flax.linen model zoo (autoencoder, LSTM seq2seq, MNIST) + h5 import
- ``train``      jit train loops, optax optimizers, orbax checkpoints + offset cursors
- ``serve``      long-lived jit scorer with ordered write-back
- ``parallel``   device mesh, data/tensor sharding, multi-host init
- ``gen``        car-fleet load generator (scenario-driven, failure modes)
- ``obs``        metrics registry (Prometheus text) + TensorBoard + generated Grafana dashboards
- ``cli``        reference-compatible entry points (cardata, lstm, creditcard, mnist_smoke)
- ``mqtt``       MQTT 5 broker/wire/bridge + scenario-driven device fleet
- ``connect``    connector runtime (file source, document sink, Avro data lake)
- ``evaluate``   anomaly eval: ROC/AUC, precision-recall, threshold confusion
- ``config``     one typed config tree (defaults < file < env < flags)
- ``utils``      host buffers, misc

The package directory on disk is
``hivemq-mqtt-tensorflow-kafka-realtime-iot-machine-learning-training-inference_tpu``;
``iotml`` is an import alias (symlink).
"""

__version__ = "0.1.0"

from . import core  # noqa: F401
