"""Top-level CLI index: `python -m iotml` lists every entry point.

The reference scatters its runnable surface across shell scripts, kubectl
plugins, and positional-arg Python files; here one command shows the map.
"""

from __future__ import annotations

import sys

COMMANDS = [
    ("iotml.cli.demo", "the whole reference pipeline end-to-end in one "
                       "command (fleet → KSQL → train → serve → anomalies)"),
    ("iotml.cli.up", "whole platform in one process (Kafka wire + MQTT + "
                     "Schema-Registry/KSQL/Connect REST + metrics + fleet)"),
    ("iotml.cli.cardata", "car-sensor autoencoder: streaming train/predict "
                          "(reference cardata-v3.py contract)"),
    ("iotml.cli.lstm", "LSTM seq2seq: streaming train/predict (reference "
                       "LSTM cardata-v2.py contract)"),
    ("iotml.cli.serve", "long-lived scorer with ordered write-back "
                        "(offset|committed|group elastic modes)"),
    ("iotml.cli.creditcard", "creditcard fraud demo: produce + train + eval"),
    ("iotml.cli.mnist_smoke", "MNIST ingest smoke test + in-memory control"),
    ("iotml.cli.devsim", "scenario-driven device fleet "
                         "(run/jobs/show/log/abort/example)"),
    ("iotml.obs.dashboards", "generate the Grafana dashboard ConfigMap"),
    ("iotml.obs", "trace: summarize a span log (IOTML_TRACE=1) into a "
                  "per-stage latency breakdown + bottleneck"),
]


def main() -> int:
    print("iotml — TPU-native streaming ML framework. Entry points:\n")
    for mod, desc in COMMANDS:
        print(f"  python -m {mod:24s} {desc}")
    print("\nSee README.md, PARITY.md, and deploy/README.md.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
