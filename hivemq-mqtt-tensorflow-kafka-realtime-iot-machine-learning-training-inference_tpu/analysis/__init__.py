"""iotml.analysis — project-wide concurrency & protocol-invariant checker.

The hot paths of this framework — MQTT broker, Kafka wire server/client,
follower replica, group coordinator, stream-proc pump — are hand-rolled
threaded code.  Their pipeline invariants (monotonic timeout clocks,
idempotent-only auto-retry, context-managed locks, no blocking I/O under
a broker lock, engine-owned topic write exclusivity) are machine-checked
here rather than left as tribal knowledge:

- ``lint``       AST lint pass over the tree: rules R1-R15, run via
                 ``python -m iotml.analysis lint`` (exit 1 on findings).
- ``protocol``   whole-program wire-protocol conformance (P1-P7):
                 api-id ↔ handler ↔ encoder ↔ error-code ↔ idempotency
                 tables extracted from the Python server/client, the
                 cluster router, the C++ client, the lint allowlist and
                 the chaos registry, checked for N-way symmetry.
- ``tracecheck`` JAX trace discipline (T1-T4): recompile & host-sync
                 hazards over the jit/scan/shard_map entry points; plus
                 a runtime recompile guard the pytest plugin arms with
                 ``IOTML_TRACECHECK=1`` (a warmed hot loop that
                 re-traces fails its test).
- ``drift``      registry drift (D1-D4): IOTML_* env knobs vs config,
                 metric label sets vs declarations, faultpoint strings
                 vs the chaos registry, rule ids vs ARCHITECTURE rows.
- ``lockorder``  static acquire-order extraction from nested ``with``
                 blocks (per-class call-graph fixpoint) — pre-seeds the
                 runtime cycle detector below.
- ``lockcheck``  runtime lock-order & race detector: an instrumented
                 ``threading.Lock``/``RLock`` wrapper that records the
                 per-thread lock-acquisition graph, fails on cycles
                 (deadlock potential), flags locks held across blocking
                 I/O, and tags unguarded mutations of registered shared
                 state from non-owner threads.  Enable for a pytest run
                 with ``IOTML_LOCKCHECK=1`` or
                 ``-p iotml.analysis.pytest_plugin``.
- the C++ edge is covered by TSan/ASan build targets instead
  (``make -C iotml/cpp sanitize``) — and statically by the protocol
  pass's P4 textual parse of ``cpp/kafka_client.cc``.

All passes share one parse per file (``analysis.program.Program``); the
CLI summary reports wall time and files parsed.

See ARCHITECTURE.md §25 for the rule tables, how to add a rule, and
how to suppress a finding with justification (``# lint-ok: <rule>
<reason>`` covers every family: R*, P*, T*, D*).
"""

from .lint import Finding, RULES, lint_paths  # noqa: F401
from .program import FileUnit, Program  # noqa: F401
