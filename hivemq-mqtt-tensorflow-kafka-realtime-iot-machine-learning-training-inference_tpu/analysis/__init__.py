"""iotml.analysis — project-wide concurrency & protocol-invariant checker.

The hot paths of this framework — MQTT broker, Kafka wire server/client,
follower replica, group coordinator, stream-proc pump — are hand-rolled
threaded code.  Their pipeline invariants (monotonic timeout clocks,
idempotent-only auto-retry, context-managed locks, no blocking I/O under
a broker lock, engine-owned topic write exclusivity) are machine-checked
here rather than left as tribal knowledge:

- ``lint``      AST lint pass over the tree: rules R1-R5, run via
                ``python -m iotml.analysis lint`` (exit 1 on findings).
- ``lockcheck`` runtime lock-order & race detector: an instrumented
                ``threading.Lock``/``RLock`` wrapper that records the
                per-thread lock-acquisition graph, fails on cycles
                (deadlock potential), flags locks held across blocking
                I/O, and tags unguarded mutations of registered shared
                state from non-owner threads.  Enable for a pytest run
                with ``IOTML_LOCKCHECK=1`` or
                ``-p iotml.analysis.pytest_plugin``.
- the C++ edge is covered by TSan/ASan build targets instead
  (``make -C iotml/cpp sanitize``).

See ARCHITECTURE.md §analysis for the rule table, how to add a rule, and
how to suppress a finding with justification.
"""

from .lint import Finding, RULES, lint_paths  # noqa: F401
