"""``python -m iotml.analysis`` — run the project checkers.

    python -m iotml.analysis lint [PATH ...] [--rule R2 --rule R4]
    python -m iotml.analysis protocol      # wire-protocol conformance
    python -m iotml.analysis tracecheck    # JAX trace discipline
    python -m iotml.analysis drift         # registry drift
    python -m iotml.analysis lockorder     # static lock-order edges
    python -m iotml.analysis all [PATH ...]
    python -m iotml.analysis rules

Every verb exits 1 when any finding survives (0 on a clean tree),
printing ``path:line: RULE message`` per finding — the format editors
and CI annotate from.  ``all`` runs lint + protocol + tracecheck +
drift over ONE shared parse of the tree (each file is read and parsed
exactly once; the summary reports wall time and files parsed).
``lockorder`` prints the statically-extracted acquire-order edges and
fails only on a static cycle.
"""

from __future__ import annotations

import argparse
import sys
import time

from .lint import RULES, default_root, lint_paths
from .program import Program


def _summary(label: str, n_findings: int, program: Program,
             t0: float, quiet: bool) -> None:
    if quiet:
        return
    dt = time.monotonic() - t0
    print(f"iotml.analysis {label}: {n_findings} finding(s), "
          f"{program.parsed()} file(s) parsed once, {dt:.2f}s wall",
          file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.analysis",
        description="concurrency & protocol-invariant checkers")
    sub = ap.add_subparsers(dest="cmd")

    lp = sub.add_parser("lint", help="run the AST lint pass (R1-R15)")
    lp.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the iotml package)")
    lp.add_argument("--rule", action="append", dest="rules", metavar="RN",
                    choices=sorted(RULES),
                    help="restrict to specific rules (repeatable)")
    lp.add_argument("--quiet", action="store_true",
                    help="suppress the summary line")

    for verb, help_ in (
            ("protocol", "wire-protocol conformance (P1-P7): server/"
                         "client/cluster/C++ symmetry"),
            ("tracecheck", "JAX trace discipline (T1-T4): recompile & "
                           "host-sync hazards"),
            ("drift", "registry drift (D1-D4): env knobs, metric "
                      "labels, faultpoints, doc rows"),
            ("lockorder", "static lock-order extraction: print edges, "
                          "fail on a static cycle"),
            ("all", "lint + protocol + tracecheck + drift over one "
                    "shared parse")):
        vp = sub.add_parser(verb, help=help_)
        vp.add_argument("paths", nargs="*",
                        help="files/dirs (default: the iotml package)")
        vp.add_argument("--quiet", action="store_true",
                        help="suppress the summary line")

    sub.add_parser("rules", help="print the rule table")

    args = ap.parse_args(argv)
    if args.cmd == "rules":
        from .drift import PASS_RULES as D_RULES
        from .protocol import PASS_RULES as P_RULES
        from .tracecheck import PASS_RULES as T_RULES
        for table in (RULES, P_RULES, T_RULES, D_RULES):
            for rid in sorted(table, key=lambda r: (r[0], int(r[1:]))):
                print(f"{rid}  {table[rid]}")
        return 0
    if args.cmd is None:
        ap.print_help()
        return 2

    t0 = time.monotonic()
    program = Program()
    findings = []

    if args.cmd == "lockorder":
        from . import lockorder
        root = args.paths[0] if args.paths else None
        edges = lockorder.analyze(root, program=program)
        for a, b, where in edges:
            print(f"{a} -> {b}  (at {where})")
        cycles = lockorder.cycles_among(edges)
        for cyc in cycles:
            print(f"STATIC CYCLE: {' -> '.join(cyc)}")
        if not args.quiet:
            dt = time.monotonic() - t0
            print(f"iotml.analysis lockorder: {len(edges)} edge(s), "
                  f"{len(cycles)} static cycle(s), "
                  f"{program.parsed()} file(s) parsed once, "
                  f"{dt:.2f}s wall", file=sys.stderr)
        return 1 if cycles else 0

    if args.cmd == "lint":
        paths = args.paths or [default_root()]
        findings = lint_paths(paths,
                              set(args.rules) if args.rules else None,
                              program=program)
    elif args.cmd == "protocol":
        from . import protocol
        root = args.paths[0] if args.paths else None
        findings = protocol.analyze(root, program=program)
    elif args.cmd == "tracecheck":
        from . import tracecheck
        if args.paths:
            findings = tracecheck.analyze(paths=args.paths,
                                          program=program)
        else:
            findings = tracecheck.analyze(program=program)
    elif args.cmd == "drift":
        from . import drift
        root = args.paths[0] if args.paths else None
        findings = drift.analyze(root, program=program)
    elif args.cmd == "all":
        from . import drift, protocol, tracecheck
        paths = args.paths or [default_root()]
        root = args.paths[0] if args.paths else None
        findings = list(lint_paths(paths, program=program))
        findings += protocol.analyze(root, program=program)
        findings += tracecheck.analyze(root, program=program)
        findings += drift.analyze(root, program=program)
        findings.sort(key=lambda f: (f.path, f.line, f.rule))

    for f in findings:
        print(f)
    _summary(args.cmd, len(findings), program, t0, args.quiet)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
