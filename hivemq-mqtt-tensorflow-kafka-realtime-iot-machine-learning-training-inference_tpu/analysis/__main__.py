"""``python -m iotml.analysis`` — run the project checkers.

    python -m iotml.analysis lint [PATH ...] [--rule R2 --rule R4]
    python -m iotml.analysis rules

``lint`` defaults to the iotml package tree and exits 1 when any finding
survives (0 on a clean tree), printing ``path:line: RULE message`` per
finding — the format editors and CI annotate from.
"""

from __future__ import annotations

import argparse
import sys

from .lint import RULES, default_root, lint_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.analysis",
        description="concurrency & protocol-invariant checkers")
    sub = ap.add_subparsers(dest="cmd")

    lp = sub.add_parser("lint", help="run the AST lint pass (R1-R5)")
    lp.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the iotml package)")
    lp.add_argument("--rule", action="append", dest="rules", metavar="RN",
                    choices=sorted(RULES),
                    help="restrict to specific rules (repeatable)")
    lp.add_argument("--quiet", action="store_true",
                    help="suppress the summary line")

    sub.add_parser("rules", help="print the rule table")

    args = ap.parse_args(argv)
    if args.cmd == "rules":
        for rid in sorted(RULES):
            print(f"{rid}  {RULES[rid]}")
        return 0
    if args.cmd != "lint":
        ap.print_help()
        return 2

    paths = args.paths or [default_root()]
    findings = lint_paths(paths, set(args.rules) if args.rules else None)
    for f in findings:
        print(f)
    if not args.quiet:
        print(f"iotml.analysis lint: {len(findings)} finding(s) over "
              f"{', '.join(paths)}", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
