"""Registry-drift pass: knobs, metrics, faultpoints, and doc rows.

Every cross-cutting registry in the platform is a contract between the
code that WRITES a name and the registry that DECLARES it — and each
has already drifted once in review history.  This pass closes the loop
statically:

D1  every ``IOTML_*`` environment read resolves to a declared config
    field (``config.env_key_names()``) or an entry in ``load_config``'s
    ``non_config`` set — an unregistered knob is a setting the config
    ladder (files, ``--section.field`` flags, precedence) silently
    cannot see.
D2  every metric usage matches its declaration: the metric attribute
    exists, every label keyword at a record site appears in the
    metric's ``DECLARED_METRIC_LABELS`` row (obs/metrics.py), and every
    declaration row names a real metric with keys drawn from
    ``ALLOWED_LABEL_KEYS``.  Labels multiply series; an undeclared
    label set is an unbudgeted cardinality dimension.
D3  every ``chaos.point("…")`` string exists in the chaos registry
    (``KNOWN_POINTS`` ∪ ``RUNNER_POINTS``), and ``POINT_ACTIONS`` keys
    that registry exactly — a typo'd faultpoint is a chaos scenario
    that silently never fires.
D4  every analysis rule (lint R*, protocol P*, trace T*, drift D*) has
    its ARCHITECTURE rule-table row — the doc table is the reviewer's
    contract for what the gate enforces.

Findings honour ``# lint-ok: D<n> <reason>`` suppressions (python
surfaces; the doc check D4 anchors in ARCHITECTURE.md itself).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .lint import Finding, default_root, suppressions_for
from .program import FileUnit, Program

PASS_RULES: Dict[str, str] = {
    "D1": "IOTML_* env read with no declared config field or "
          "non_config entry",
    "D2": "metric usage drifts from its declaration (unknown metric, "
          "undeclared label set, or stale declaration row)",
    "D3": "chaos faultpoint drift (unregistered point string or "
          "POINT_ACTIONS mismatch)",
    "D4": "analysis rule missing its ARCHITECTURE rule-table row",
}

_ENV_HELPERS = frozenset({"getenv", "_env", "_env_int", "_env_float",
                          "_env_bool", "_env_str", "_env_on"})
_RECORD_ATTRS = frozenset({"inc", "observe", "set", "time"})
_METRIC_MODULE_ALIASES = frozenset({"obs_metrics", "metrics", "_metrics"})


def _line_node(line: int):
    import types
    return types.SimpleNamespace(lineno=line, end_lineno=line)


# --------------------------------------------------------------------------
# D1: env knobs
# --------------------------------------------------------------------------

def declared_env_keys(config_path: Optional[str] = None) -> Set[str]:
    """IOTML_* keys the config ladder understands: the generated
    section_field keys plus ``load_config``'s ``non_config`` set
    (parsed from the source so the analyzer and the loader can never
    disagree about what the loader would reject)."""
    from .. import config as _config

    keys = set(_config.env_key_names())
    path = config_path or os.path.join(default_root(), "config.py")
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "non_config"
                        for t in node.targets):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    keys.add(sub.value)
    return keys


def _env_reads(tree: ast.Module) -> List[Tuple[str, int]]:
    """(key, line) for every constant IOTML_* environment read:
    ``*.get("IOTML_X", …)`` / ``environ["IOTML_X"]`` / ``os.getenv`` /
    ``_env*("IOTML_X")`` helper calls."""
    out: List[Tuple[str, int]] = []

    def const_key(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.startswith("IOTML_") \
                and len(node.value) > len("IOTML_"):
            return node.value
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.args:
            key = const_key(node.args[0])
            if key is None:
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("get",) and (
                    (isinstance(f.value, ast.Attribute)
                     and f.value.attr == "environ")
                    or (isinstance(f.value, ast.Name)
                        and f.value.id in ("env", "environ", "_env"))):
                out.append((key, node.lineno))
            elif isinstance(f, ast.Attribute) and f.attr == "getenv":
                out.append((key, node.lineno))
            elif isinstance(f, ast.Name) and f.id in _ENV_HELPERS:
                out.append((key, node.lineno))
        elif isinstance(node, ast.Subscript):
            base = node.value
            if (isinstance(base, ast.Attribute) and base.attr == "environ") \
                    or (isinstance(base, ast.Name)
                        and base.id in ("environ", "env")):
                key = const_key(node.slice)
                if key is not None:
                    out.append((key, node.lineno))
    return out


# --------------------------------------------------------------------------
# D2: metrics
# --------------------------------------------------------------------------

def _metric_decls(tree: ast.Module) -> Dict[str, Tuple[str, int]]:
    """var/attr name -> (metric_name, line) for every
    ``x = <registry>.counter|gauge|histogram("name", …)``."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        if not (isinstance(call.func, ast.Attribute)
                and call.func.attr in ("counter", "gauge", "histogram")
                and call.args
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = (call.args[0].value, node.lineno)
            elif isinstance(t, ast.Attribute):
                out[t.attr] = (call.args[0].value, node.lineno)
    return out


def _metric_uses(tree: ast.Module) -> List[Tuple[str, bool,
                                                 Tuple[str, ...], int]]:
    """(attr, via_metrics_module, label_keys, line) for every
    ``<recv>.<attr>.inc|observe|set|time(…)`` record site.  Dynamic
    ``**labels`` cannot be resolved statically and is skipped."""
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _RECORD_ATTRS):
            continue
        recv = node.func.value
        if not isinstance(recv, ast.Attribute):
            continue
        base = recv.value
        via_module = isinstance(base, ast.Name) \
            and base.id in _METRIC_MODULE_ALIASES
        via_self = isinstance(base, ast.Name) and base.id == "self"
        if not (via_module or via_self):
            continue
        keys = tuple(sorted(k.arg for k in node.keywords
                            if k.arg is not None))
        out.append((recv.attr, via_module, keys, node.lineno))
    return out


# --------------------------------------------------------------------------
# D3: chaos faultpoints
# --------------------------------------------------------------------------

def _chaos_points(tree: ast.Module) -> List[Tuple[str, int]]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "point" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            out.append((node.args[0].value, node.lineno))
    return out


# --------------------------------------------------------------------------
# the pass
# --------------------------------------------------------------------------

class _Drift:
    def __init__(self) -> None:
        self.findings: List[Finding] = []

    def emit(self, sup, path: str, rule: str, line: int,
             message: str) -> None:
        if sup is not None and sup.suppressed(rule, _line_node(line)):
            return
        self.findings.append(Finding(path, line, rule, message))


def analyze(root: Optional[str] = None, *,
            paths: Optional[Iterable[str]] = None,
            program: Optional[Program] = None,
            architecture: Optional[str] = None) -> List[Finding]:
    """Run the registry-drift pass over the package tree (or explicit
    ``paths`` for fixture corpora — registries still come from the real
    tree, so a fixture exercises the same contracts production does)."""
    from .protocol import FAULTS_REL, chaos_registry

    base = root if root is not None else default_root()
    program = program if program is not None else Program()
    out = _Drift()

    env_declared = declared_env_keys(os.path.join(base, "config.py"))

    metrics_path = os.path.join(base, "obs", "metrics.py")
    metrics_unit = program.unit(metrics_path, rel="obs/metrics.py")
    decls: Dict[str, Tuple[str, int]] = {}
    from ..obs import metrics as _obs_metrics
    allowed_labels = frozenset(getattr(_obs_metrics, "ALLOWED_LABEL_KEYS",
                                       frozenset()))
    declared_labels: Dict[str, tuple] = dict(
        getattr(_obs_metrics, "DECLARED_METRIC_LABELS", {}))
    label_table_line = 0
    if metrics_unit.tree is not None:
        for node in ast.walk(metrics_unit.tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name)
                    and t.id == "DECLARED_METRIC_LABELS"
                    for t in node.targets):
                label_table_line = node.lineno

    faults_unit = program.unit(os.path.join(base, FAULTS_REL),
                               rel=FAULTS_REL)
    registry = chaos_registry(faults_unit)
    known = set(registry.get("KNOWN_POINTS", {})) \
        | set(registry.get("RUNNER_POINTS", {}))
    actions = registry.get("POINT_ACTIONS", {})

    units = program.units(paths if paths is not None else [base])
    for unit in units:
        if unit.tree is None:
            continue
        sup = suppressions_for(unit)
        for key, line in _env_reads(unit.tree):
            if key not in env_declared:
                out.emit(sup, unit.path, "D1", line,
                         f"env read of {key!r} matches no declared "
                         f"config field and no non_config entry: the "
                         f"config ladder (files, flags, precedence) "
                         f"cannot see this knob")
        decls.update(_metric_decls(unit.tree))
        for point, line in _chaos_points(unit.tree):
            if point not in known:
                out.emit(sup, unit.path, "D3", line,
                         f"faultpoint {point!r} is not in the chaos "
                         f"registry (KNOWN_POINTS/RUNNER_POINTS, "
                         f"{FAULTS_REL}): a scenario naming it would "
                         f"never fire")

    # second sweep for metric uses: declarations from ALL files must be
    # in hand first (chaos_injected lives in chaos/faults.py, the _m_*
    # family on mqtt instances)
    for unit in units:
        if unit.tree is None:
            continue
        sup = suppressions_for(unit)
        for attr, via_module, keys, line in _metric_uses(unit.tree):
            if attr not in decls:
                if via_module:
                    out.emit(sup, unit.path, "D2", line,
                             f"metric {attr!r} recorded here has no "
                             f"declaration (no <registry>.counter/"
                             f"gauge/histogram assignment found)")
                continue
            declared = declared_labels.get(attr, ())
            extra = set(keys) - set(declared)
            if extra:
                out.emit(sup, unit.path, "D2", line,
                         f"metric {attr!r} recorded with label keys "
                         f"{sorted(extra)} not in its "
                         f"DECLARED_METRIC_LABELS row "
                         f"(obs/metrics.py declares "
                         f"{sorted(declared) or 'no labels'}): an "
                         f"undeclared label is an unbudgeted "
                         f"cardinality dimension")

    # declaration-table hygiene (anchored in obs/metrics.py).  Stale-row
    # detection needs the WHOLE tree's declarations in hand, so it only
    # runs in tree scope — a fixture-scoped ``paths`` run would see
    # every real row as undeclared.
    msup = suppressions_for(metrics_unit)
    for attr, lbls in sorted(declared_labels.items()):
        if paths is None and attr not in decls:
            out.emit(msup, metrics_unit.path, "D2", label_table_line,
                     f"DECLARED_METRIC_LABELS row {attr!r} names no "
                     f"declared metric (stale row)")
        bad = set(lbls) - allowed_labels
        if bad:
            out.emit(msup, metrics_unit.path, "D2", label_table_line,
                     f"DECLARED_METRIC_LABELS row {attr!r} uses label "
                     f"keys {sorted(bad)} outside ALLOWED_LABEL_KEYS")

    # POINT_ACTIONS must mirror the point registry exactly
    fsup = suppressions_for(faults_unit)
    for point in sorted(set(actions) - known):
        out.emit(fsup, faults_unit.path, "D3",
                 actions.get(point, 0),
                 f"POINT_ACTIONS entry {point!r} is not a registered "
                 f"faultpoint")
    for point in sorted(known - set(actions)):
        line = registry.get("KNOWN_POINTS", {}).get(
            point, registry.get("RUNNER_POINTS", {}).get(point, 0))
        out.emit(fsup, faults_unit.path, "D3", line,
                 f"faultpoint {point!r} has no POINT_ACTIONS row: no "
                 f"action is legal at it, so scenarios naming it are "
                 f"rejected at parse")

    # D4: every analysis rule has its ARCHITECTURE rule-table row
    arch = architecture if architecture is not None \
        else os.path.join(os.path.dirname(base), "ARCHITECTURE.md")
    if os.path.exists(arch):
        with open(arch, "r", encoding="utf-8") as f:
            doc = f.read()
        from .lint import RULES as _LINT_RULES
        from .protocol import PASS_RULES as _P_RULES
        from .tracecheck import PASS_RULES as _T_RULES
        all_rules = {}
        all_rules.update(_LINT_RULES)
        all_rules.update(_P_RULES)
        all_rules.update(_T_RULES)
        all_rules.update(PASS_RULES)
        for rule_id in sorted(all_rules,
                              key=lambda r: (r[0], int(r[1:]))):
            if not re.search(rf"^\|\s*{rule_id}\b", doc, re.M):
                out.findings.append(Finding(
                    arch, 1, "D4",
                    f"analysis rule {rule_id} ({all_rules[rule_id]!r}) "
                    f"has no ARCHITECTURE rule-table row"))

    return sorted(out.findings, key=lambda f: (f.path, f.line, f.rule))
