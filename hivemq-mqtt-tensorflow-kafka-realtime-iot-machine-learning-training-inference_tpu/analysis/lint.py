"""AST lint pass — repo-specific concurrency & protocol invariants.

Rules (see ARCHITECTURE.md §analysis for the full table):

  R1  no non-monotonic clocks: ``time.time()`` is forbidden in the
      stream/mqtt wire, broker and replica modules — deadlines and
      timeouts there must use ``time.monotonic()`` (a wall-clock step,
      e.g. NTP, must never extend or collapse a protocol timeout).
      Legitimate wall-clock reads (record timestamps, uptime stats)
      carry ``# wallclock-ok: <reason>``.
  R2  every ``KafkaWireBroker._request`` call site must name an API
      from the IDEMPOTENT_APIS allowlist *by constant name* or carry a
      ``# retry-ok: <reason>`` justification acknowledging the
      non-idempotent delivery contract (the client auto-retries only
      allowlisted APIs after a reconnect; everything else surfaces
      ConnectionError — kafka_wire.py).
  R3  no bare ``.acquire()`` on locks: context-manager (``with``) only,
      so the runtime lockcheck sees every hold and release is
      exception-safe.
  R4  no blocking call (``recv``/``recv_into``/``recv_exact``/
      ``accept``/``sleep``/``select``) while a lock is held — checked
      by a call-graph walk within the module, so a helper that blocks
      three frames down is still caught.
  R5  engine-owned topics (``SENSOR_DATA_S_AVRO*``) may only be
      produced from ``streamproc/`` — the broker enforces this at
      runtime (Broker.restrict_topic); the lint closes it by
      construction.
  R6  metric families and trace span/stage names follow the lowercase
      snake_case convention (framework-owned names must match
      ``iotml_[a-z0-9_]+`` exactly), and span recording
      (``ctx.mark``/``ctx.close``/``tracing.start``/``tracing.flush``)
      must not happen while a lock is held — the trace collector is
      lock-free by contract (checked with R4's call-graph walk).
  R7  chaos faultpoint discipline: ``chaos.point()`` shims and
      ``iotml.chaos`` imports may appear only in the allowlisted
      production modules (CHAOS_ALLOWED_MODULES), and those modules may
      import nothing from ``iotml.chaos`` except the shim module
      ``faults`` — scenario/runner code (and its heavyweight deps) must
      never leak into hot paths, and new injection sites are a reviewed
      allowlist change, not a drive-by.
  R8  supervised-thread discipline: every ``threading.Thread(...)``
      constructed outside ``iotml/supervise/`` must be ``daemon=True``,
      carry an explicit ``name=``, and be registered with the
      supervisor registry (wrapped in ``register_thread(...)``) — the
      self-healing runtime can only supervise what it can enumerate,
      and a fire-and-forget anonymous thread is exactly the erosion
      the supervise subsystem exists to stop.
  R9  durable-store write discipline: outside ``iotml/store/``, no
      ``os.fsync`` at all, and no ``open()``/``os.open()`` whose
      arguments name a store path (identifiers like ``store_dir`` /
      ``store_path`` / segment paths) — every byte written under a
      store directory goes through ``store.segment.SegmentWriter``, so
      the durability promises (fsync accounting, torn-tail recovery
      semantics, atomic-rename publication) are made in exactly one
      place.  Extended to the REMOTE tier: segment blob uploads
      (``upload_segment``), ``.stage`` intent markers, ``tiered/``
      blob names and the tier manifest are ``store.remote.RemoteTier``'s
      alone — a foreign manifest write could commit torn blobs, which
      the stage → blobs → manifest-commit protocol exists to forbid.
  R11 model-registry write discipline (R9's story for model
      artifacts): outside ``iotml/mlops/``, no ``open()``/``os.open()``
      or ``atomic_write()`` whose arguments name a registry path
      (``registry_dir`` / ``registry_root`` / ``version_dir`` /
      ``artifact_path`` / ``manifest.json``) — every byte under a
      registry goes through ``mlops.registry.ModelRegistry`` (the one
      writer), or the manifest-as-commit-marker recovery contract (a
      version is committed IFF its manifest parses) silently breaks.
  R12 compaction / twin-changelog write discipline: the ``CAR_TWIN``
      changelog has ONE writer (``iotml/twin/``'s TwinService — a
      foreign producer corrupts every rebuild), and the segment
      compaction rewrite machinery (``compact_log`` / ``sweep_cleaned``
      / any write on a ``.cleaned`` rewrite path) is
      ``iotml/store/``-internal — everyone else triggers compaction
      through ``Broker.run_compaction`` so the swap protocol, the
      broker lock and the crash-safety story live in exactly one place.
  R15 ISR / quorum-HWM mutation discipline (R9/R11/R12's story for
      replicated durability): the in-sync-replica set and the quorum
      high-water mark are mutated ONLY inside ``iotml/replication/``
      (``register_follower`` / ``unregister_follower`` /
      ``evict_stale``), and the two wire-ingress calls —
      ``observe_fetch`` (follower positions entering the ISR) and
      ``wait_replicated`` (the acks=all quorum wait) — may additionally
      appear in ``stream/kafka_wire.py``, where the protocol lands.
      A foreign mutation would let acks=all ack records a failover can
      lose (the exact loss the quorum exists to rule out).

Suppression: append ``# lint-ok: RN <reason>`` to the flagged line (for
R4, to the ``with`` line holding the lock).  A suppression WITHOUT a
reason is itself a finding — justifications are the point.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .program import FileUnit, Program, iter_py_files

# APIs the wire client may auto-retry after a reconnect: a duplicate of
# any of these is invisible (reads) or a no-op (liveness signal).  Kept
# in sync with kafka_wire.IDEMPOTENT_APIS by tests/test_analysis.py.
IDEMPOTENT_API_NAMES = frozenset({
    "FETCH", "RAW_FETCH", "METADATA", "LIST_OFFSETS", "OFFSET_FETCH",
    "API_VERSIONS", "SASL_HANDSHAKE", "HEARTBEAT", "FIND_COORDINATOR",
})

# R5: topics written exclusively by the stream-proc engine (the AVRO leg
# and everything derived from it) — prefix match, like the broker's
# runtime restriction.
ENGINE_OWNED_TOPIC_PREFIXES = ("SENSOR_DATA_S_AVRO",)

# R4: calls that park the thread.  Send-side calls (sendall) are
# deliberately not listed: writing under a write-lock is the normal way
# to keep frames atomic, and the kernel buffer usually absorbs it.
BLOCKING_CALLS = frozenset({
    "recv", "recv_into", "recv_exact", "accept", "sleep", "select",
})

# R1 applies to modules under these path segments (the wire/broker/
# replica/timeout paths); the rest of the tree may use wall clocks.
R1_PATH_SEGMENTS = ("stream", "mqtt")

# R7: the only production modules that may compile in chaos faultpoints
# (matched on the trailing (package, file) of the path), and the only
# chaos module they may import.  Files under an iotml/chaos/ directory
# are the subsystem itself and exempt.
CHAOS_ALLOWED_MODULES = frozenset({
    ("stream", "kafka_wire.py"), ("stream", "broker.py"),
    ("stream", "replica.py"), ("mqtt", "broker.py"),
    ("serve", "scorer.py"), ("train", "live.py"),
    ("mlops", "checkpoint.py"), ("mlops", "registry.py"),
    ("store", "compact.py"), ("online", "learner.py"),
    ("store", "remote.py"),
})
CHAOS_SHIM_MODULE = "faults"
# Drill-harness modules outside chaos/supervise: live-drill peers of
# chaos.runner (they arm engines / reuse its Invariant machinery against
# real platforms), exempt from R7 exactly like the supervise drills.
CHAOS_HARNESS_MODULES = frozenset({
    ("mlops", "drill.py"), ("mlops", "__main__.py"),
    ("twin", "drill.py"), ("twin", "__main__.py"),
    ("online", "drill.py"), ("online", "__main__.py"),
    ("replication", "drill.py"), ("replication", "__main__.py"),
    ("obs", "drill.py"), ("obs", "__main__.py"),
    ("gateway", "drill.py"), ("gateway", "__main__.py"),
})

# R6 (naming): metric families and span/stage names are lowercase
# snake_case; framework-owned names (iotml-prefixed) must follow the
# full `iotml_[a-z0-9_]+` convention.  Reference-parity families
# (mqtt_*, kafka_extension_*, agent_*, com_hivemq_* — the names the
# reference's Grafana dashboards chart) are lowercase snake too, so
# they pass; what the rule rejects is uppercase, dashes, dots and a
# malformed iotml prefix — names Prometheus relabeling and the span
# CLI's aggregation would silently fork on.
_METRIC_FACTORY_CALLS = frozenset({"counter", "gauge", "histogram"})
_SPAN_LITERAL_CALLS = frozenset({"mark", "close"})  # TraceContext methods
_TRACING_MODULE_CALLS = frozenset({"start", "flush", "liveness"})
_SNAKE_NAME_RE = re.compile(r"[a-z][a-z0-9_]*\Z")
_IOTML_NAME_RE = re.compile(r"iotml_[a-z0-9_]+\Z")
# R6 label vocabulary (ISSUE 13): metric labels at .inc/.set/.observe/
# .time call sites must come from the CLOSED key set mirrored in
# obs.metrics.ALLOWED_LABEL_KEYS.  Labels multiply series — one key
# drawn from an unbounded domain (a car id, a trace id, an offset)
# turns a fixed-cost scrape into an unbounded allocation, so a new
# label key is a reviewed vocabulary change, not a drive-by.
_METRIC_RECORD_CALLS = frozenset({"inc", "observe", "set", "time"})
_ALLOWED_METRIC_LABELS = frozenset({
    "stage", "topic", "partition", "group", "phase", "loop", "process",
    "component", "detector", "action", "fault", "source", "outcome",
    "unit", "le", "slo", "window", "shard", "route", "code",
})

RULES: Dict[str, str] = {
    "R1": "non-monotonic clock (time.time) in wire/broker/replica code; "
          "use time.monotonic() or annotate '# wallclock-ok: <reason>'",
    "R2": "_request call site must name an IDEMPOTENT_APIS constant or "
          "carry '# retry-ok: <reason>'",
    "R3": "bare Lock.acquire(); hold locks via 'with' only",
    "R4": "blocking call while a lock is held (module call-graph walk)",
    "R5": "engine-owned topic produced outside streamproc/",
    "R6": "metric/span name violates the iotml_[a-z0-9_]+ naming "
          "convention, or a span is recorded while a lock is held",
    "R7": "chaos shim (chaos.point / iotml.chaos import) outside the "
          "faultpoint allowlist, or a production import of a chaos "
          "module other than the shim (iotml.chaos.faults)",
    "R8": "threading.Thread outside iotml/supervise/ must be daemon, "
          "named, and wrapped in register_thread(...) (supervisor "
          "registry)",
    "R9": "naked store-dir write (os.fsync, or open()/os.open() on a "
          "store path) outside iotml/store/: all store-dir bytes go "
          "through SegmentWriter; remote-tier writes (upload_segment, "
          ".stage markers, tiered/ blobs, the tier manifest) go "
          "through RemoteTier",
    "R10": "direct broker-instance addressing outside iotml/cluster/ "
           "(ShardBroker(...) construction, or subscripting a "
           "controller's .brokers/.servers/.serving/.replicas): clients "
           "route via PartitionMap / ClusterClient",
    "R11": "naked model-registry write (open()/os.open()/atomic_write() "
           "on a registry path) outside iotml/mlops/: all registry "
           "bytes go through ModelRegistry (manifest-as-commit-marker "
           "recovery depends on the one-writer discipline)",
    "R12": "twin-changelog produce outside iotml/twin/ (CAR_TWIN has "
           "one writer: TwinService), or compaction rewrite machinery "
           "(compact_log / sweep_cleaned / a write on a .cleaned path) "
           "outside iotml/store/: compact via Broker.run_compaction",
    "R13": "in-place .set_params(...) on a serving scorer outside "
           "iotml/mlops/ & iotml/online/: model updates go THROUGH "
           "the registry (versioning, rollback gate, swap metrics) — "
           "a direct weight poke is an unversioned deploy nothing can "
           "roll back",
    "R15": "ISR-set / quorum-HWM mutation (register_follower / "
           "unregister_follower / evict_stale) outside "
           "iotml/replication/, or the wire-ingress calls "
           "(observe_fetch / wait_replicated) outside "
           "iotml/replication/ + stream/kafka_wire.py: a foreign "
           "mutation lets acks=all ack records a failover can lose",
    "R14": "frame parsing OR encoding (the [len|crc|attrs|offset|ts|"
           "key|value|headers] layout: scan_records / iter_frames / "
           "decode_record / encode_record, the >IBqqi head struct, or "
           "a direct iotml_frames_* native-symbol call) outside "
           "iotml/store/ + iotml/ops/framing.py (+ stream/native.py "
           "for the ctypes binding): the segmented log's frame is the "
           "ONE wire→disk→host contract with ONE codec — consume raw "
           "batches via Broker.fetch_raw + FrameDecoder, produce them "
           "via ops.framing helpers / RawBatchProducer",
    "R16": "direct TwinTable access outside iotml/twin/ + "
           "iotml/gateway/ (TwinTable(...) construction, "
           ".apply_changelog(...), or reaching through a service's "
           ".table): the materialised twin has two legal holders — "
           "TwinService and the gateway's standby/serving plane; "
           "everyone else queries via TwinService / TwinFeatureStore / "
           "GatewayClient, or a foreign mutation forks state the "
           "changelog can never rebuild",
}

# R14: the segment frame codec's entry points, and the frame-head
# struct format that marks a hand-rolled parser.  Same conservative
# name-matching as R9/R11 (a false positive justifies itself with a
# suppression).
_FRAME_PARSER_CALLS = frozenset({"scan_records", "iter_frames",
                                 "decode_record", "encode_record"})
# R14 write-path extension (ISSUE 12): the frame engine's native
# symbols may be bound/called ONLY by iotml/stream/native.py (the one
# ctypes binding) and the exempt frame owners — a direct ctypes call
# elsewhere is a second frame codec in disguise.
_FRAME_NATIVE_SYMBOLS = frozenset({
    "iotml_frames_decode_columnar", "iotml_frames_encode_columnar",
    "iotml_frames_encode_values", "iotml_frames_restamp",
    "iotml_frames_validate"})
_FRAME_HEAD_RE = re.compile(r"IBqqi")
_STRUCT_CALLS = frozenset({"Struct", "pack", "unpack", "unpack_from",
                           "pack_into"})

# R12: the compacted twin-changelog topics whose produce is confined to
# iotml/twin/, the store-internal compaction entry points, and the
# rewrite-tmp path marker (same conservative name-matching as R9/R11).
_TWIN_CHANGELOG_TOPICS = frozenset({"CAR_TWIN"})
# R12 extension (ISSUE 17): the telemetry plane's log topics have one
# writer family too — the obs package (FleetCollector's snapshot
# changelog, TsdbAppender's chunk stream, SloEngine's alert
# transitions).  A foreign producer forks the very history the SLO
# engine alerts FROM.
_OBS_TELEMETRY_TOPICS = frozenset({
    "_IOTML_METRICS", "_IOTML_TSDB", "_IOTML_ALERTS"})
_OBS_TOPIC_BY_NAME = {
    "METRICS_TOPIC": "_IOTML_METRICS",
    "TSDB_TOPIC": "_IOTML_TSDB",
    "ALERTS_TOPIC": "_IOTML_ALERTS"}
_COMPACT_WRITE_CALLS = frozenset({"compact_log", "sweep_cleaned"})
_CLEANED_PATH_RE = re.compile(r"\.cleaned|CLEANED_SUFFIX")

# R15: the replication state's mutating entry points.  `observe_fetch`
# is additionally allowed in stream/kafka_wire.py (the wire server is
# where follower fetch positions enter the system); everything else is
# iotml/replication/-internal.  Same conservative name-matching as
# R9/R11/R12 — a false positive justifies itself with a suppression.
_ISR_MUTATION_CALLS = frozenset({
    "register_follower", "unregister_follower", "evict_stale"})
_ISR_INGRESS_CALLS = frozenset({"observe_fetch", "wait_replicated"})

# R10: the cluster-internal collections whose per-instance subscripting
# outside the package bypasses PartitionMap routing (and with it the
# NOT_LEADER + epoch-fencing invariants).  The chaos/supervise drill
# harnesses are exempt — proving failover requires touching the victim.
_R10_COLLECTIONS = frozenset({"brokers", "servers", "serving", "replicas"})

# R16: the TwinTable surface reachable through a service's `.table`
# attribute.  `apply_changelog` is caught at the call site, so the
# attribute-chain check covers the rest of the table API (same
# conservative name-matching as R9/R11/R12 — a false positive
# justifies itself with a suppression).
_TWIN_TABLE_ATTRS = frozenset({"apply", "snapshot", "resume_offsets",
                               "twins", "cars", "get"})

# R9: identifier substrings that mark an open() argument as a store
# path.  Conservative by construction (names, not data flow) — matching
# errs toward flagging, and a false positive justifies itself with a
# suppression, the lint's usual direction.
_STORE_PATH_NAME_RE = re.compile(
    r"store_dir|store_path|storedir|segment_path|\.slog\b", re.IGNORECASE)

# R9 (tier extension): remote-tier write surfaces.  Blob names under
# the remote "tiered/" prefix, ".stage" intent markers and the remote
# tier manifest are written ONLY by store.remote.RemoteTier — a foreign
# writer could commit a manifest entry for torn blobs, the exact state
# the stage -> blobs -> manifest-commit protocol exists to rule out.
# Same conservative name-matching as the store-path regex above.
_TIER_PATH_NAME_RE = re.compile(
    r"tiered/|\.stage\b|remote_tier|tier_manifest", re.IGNORECASE)
#: RemoteTier's mutating entry points — calling one outside the store
#: package is a remote-tier write regardless of argument spelling.
_TIER_WRITE_CALLS = frozenset({"upload_segment"})

# R11: identifier substrings marking an open()/atomic_write() argument
# as a model-registry path.  Same conservative name-based matching as
# R9 (flagging errs toward a justified suppression, not silence).
_REGISTRY_PATH_NAME_RE = re.compile(
    r"registry_dir|registry_root|version_dir|artifact_path"
    r"|manifest\.json|model_registry", re.IGNORECASE)

# [A-Z]\d+ not R\d: two-digit rules exist since R10, and the
# single-digit form silently failed to parse their suppressions (the
# lint-ok line then neither suppressed nor flagged-as-reasonless — it
# just lied); the letter class covers the whole-program passes' finding
# families too (P* protocol, T* tracecheck, D* drift) so one
# suppression mechanism serves every pass
_SUPPRESS_RE = re.compile(r"#\s*lint-ok:\s*([A-Z]\d+)\b[ \t]*(.*)")
_RETRY_OK_RE = re.compile(r"#\s*retry-ok:[ \t]*(.*)")
_WALLCLOCK_RE = re.compile(r"#\s*wallclock-ok:[ \t]*(.*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class _Suppressions:
    """Per-file suppression comments, and the findings malformed ones
    produce (a suppression without a reason is flagged, not honored)."""

    def __init__(self, path: str, source: str):
        self.by_rule: Dict[str, Set[int]] = {}
        self.retry_ok: Set[int] = set()
        self.wallclock_ok: Set[int] = set()
        self.findings: List[Finding] = []
        self.comment_only: Set[int] = set()
        for i, text in enumerate(source.splitlines(), start=1):
            if text.lstrip().startswith("#"):
                self.comment_only.add(i)
            m = _SUPPRESS_RE.search(text)
            if m:
                rule, reason = m.group(1), m.group(2).strip()
                if not reason:
                    self.findings.append(Finding(
                        path, i, rule,
                        "suppression without justification: write "
                        f"'# lint-ok: {rule} <why this is safe>'"))
                else:
                    self.by_rule.setdefault(rule, set()).add(i)
            m = _RETRY_OK_RE.search(text)
            if m:
                if not m.group(1).strip():
                    self.findings.append(Finding(
                        path, i, "R2",
                        "retry-ok without justification: write "
                        "'# retry-ok: <redelivery story>'"))
                else:
                    self.retry_ok.add(i)
            m = _WALLCLOCK_RE.search(text)
            if m:
                if not m.group(1).strip():
                    self.findings.append(Finding(
                        path, i, "R1",
                        "wallclock-ok without justification: write "
                        "'# wallclock-ok: <why wall time is correct>'"))
                else:
                    self.wallclock_ok.add(i)

    def _effective_lines(self, node: ast.AST) -> Iterable[int]:
        """The node's own span, plus the contiguous pure-comment block
        immediately above it — where multi-line justifications live."""
        first = node.lineno
        last = getattr(node, "end_lineno", first)
        lines = list(range(first, last + 1))
        ln = first - 1
        while ln in self.comment_only:
            lines.append(ln)
            ln -= 1
        return lines

    def suppressed(self, rule: str, node: ast.AST) -> bool:
        marked = self.by_rule.get(rule, set())
        if rule == "R1":
            marked = marked | self.wallclock_ok
        return any(ln in marked for ln in self._effective_lines(node))

    def retry_justified(self, node: ast.AST) -> bool:
        return any(ln in self.retry_ok
                   for ln in self._effective_lines(node))


def _call_name(node: ast.Call) -> Optional[str]:
    """Terminal name of the called thing: foo() → foo, a.b.foo() → foo."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_time_time(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "time"
            and isinstance(f.value, ast.Name) and f.value.id == "time")


def _is_thread_ctor(node: ast.Call) -> bool:
    """``<any name>.Thread(...)`` or a bare imported ``Thread(...)``.
    Matching ANY module name (not just ``threading``) closes the
    ``import threading as t; t.Thread(...)`` evasion — conservative in
    the lint's usual direction: flag, and let a false positive justify
    itself with a suppression."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr == "Thread" and isinstance(f.value, ast.Name)
    return isinstance(f, ast.Name) and f.id == "Thread"


def _lockish_name(expr: ast.expr) -> Optional[str]:
    """Terminal identifier of a with-item if it names a lock."""
    e = expr
    if isinstance(e, ast.Call):  # e.g. broker.producer_grant(tok) — not a lock
        return None
    name = None
    if isinstance(e, ast.Attribute):
        name = e.attr
    elif isinstance(e, ast.Name):
        name = e.id
    if name is not None and "lock" in name.lower():
        return name
    return None


def _str_arg0(node: ast.Call) -> Optional[str]:
    """First positional argument when it is a string literal."""
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _is_tracing_module_call(node: ast.Call) -> bool:
    """``tracing.start(...)`` / ``tracing.flush()`` style module calls."""
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in _TRACING_MODULE_CALLS
            and isinstance(f.value, ast.Name) and f.value.id == "tracing")


def _span_call_reason(node: ast.Call, name: Optional[str]) -> Optional[str]:
    """The R6 under-lock predicate: 'records a span (...)' or None.

    Span-recording shapes: a TraceContext method with a string-literal
    stage (``ctx.mark("decode")``, ``ctx.close("score")``) or a call on
    the tracing module (``tracing.start(...)``, ``tracing.flush()``).
    The literal-argument requirement keeps generic ``.close()`` /
    ``.mark()`` methods of unrelated objects out of the rule."""
    if name in _SPAN_LITERAL_CALLS and isinstance(node.func, ast.Attribute) \
            and _str_arg0(node) is not None:
        return f"records a span ({name}({_str_arg0(node)!r}))"
    if _is_tracing_module_call(node):
        return f"records a span (tracing.{node.func.attr}())"
    return None


# --------------------------------------------------------------- R4 engine
class _ModuleCallGraph:
    """Module-local may-block analysis.

    Functions are indexed by bare name (methods too — self-dispatch within
    a module resolves by name; cross-class collisions make the analysis
    conservative, which errs toward flagging).  A function "may block" if
    its body contains a BLOCKING_CALLS call or a call to a module function
    that (transitively) may block.
    """

    def __init__(self, tree: ast.Module):
        self.bodies: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # first definition wins; duplicates would only make the
                # result depend on dict order
                self.bodies.setdefault(node.name, node)
        # one memo per predicate kind: "block" (R4) and "span" (R6)
        self._memos: Dict[str, Dict[str, Optional[str]]] = {
            "block": {}, "span": {}}

    @staticmethod
    def _block_pred(node: ast.Call, name: Optional[str]) -> Optional[str]:
        if name in BLOCKING_CALLS:
            return f"calls blocking {name}()"
        return None

    @staticmethod
    def _span_pred(node: ast.Call, name: Optional[str]) -> Optional[str]:
        return _span_call_reason(node, name)

    def blocking_reason(self, func_name: str) -> Optional[str]:
        """None, or 'calls recv (net.py-style helper chain)' style text."""
        return self._reason(func_name, "block", self._block_pred)

    def span_reason(self, func_name: str) -> Optional[str]:
        """None, or the span-recording chain — the same transitive walk
        R4 uses, with the R6 predicate."""
        return self._reason(func_name, "span", self._span_pred)

    def _reason(self, func_name: str, kind: str, pred,
                _visiting: Optional[Set[str]] = None) -> Optional[str]:
        memo = self._memos[kind]
        if func_name in memo:
            return memo[func_name]
        body = self.bodies.get(func_name)
        if body is None:
            return None
        _visiting = _visiting or set()
        if func_name in _visiting:
            return None  # recursion: already being decided
        _visiting.add(func_name)
        memo[func_name] = None  # break cycles pessimistically-clean
        reason = None
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            direct = pred(node, name)
            if direct:
                reason = f"{func_name}() {direct}"
                break
            if name and name != func_name and name in self.bodies:
                inner = self._reason(name, kind, pred, _visiting)
                if inner:
                    reason = f"{func_name}() -> {inner}"
                    break
        memo[func_name] = reason
        return reason


# ----------------------------------------------------------------- checker
class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, tree: ast.Module,
                 sup: _Suppressions, rules: Set[str],
                 graph: Optional[_ModuleCallGraph] = None):
        self.path = path
        self.rel = rel
        self.sup = sup
        self.rules = rules
        self.findings: List[Finding] = list(sup.findings)
        if graph is None and rules & {"R4", "R6"}:
            graph = _ModuleCallGraph(tree)
        self.graph = graph
        parts = rel.replace(os.sep, "/").split("/")
        self.r1_scoped = any(seg in parts for seg in R1_PATH_SEGMENTS)
        self.in_streamproc = "streamproc" in parts
        # R7 scoping: the chaos package itself is exempt, and so is the
        # supervise package — its live drills are the threaded peer of
        # chaos.runner (harness code arming engines against real
        # platforms), not a hot path
        self.in_chaos = "chaos" in parts or "supervise" in parts or (
            len(parts) >= 2 and (parts[-2], parts[-1])
            in CHAOS_HARNESS_MODULES)
        self.chaos_allowed = self.in_chaos or (
            len(parts) >= 2 and (parts[-2], parts[-1])
            in CHAOS_ALLOWED_MODULES)
        # R8 scoping: the supervise package OWNS thread lifecycles (the
        # registry itself, the monitor) and is exempt from wrapping
        self.in_supervise = "supervise" in parts
        # R10 scoping: the cluster package owns broker instances; the
        # chaos/supervise drill harnesses may address victims directly
        self.r10_exempt = "cluster" in parts or self.in_chaos
        # R9 scoping: the store package OWNS the bytes (SegmentWriter,
        # atomic_write) and is the one place fsync may appear
        self.in_store = "store" in parts
        # R14 scoping: the store package plus ops/framing.py (the frame
        # contract's stream-layer half, whose helpers delegate to the
        # store codec) are the only frame parsers/encoders
        self.r14_exempt = self.in_store or (
            len(parts) >= 2 and (parts[-2], parts[-1])
            == ("ops", "framing.py"))
        # ...and stream/native.py additionally holds the ONE ctypes
        # binding of the frame engine's native symbols
        self.r14_native_exempt = self.r14_exempt or (
            len(parts) >= 2 and (parts[-2], parts[-1])
            == ("stream", "native.py"))
        # R11 scoping: the mlops package owns registry bytes
        self.in_mlops = "mlops" in parts
        # R15 scoping: the replication package owns the ISR set and
        # the quorum HWM; the wire server holds the ONE ingress where
        # follower fetch positions are observed
        self.in_replication = "replication" in parts
        self.r15_ingress = self.in_replication or (
            len(parts) >= 2 and (parts[-2], parts[-1])
            == ("stream", "kafka_wire.py"))
        # R12 scoping: the twin package owns the CAR_TWIN changelog;
        # the obs package owns the telemetry-plane topics
        # (_IOTML_METRICS / _IOTML_TSDB / _IOTML_ALERTS)
        self.in_twin = "twin" in parts
        self.in_obs = "obs" in parts
        # R16 scoping: the twin package owns the TwinTable, and the
        # gateway's standby/serving plane is its second legal holder
        # (a standby IS a continuously-rebuilt table); the chaos/
        # supervise drill harnesses may snapshot victims directly
        self.r16_exempt = self.in_twin or "gateway" in parts \
            or self.in_chaos
        # R13 scoping: the registry machinery (mlops watchers/rollouts)
        # and the online learner's adaptation path are the two places a
        # scorer's weights may legally be set in place — everything
        # else deploys through the registry
        self.r13_exempt = self.in_mlops or "online" in parts
        #: Thread(...) call nodes already seen as a register_thread(...)
        #: argument — outer calls visit before inner ones, so by the
        #: time visit_Call reaches the Thread node it is marked
        self._registered_threads: Set[int] = set()
        self._lock_stack: List[Tuple[str, int, bool]] = []  # (name, line, suppressed)

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        if rule not in self.rules or self.sup.suppressed(rule, node):
            return
        self.findings.append(Finding(self.path, node.lineno, rule, message))

    # ----------------------------------------------------------- R7 imports
    def _check_chaos_import(self, node: ast.AST, dotted: str,
                            names: Optional[List[str]] = None) -> None:
        """`dotted` is the imported module path (relative dots stripped);
        `names` the from-import aliases (None for a plain import)."""
        segs = [s for s in dotted.split(".") if s]
        if "chaos" in segs and not self.in_chaos:
            if not self.chaos_allowed:
                self._emit("R7", node,
                           "iotml.chaos import outside the faultpoint "
                           "allowlist (CHAOS_ALLOWED_MODULES): injection "
                           "sites are a reviewed allowlist change")
            elif not (segs[-1] == CHAOS_SHIM_MODULE
                      or (segs[-1] == "chaos" and names is not None
                          and all(n == CHAOS_SHIM_MODULE for n in names))):
                self._emit("R7", node,
                           "production code may import nothing from "
                           "iotml.chaos except the shim module "
                           f"'{CHAOS_SHIM_MODULE}' — scenario/runner "
                           "code must not leak into hot paths")

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        names = [a.name for a in node.names]
        self._check_chaos_import(node, node.module or "", names)
        # the evasion form: `from iotml import chaos` / `from .. import
        # chaos` carries the package in the ALIAS list, not the module
        # path — importing the package (rather than the shim) is a
        # violation everywhere outside the subsystem itself
        segs = [s for s in (node.module or "").split(".") if s]
        if "chaos" not in segs and "chaos" in names and not self.in_chaos:
            self._emit("R7", node,
                       "importing the iotml.chaos package itself: "
                       "production code may import only the shim module "
                       f"('{CHAOS_SHIM_MODULE}'), and only in "
                       "CHAOS_ALLOWED_MODULES")
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self._check_chaos_import(node, alias.name)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # R10 — `<x>.brokers[i]` / `.servers[i]` / `.serving[i]` /
        # `.replicas[i]`: picking a broker instance by index outside the
        # cluster package bypasses PartitionMap routing — and with it
        # the NOT_LEADER re-route and epoch-fencing invariants
        v = node.value
        if not self.r10_exempt and isinstance(v, ast.Attribute) \
                and v.attr in _R10_COLLECTIONS:
            self._emit("R10", node,
                       f"direct broker-instance addressing "
                       f"(.{v.attr}[...]) outside iotml/cluster/: "
                       f"route via PartitionMap / ClusterClient")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # R16 — reaching through a service's `.table` to the TwinTable
        # API outside the twin/gateway planes: serving raw table state
        # bypasses the owner's locking and the provenance the
        # changelog's crash story depends on
        v = node.value
        if not self.r16_exempt and isinstance(v, ast.Attribute) \
                and v.attr == "table" and node.attr in _TWIN_TABLE_ATTRS:
            self._emit("R16", node,
                       f"direct TwinTable access (.table.{node.attr}) "
                       "outside iotml/twin/ + iotml/gateway/: query "
                       "via TwinService / TwinFeatureStore / "
                       "GatewayClient")
        self.generic_visit(node)

    # R4 needs with-scope tracking, so visit With explicitly
    def visit_With(self, node: ast.With) -> None:
        held = []
        for item in node.items:
            name = _lockish_name(item.context_expr)
            if name is not None:
                held.append((name, node.lineno,
                             self.sup.suppressed("R4", node)))
        self._lock_stack.extend(held)
        self.generic_visit(node)
        del self._lock_stack[len(self._lock_stack) - len(held):]

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node)

        # R1 — wall clock in wire/broker/replica code
        if self.r1_scoped and _is_time_time(node):
            self._emit("R1", node,
                       "time.time() in wire/broker/replica code: use "
                       "time.monotonic() for deadlines/timeouts, or "
                       "annotate '# wallclock-ok: <reason>' for real "
                       "wall-clock reads (timestamps, uptime)")

        # R2 — _request call sites
        if name == "_request" and isinstance(node.func, ast.Attribute):
            api = node.args[0] if node.args else None
            api_name = api.id if isinstance(api, ast.Name) else None
            if api_name not in IDEMPOTENT_API_NAMES \
                    and not self.sup.retry_justified(node):
                shown = api_name or ast.unparse(api) if api else "<missing>"
                self._emit("R2", node,
                           f"_request({shown}, ...) is not on the "
                           "IDEMPOTENT_APIS allowlist: a reconnect will NOT "
                           "auto-retry it; add '# retry-ok: <redelivery "
                           "story>' acknowledging the contract")

        # R3 — bare acquire
        if name == "acquire" and isinstance(node.func, ast.Attribute):
            self._emit("R3", node,
                       "bare .acquire(): hold locks with 'with <lock>:' so "
                       "release is exception-safe and the runtime lockcheck "
                       "sees the hold")

        # R4 — blocking under a held lock
        if self._lock_stack and name is not None:
            active = [(n, ln) for n, ln, suppressed in self._lock_stack
                      if not suppressed]
            if active:
                reason = None
                if name in BLOCKING_CALLS:
                    reason = f"blocking {name}()"
                elif self.graph is not None and name in self.graph.bodies:
                    inner = self.graph.blocking_reason(name)
                    if inner:
                        reason = inner
                if reason is not None:
                    lock_name, lock_line = active[-1]
                    self._emit("R4", node,
                               f"{reason} while holding {lock_name} "
                               f"(acquired line {lock_line}): a stalled "
                               "peer parks every thread contending this "
                               "lock")
                # R6 — span recording under a held lock (same transitive
                # walk): the trace collector is lock-free by contract, so
                # a mark inside a critical section would smuggle exporter
                # work — and its latency — under a protocol lock
                sreason = _span_call_reason(node, name)
                if sreason is None and self.graph is not None \
                        and name in self.graph.bodies:
                    sreason = self.graph.span_reason(name)
                if sreason is not None:
                    lock_name, lock_line = active[-1]
                    self._emit("R6", node,
                               f"{sreason} while holding {lock_name} "
                               f"(acquired line {lock_line}): record "
                               "spans outside critical sections — the "
                               "collector is lock-free by design")

        # R6 — metric/span naming convention
        if name in _METRIC_FACTORY_CALLS and \
                isinstance(node.func, ast.Attribute):
            metric = _str_arg0(node)
            if metric is not None and not (
                    _SNAKE_NAME_RE.fullmatch(metric)
                    and (not metric.startswith("iotml")
                         or _IOTML_NAME_RE.fullmatch(metric))):
                self._emit("R6", node,
                           f"metric name {metric!r} violates the naming "
                           "convention: lowercase snake_case, and "
                           "framework-owned families must match "
                           "iotml_[a-z0-9_]+ exactly")
        stage = _str_arg0(node) if (
            (name in _SPAN_LITERAL_CALLS
             and isinstance(node.func, ast.Attribute))
            or _is_tracing_module_call(node)) else None
        if stage is not None and not _SNAKE_NAME_RE.fullmatch(stage):
            self._emit("R6", node,
                       f"span/stage name {stage!r} violates the naming "
                       "convention ([a-z][a-z0-9_]*): the span CLI and "
                       "the stage-label histograms aggregate by this "
                       "string")
        # R6 — metric LABEL vocabulary: keyword labels at metric record
        # sites must come from the closed set (see
        # obs.metrics.ALLOWED_LABEL_KEYS).  A runaway per-entity label
        # (car_id, trace, offset...) must fail here before it fails
        # production with an unbounded series explosion.
        if name in _METRIC_RECORD_CALLS and \
                isinstance(node.func, ast.Attribute) and node.keywords:
            for kw in node.keywords:
                if kw.arg is None:  # **labels passthrough: the metric
                    continue        # classes' own plumbing
                if kw.arg not in _ALLOWED_METRIC_LABELS:
                    self._emit("R6", node,
                               f"metric label {kw.arg!r} outside the "
                               "closed label vocabulary "
                               "(obs.metrics.ALLOWED_LABEL_KEYS): "
                               "unbounded label domains explode series "
                               "cardinality — extend the vocabulary "
                               "deliberately or drop the label")

        # R7 — faultpoint shim compiled outside the allowlist
        if name == "point" and not self.chaos_allowed \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in ("chaos", CHAOS_SHIM_MODULE) \
                and _str_arg0(node) is not None:
            self._emit("R7", node,
                       f"chaos.point({_str_arg0(node)!r}) outside the "
                       "faultpoint allowlist (CHAOS_ALLOWED_MODULES): "
                       "new injection sites are a reviewed allowlist "
                       "change, not a drive-by")

        # R8 — supervised-thread discipline.  Outer calls visit before
        # their argument nodes, so marking register_thread's Thread
        # argument here is always ahead of that Thread's own visit.
        if name == "register_thread":
            for arg in node.args:
                if isinstance(arg, ast.Call) and _is_thread_ctor(arg):
                    self._registered_threads.add(id(arg))
        if not self.in_supervise and _is_thread_ctor(node):
            kw = {k.arg: k.value for k in node.keywords}
            missing = []
            d = kw.get("daemon")
            if not (isinstance(d, ast.Constant) and d.value is True):
                missing.append("daemon=True")
            if "name" not in kw:
                missing.append("an explicit name=")
            if id(node) not in self._registered_threads:
                missing.append("a register_thread(...) wrapper "
                               "(iotml.supervise.registry)")
            if missing:
                self._emit("R8", node,
                           "unsupervised thread: needs "
                           + ", ".join(missing)
                           + " — the self-healing runtime can only "
                             "supervise what it can enumerate")

        # R9 — durable-store write discipline: fsync is SegmentWriter's
        # alone, and an open() on a store path bypasses the frame/CRC/
        # fsync contract recovery depends on
        if not self.in_store:
            if name == "fsync":
                self._emit("R9", node,
                           "os.fsync outside iotml/store/: durability "
                           "promises are made in one place — route the "
                           "write through store.segment.SegmentWriter")
            if name == "open":
                arg_src = " ".join(
                    ast.unparse(a) for a in list(node.args)
                    + [kw.value for kw in node.keywords])
                if _STORE_PATH_NAME_RE.search(arg_src):
                    self._emit("R9", node,
                               "naked open() on a store path outside "
                               "iotml/store/: all bytes under a store "
                               "dir go through SegmentWriter (framing, "
                               "CRC, fsync accounting, recovery "
                               "semantics)")
            # tier extension: remote-tier writes (segment blob uploads,
            # .stage markers, the remote manifest) are RemoteTier's
            # alone — a foreign manifest write could reference torn
            # blobs, which the commit-marker protocol exists to forbid
            if name in _TIER_WRITE_CALLS:
                self._emit("R9", node,
                           "remote-tier segment upload outside "
                           "iotml/store/: sealed segments reach the "
                           "object store only through RemoteTier's "
                           "stage -> blobs -> manifest-commit protocol")
            if name in ("open", "upload", "put_text", "atomic_write"):
                arg_src = " ".join(
                    ast.unparse(a) for a in list(node.args)
                    + [kw.value for kw in node.keywords])
                if _TIER_PATH_NAME_RE.search(arg_src):
                    self._emit("R9", node,
                               f"naked {name}() on a remote-tier path "
                               "(tiered/ blob, .stage marker, tier "
                               "manifest) outside iotml/store/: the "
                               "remote tier has ONE writer, RemoteTier "
                               "— local bytes stay authoritative until "
                               "ITS manifest commit")

        # R11 — model-registry write discipline: registry bytes are
        # ModelRegistry's alone; a naked open/atomic_write on a registry
        # path bypasses the staged-rename + manifest-as-commit-marker
        # protocol that torn-publish recovery depends on
        if not self.in_mlops and name in ("open", "atomic_write"):
            arg_src = " ".join(
                ast.unparse(a) for a in list(node.args)
                + [kw.value for kw in node.keywords])
            if _REGISTRY_PATH_NAME_RE.search(arg_src):
                self._emit("R11", node,
                           f"naked {name}() on a model-registry path "
                           "outside iotml/mlops/: all registry bytes "
                           "go through ModelRegistry (staged rename + "
                           "manifest commit marker + checksum; a "
                           "version is immutable once committed)")

        # R12 — compaction / twin-changelog write discipline.  First
        # half: CAR_TWIN (the twin's compacted changelog) has ONE
        # writer, TwinService — a foreign producer corrupts every
        # rebuild the changelog exists to make possible.
        if name in ("produce", "produce_many", "produce_batch"):
            topic = None
            topic_nodes = list(node.args)[:1] + [
                kw.value for kw in node.keywords if kw.arg == "topic"]
            for a in topic_nodes:
                if isinstance(a, ast.Constant) and \
                        isinstance(a.value, str):
                    topic = a.value
                elif isinstance(a, (ast.Name, ast.Attribute)):
                    const = a.id if isinstance(a, ast.Name) else a.attr
                    if const == "CHANGELOG_TOPIC":
                        topic = "CAR_TWIN"
                    elif const in _OBS_TOPIC_BY_NAME:
                        topic = _OBS_TOPIC_BY_NAME[const]
            if not self.in_twin and topic in _TWIN_CHANGELOG_TOPICS:
                self._emit("R12", node,
                           f"produce to twin changelog {topic!r} outside "
                           "iotml/twin/: the changelog has one writer "
                           "(TwinService) — a foreign record corrupts "
                           "every rebuild that replays it")
            # telemetry-plane one-writer surface (ISSUE 17): the scrape
            # changelog, the TSDB chunk stream, and the alert log are
            # produced by the obs package alone — a foreign record
            # forks the history the SLO engine alerts from
            if not self.in_obs and topic in _OBS_TELEMETRY_TOPICS:
                self._emit("R12", node,
                           f"produce to telemetry topic {topic!r} "
                           "outside iotml/obs/: the telemetry plane's "
                           "log topics have one writer family "
                           "(FleetCollector / TsdbAppender / SloEngine)")
        # Second half: the segment-rewrite machinery is store-internal;
        # compaction is triggered through Broker.run_compaction so the
        # swap protocol and its crash-safety live in one place
        if not self.in_store:
            if name in _COMPACT_WRITE_CALLS:
                self._emit("R12", node,
                           f"{name}() outside iotml/store/: segment "
                           "compaction machinery is store-internal — "
                           "trigger it via Broker.run_compaction")
            if name in ("open", "atomic_write", "SegmentWriter"):
                arg_src = " ".join(
                    ast.unparse(a) for a in list(node.args)
                    + [kw.value for kw in node.keywords])
                if _CLEANED_PATH_RE.search(arg_src):
                    self._emit("R12", node,
                               f"{name}() on a .cleaned rewrite path "
                               "outside iotml/store/: the compaction "
                               "swap protocol (durable tmp + atomic "
                               "os.replace + mount-time sweep) is the "
                               "store's alone")

        # R14 — ONE frame parser: the segment frame codec's entry
        # points (and any hand-rolled >IBqqi head struct) are confined
        # to iotml/store/ + iotml/ops/framing.py; everyone else
        # consumes raw batches through Broker.fetch_raw + FrameDecoder
        # or the ops.framing helpers, so the wire→disk→host contract
        # cannot fork
        if not self.r14_exempt:
            if name in _FRAME_PARSER_CALLS:
                self._emit("R14", node,
                           f"{name}() outside iotml/store/ + iotml/ops/"
                           "framing.py: the store frame has ONE parser "
                           "— go through Broker.fetch_raw + "
                           "FrameDecoder (or ops.framing helpers)")
            if name in _STRUCT_CALLS:
                arg_src = " ".join(
                    ast.unparse(a) for a in list(node.args)
                    + [kw.value for kw in node.keywords])
                if _FRAME_HEAD_RE.search(arg_src):
                    self._emit("R14", node,
                               "hand-rolled frame-head struct "
                               "(>IBqqi) outside iotml/store/ + "
                               "iotml/ops/framing.py: the frame "
                               "layout is one contract with one "
                               "parser")
        if not self.r14_native_exempt and name in _FRAME_NATIVE_SYMBOLS:
            # write-path extension: a direct ctypes call on the frame
            # engine's symbols is a second frame codec in disguise —
            # the one binding lives in stream/native.py
            self._emit("R14", node,
                       f"direct native frame-codec call {name}() "
                       "outside iotml/stream/native.py: frame "
                       "encoding/decoding goes through the bound "
                       "NativeCodec/FrameDecoder or ops.framing "
                       "helpers")

        # R15 — ISR / quorum-HWM mutation discipline: membership and
        # the quorum mark have one owner (iotml/replication/), plus the
        # wire server's observe_fetch ingress.  A drive-by eviction or
        # admission would silently change what acks=all means.
        if not self.in_replication and name in _ISR_MUTATION_CALLS \
                and isinstance(node.func, ast.Attribute):
            self._emit("R15", node,
                       f"{name}() outside iotml/replication/: the ISR "
                       "set and the quorum HWM are mutated in one "
                       "place — acks=all durability is only as strong "
                       "as the narrowest mutation path")
        if not self.r15_ingress and name in _ISR_INGRESS_CALLS \
                and isinstance(node.func, ast.Attribute):
            self._emit("R15", node,
                       f"{name}() outside iotml/replication/ + "
                       "stream/kafka_wire.py: follower positions and "
                       "quorum waits enter through the wire server's "
                       "handlers only — a second ingress could admit "
                       "a replica that never fetched")

        # R13 — model updates go through the registry: an in-place
        # .set_params(...) on a serving scorer outside the mlops/online
        # machinery is an unversioned deploy — no registry id, no
        # rollback target, no swap metric, invisible to /healthz
        if not self.r13_exempt and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "set_params":
            self._emit("R13", node,
                       ".set_params(...) on a scorer outside "
                       "iotml/mlops/ & iotml/online/: publish the "
                       "weights as a registry version and let a "
                       "RegistryWatcher swap it (versioned, gated, "
                       "rollback-able)")

        # R16 — TwinTable one-owner discipline: constructing a table or
        # applying changelog records outside the twin/gateway planes
        # builds a twin nobody's changelog covers — a rebuild after a
        # crash silently disagrees with what was served
        if not self.r16_exempt:
            if name == "TwinTable":
                self._emit("R16", node,
                           "TwinTable(...) constructed outside "
                           "iotml/twin/ + iotml/gateway/: the "
                           "materialised twin is built by TwinService "
                           "or adopted through the gateway standby "
                           "plane — query via TwinService / "
                           "TwinFeatureStore / GatewayClient")
            if name == "apply_changelog" \
                    and isinstance(node.func, ast.Attribute):
                self._emit("R16", node,
                           ".apply_changelog(...) outside iotml/twin/ "
                           "+ iotml/gateway/: changelog replay is the "
                           "table owners' alone — a foreign apply "
                           "forks state the changelog can never "
                           "rebuild")

        # R10 — broker instances are the cluster package's to build:
        # constructing a ShardBroker elsewhere bypasses the controller's
        # ownership wiring (and the map that fences it)
        if not self.r10_exempt and name == "ShardBroker":
            self._emit("R10", node,
                       "ShardBroker(...) constructed outside "
                       "iotml/cluster/: broker instances belong to the "
                       "ClusterController; clients route via "
                       "PartitionMap / ClusterClient")

        # R5 — engine-owned topic produced outside streamproc/
        if not self.in_streamproc and name in ("produce", "produce_many",
                                               "produce_batch"):
            topic = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                topic = node.args[0].value
            for kw in node.keywords:
                if kw.arg == "topic" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    topic = kw.value.value
            if topic is not None and \
                    topic.startswith(ENGINE_OWNED_TOPIC_PREFIXES):
                self._emit("R5", node,
                           f"produce to engine-owned topic {topic!r} outside "
                           "streamproc/: the AVRO leg is written exclusively "
                           "by the stream-proc engine (trusted_passthrough "
                           "soundness; Broker.restrict_topic enforces this "
                           "at runtime)")

        self.generic_visit(node)


# --------------------------------------------------------------- driver
# directory walk relocated to program.py (shared with the whole-program
# passes); the old private name stays importable for callers/tests
_iter_py_files = iter_py_files


def suppressions_for(unit: FileUnit) -> _Suppressions:
    """The unit's suppression table — parsed once, shared across lint
    and the whole-program passes (one `# lint-ok:` mechanism)."""
    return unit.cached(
        "suppressions", lambda u: _Suppressions(u.path, u.source))


def call_graph_for(unit: FileUnit) -> Optional[_ModuleCallGraph]:
    """The unit's module-local call graph (R4's walker) — built once,
    shared with tracecheck/protocol/lockorder reachability walks."""
    if unit.tree is None:
        return None
    return unit.cached("callgraph", lambda u: _ModuleCallGraph(u.tree))


def lint_unit(unit: FileUnit,
              rules: Optional[Set[str]] = None) -> List[Finding]:
    """Lint one pre-parsed unit (the parse-once entry point)."""
    rules = rules or set(RULES)
    if unit.tree is None:
        e = unit.parse_error
        return [Finding(unit.path, (e.lineno or 0) if e else 0, "PARSE",
                        f"syntax error: {e.msg if e else 'unparseable'}")]
    sup = suppressions_for(unit)
    graph = call_graph_for(unit) if rules & {"R4", "R6"} else None
    linter = _FileLinter(unit.path, unit.rel, unit.tree, sup, rules,
                         graph=graph)
    linter.visit(unit.tree)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.rule))


def lint_file(path: str, rel: Optional[str] = None,
              rules: Optional[Set[str]] = None,
              program: Optional[Program] = None) -> List[Finding]:
    program = program if program is not None else Program()
    return lint_unit(program.unit(path, rel=rel if rel is not None
                                  else path), rules)


def lint_paths(paths: Iterable[str],
               rules: Optional[Set[str]] = None,
               program: Optional[Program] = None) -> List[Finding]:
    program = program if program is not None else Program()
    out: List[Finding] = []
    for unit in program.units(paths):
        out.extend(lint_unit(unit, rules))
    return out


def default_root() -> str:
    """The iotml package directory this module is part of."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
