"""Runtime lock-order & race detector — instrumented ``threading`` locks.

``install()`` monkeypatches ``threading.Lock``/``threading.RLock`` with a
checking wrapper (no ``sys.setprofile`` — zero per-bytecode overhead, the
cost rides only on lock operations) and instruments the stream layer's
shared state.  While installed it records:

- the **lock-acquisition graph**: lock identity is the *allocation site*
  (file:line of the ``threading.Lock()`` call — lockdep's lock-class
  idea), nodes are sites, and an edge A→B means some thread acquired B
  while holding A.  A path B→…→A at edge-insert time is a lock-order
  **cycle** — deadlock potential, reported as a violation (the pytest
  plugin fails the run on these).
- **blocking I/O under a lock**: ``time.sleep`` and ``socket`` recv/
  accept while any checked lock is held (warning: a stalled peer parks
  every contender).
- **unguarded shared-state mutation**: dicts registered via ``watch()``
  (broker topic/partition maps and group-offset table, coordinator
  membership tables, replica cursors) flag mutations made without their
  guarding lock held — or, for owner-thread state, from a thread other
  than the first mutator.

Scope: only locks *created after* ``install()`` are checked (the stream
stack creates its locks per-object in ``__init__``, so installing before
the system under test is constructed — the pytest plugin's timing —
covers everything).  ``uninstall()`` restores the patched names and
returns the final ``State`` for inspection.
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_SLEEP = time.sleep
_ALLOC = __import__("_thread").allocate_lock

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))


class Violation:
    """One detected problem.  ``kind`` is 'cycle' | 'io-under-lock' |
    'unguarded-mutation'; only cycles fail a checked run."""

    __slots__ = ("kind", "message", "thread")

    def __init__(self, kind: str, message: str):
        self.kind = kind
        self.message = message
        self.thread = threading.current_thread().name

    def __repr__(self) -> str:
        return f"[{self.kind}] ({self.thread}) {self.message}"


class State:
    """Collected graph + findings; internal mutation guarded by a RAW
    lock (never a checked one — the checker must not check itself)."""

    def __init__(self):
        self._mu = _ALLOC()
        self.edges: Dict[Tuple[str, str], str] = {}   # (a, b) -> example site
        self.graph: Dict[str, Set[str]] = {}
        self.violations: List[Violation] = []
        self._seen: Set[str] = set()  # dedup key per violation

    # ------------------------------------------------------------ record
    def record_edge(self, held_site: str, new_site: str,
                    acquire_at: str) -> None:
        if held_site == new_site:
            return  # two instances of one lock class: no order info
        with self._mu:
            known = (held_site, new_site) in self.edges
            if not known:
                self.edges[(held_site, new_site)] = acquire_at
                self.graph.setdefault(held_site, set()).add(new_site)
            if known:
                return
            path = self._path(new_site, held_site)
        if path is not None:
            cycle = " -> ".join([held_site, new_site] + path[1:])
            self.add("cycle",
                     f"lock-order cycle: {cycle} (edge added at "
                     f"{acquire_at}); opposite-order acquisition can "
                     f"deadlock", key=f"cycle:{held_site}|{new_site}")

    def preseed_static(self, edges) -> int:
        """Insert statically-derived acquire-order edges (analysis
        .lockorder) so runtime acquisitions are checked against orders
        the code can express even when this run never executes them.
        A cycle already present among the seeded edges is reported as
        kind 'static-cycle' (a warning unless strict mode promotes it);
        a RUNTIME edge that later closes a cycle through seeded edges
        fails via the ordinary ``record_edge`` detection."""
        n = 0
        for a, b, where in edges:
            if a == b:
                continue
            with self._mu:
                if (a, b) in self.edges:
                    continue
                path = self._path(b, a)
                self.edges[(a, b)] = f"static:{where}"
                self.graph.setdefault(a, set()).add(b)
            n += 1
            if path is not None:
                cycle = " -> ".join([a, b] + path[1:])
                self.add("static-cycle",
                         f"statically-derived lock-order cycle: {cycle} "
                         f"(edge from source at {where}); opposite-order "
                         f"acquisition paths both exist in the code",
                         key=f"static-cycle:{a}|{b}")
        return n

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src→dst in the order graph (caller holds _mu)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self.graph.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def add(self, kind: str, message: str,
            key: Optional[str] = None) -> None:
        key = key or f"{kind}:{message}"
        with self._mu:
            if key in self._seen:
                return
            self._seen.add(key)
            self.violations.append(Violation(kind, message))

    # ----------------------------------------------------------- inspect
    def cycles(self) -> List[Violation]:
        return [v for v in self.violations if v.kind == "cycle"]

    def warnings(self) -> List[Violation]:
        return [v for v in self.violations if v.kind != "cycle"]

    def report(self) -> str:
        lines = [f"lockcheck: {len(self.edges)} lock-order edges, "
                 f"{len(self.cycles())} cycles, "
                 f"{len(self.warnings())} warnings"]
        lines += [f"  {v!r}" for v in self.violations]
        return "\n".join(lines)


_state: Optional[State] = None
_held = threading.local()  # .stack: List[CheckedLockBase] per thread


def _held_stack() -> list:
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


def _caller_site(depth: int = 2) -> str:
    """file:line of the first frame outside this module."""
    f = sys._getframe(depth)
    while f is not None and \
            os.path.dirname(f.f_code.co_filename) == _THIS_DIR:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def _site_of_creation() -> str:
    f = sys._getframe(2)
    while f is not None and \
            os.path.dirname(f.f_code.co_filename) == _THIS_DIR:
        f = f.f_back
    if f is None:
        return "<unknown>"
    rel = f.f_code.co_filename
    parts = rel.replace(os.sep, "/").split("/")
    short = "/".join(parts[-2:])
    return f"{short}:{f.f_lineno}"


class CheckedLockBase:
    """Common acquire/release bookkeeping over a real lock."""

    _reentrant = False

    def __init__(self, real, site: str):
        self._real = real
        self._site = site

    # ----------------------------------------------------------- acquire
    def acquire(self, blocking: bool = True, timeout: float = -1):
        st = _state
        stack = _held_stack()
        if st is not None and blocking:
            already = any(h is self for h in stack)
            if not already:
                at = _caller_site()
                for h in stack:
                    st.record_edge(h._site, self._site, at)
        got = self._real.acquire(blocking, timeout)  # lint-ok: R3 the wrapper IS the context manager; this is the delegated primitive
        if got:
            stack.append(self)
        return got

    def release(self):
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._real.release()

    def __enter__(self):
        self.acquire()  # lint-ok: R3 context-manager protocol itself
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._real.locked()

    def _at_fork_reinit(self):
        # concurrent.futures/threading call this in fork children; the
        # child is single-threaded so the held stack needs no repair
        self._real._at_fork_reinit()

    def held_by_current_thread(self) -> bool:
        return any(h is self for h in _held_stack())

    def __repr__(self):
        return f"<{type(self).__name__} site={self._site}>"


class CheckedLock(CheckedLockBase):
    pass


class CheckedRLock(CheckedLockBase):
    _reentrant = True

    # threading.Condition integration: these three let a Condition built
    # on a checked RLock fully release/restore it around wait(), keeping
    # the held-stack truthful while the thread is parked.
    def _is_owned(self):
        return self._real._is_owned()

    def _release_save(self):
        stack = _held_stack()
        count = 0
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                count += 1
        return (self._real._release_save(), count)

    def _acquire_restore(self, saved):
        real_state, count = saved
        self._real._acquire_restore(real_state)
        _held_stack().extend([self] * count)


def _make_lock():
    if _state is None:
        return _REAL_LOCK()
    return CheckedLock(_REAL_LOCK(), _site_of_creation())


def _make_rlock():
    if _state is None:
        return _REAL_RLOCK()
    return CheckedRLock(_REAL_RLOCK(), _site_of_creation())


# ----------------------------------------------------------- I/O probes
def _flag_io(what: str) -> None:
    st = _state
    if st is None:
        return
    stack = _held_stack()
    if not stack:
        return
    sites = ", ".join(h._site for h in stack)
    at = _caller_site()
    st.add("io-under-lock",
           f"{what} at {at} while holding [{sites}]: a stalled peer parks "
           f"every thread contending these locks",
           key=f"io:{what}:{at}:{sites}")


def _checked_sleep(seconds):
    _flag_io("time.sleep")
    return _REAL_SLEEP(seconds)


def _patch_socket_probes(install: bool) -> None:
    # socket.socket is the pure-Python subclass of _socket.socket, so a
    # shadowing class attribute is enough — and removable.
    if install:
        real_recv = socket.socket.recv
        real_accept = socket.socket.accept

        def recv(self, *a, **k):
            _flag_io("socket.recv")
            return real_recv(self, *a, **k)

        def accept(self):
            _flag_io("socket.accept")
            return real_accept(self)

        recv._lockcheck = accept._lockcheck = True  # type: ignore
        socket.socket.recv = recv      # type: ignore[method-assign]
        socket.socket.accept = accept  # type: ignore[method-assign]
    else:
        for name in ("recv", "accept"):
            fn = socket.socket.__dict__.get(name)
            if fn is not None and getattr(fn, "_lockcheck", False):
                if name == "recv":
                    del socket.socket.recv    # fall back to C method
                else:
                    socket.socket.accept = _PY_SOCKET_ACCEPT


_PY_SOCKET_ACCEPT = socket.socket.accept  # the stdlib Python-level accept


# ------------------------------------------------- shared-state watching
class WatchedDict(dict):
    """dict that flags mutations made without the guard.

    guard = a checked lock  → mutation requires it held by this thread;
    guard = None (owner mode) → first mutating thread becomes the owner,
    mutations from any other thread are flagged.  Reads are never
    checked (torn reads are the reader's lock discipline, flagged where
    the mutation happens)."""

    def __init__(self, data, label: str, lock=None):
        super().__init__(data)
        self._lc_label = label
        self._lc_lock = lock if isinstance(lock, CheckedLockBase) else None
        self._lc_owner: Optional[int] = None

    def _lc_check(self):
        st = _state
        if st is None:
            return
        if self._lc_lock is not None:
            if not self._lc_lock.held_by_current_thread():
                st.add("unguarded-mutation",
                       f"{self._lc_label} mutated at {_caller_site()} "
                       f"without holding {self._lc_lock._site}",
                       key=f"mut:{self._lc_label}:{_caller_site()}")
        else:
            me = threading.get_ident()
            if self._lc_owner is None:
                self._lc_owner = me
            elif self._lc_owner != me:
                st.add("unguarded-mutation",
                       f"{self._lc_label} mutated at {_caller_site()} from "
                       f"non-owner thread "
                       f"{threading.current_thread().name}",
                       key=f"mut:{self._lc_label}:{_caller_site()}")

    def __setitem__(self, k, v):
        self._lc_check()
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._lc_check()
        super().__delitem__(k)

    def pop(self, *a):
        self._lc_check()
        return super().pop(*a)

    def popitem(self):
        self._lc_check()
        return super().popitem()

    def clear(self):
        self._lc_check()
        super().clear()

    def update(self, *a, **k):
        self._lc_check()
        super().update(*a, **k)

    def setdefault(self, k, default=None):
        if k not in self:
            self._lc_check()
        return super().setdefault(k, default)


def watch(obj, attr: str, lock=None, label: Optional[str] = None) -> None:
    """Replace ``obj.attr`` (a dict) with a mutation-checking wrapper.
    No-op when lockcheck is not installed or the attr is already
    watched."""
    if _state is None:
        return
    cur = getattr(obj, attr)
    if isinstance(cur, WatchedDict) or not isinstance(cur, dict):
        return
    setattr(obj, attr, WatchedDict(
        cur, label or f"{type(obj).__name__}.{attr}", lock=lock))


_instrumented = False


def _instrument_stream_layer() -> None:
    """Wrap the stream layer's constructors so every instance created
    under lockcheck gets its shared tables watched.  Idempotent; the
    wrappers are no-ops when lockcheck is not installed."""
    global _instrumented
    if _instrumented:
        return
    _instrumented = True

    def after_init(cls, register):
        orig = cls.__init__

        def __init__(self, *a, **k):
            orig(self, *a, **k)
            if _state is not None:
                register(self)

        __init__.__wrapped__ = orig  # type: ignore[attr-defined]
        cls.__init__ = __init__

    try:
        from ..stream.broker import Broker

        after_init(Broker, lambda b: (
            watch(b, "_topics", lock=b._lock, label="Broker._topics"),
            watch(b, "_parts", lock=b._lock, label="Broker._parts"),
            watch(b, "_group_offsets", lock=b._lock,
                  label="Broker._group_offsets")))
    except Exception:  # pragma: no cover - import cycles in exotic setups
        pass
    try:
        from ..stream.group import GroupCoordinator

        after_init(GroupCoordinator, lambda g: (
            watch(g, "_heartbeats", lock=g._lock,
                  label="GroupCoordinator._heartbeats"),
            watch(g, "_subscriptions", lock=g._lock,
                  label="GroupCoordinator._subscriptions"),
            watch(g, "_assignments", lock=g._lock,
                  label="GroupCoordinator._assignments")))
    except Exception:  # pragma: no cover
        pass
    try:
        from ..stream.replica import FollowerReplica

        after_init(FollowerReplica, lambda r: watch(
            r, "_parts", label="FollowerReplica._parts"))
    except Exception:  # pragma: no cover
        pass


# ------------------------------------------------------------ lifecycle
def install() -> State:
    """Patch the lock factories and I/O probes; returns the live State.
    Idempotent: a second install returns the existing State."""
    global _state
    if _state is not None:
        return _state
    _state = State()
    threading.Lock = _make_lock          # type: ignore[assignment]
    threading.RLock = _make_rlock        # type: ignore[assignment]
    time.sleep = _checked_sleep          # type: ignore[assignment]
    _patch_socket_probes(True)
    _instrument_stream_layer()
    return _state


def uninstall() -> Optional[State]:
    """Restore the patched names; returns the final State (or None if
    lockcheck was not installed).  Checked locks already handed out keep
    working — they wrap real locks."""
    global _state
    st = _state
    if st is None:
        return None
    _state = None
    threading.Lock = _REAL_LOCK          # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK        # type: ignore[assignment]
    time.sleep = _REAL_SLEEP             # type: ignore[assignment]
    _patch_socket_probes(False)
    return st


def state() -> Optional[State]:
    return _state


def enabled_by_env() -> bool:
    return os.environ.get("IOTML_LOCKCHECK", "") not in ("", "0")
