"""Static lock-order extraction: acquire-order edges from the source.

The runtime detector (lockcheck) only sees orders the test run actually
executes.  This pass derives the same ``site → site`` acquire-order
edges statically — ``with self.a: … with self.b:`` nesting, including
acquisitions buried in methods the outer ``with`` body calls (per-class
call-graph fixpoint) — so the runtime cycle detector can be PRE-SEEDED
with every order the code can express.  A runtime acquisition that
completes a cycle through a statically-derived edge then fails the run
even though the opposite order was never executed in this session.

Lock identity matches lockcheck's runtime keying exactly: the
*allocation site* of the ``threading.Lock()``/``RLock()`` call as
``{parent-dir}/{file}.py:{lineno}`` (see ``_site_of_creation``), so
static and runtime edges land in one graph.

Scope and honesty: resolution is per class within one module —
``self.X`` locks and ``self.method()`` calls.  Locks passed across
objects or modules are out of reach; what this buys is the dominant
idiom (every broker/coordinator/replica lock is a ``self`` attribute
acquired by its own methods).  Edges are facts about nesting in the
source, not findings — cycles among them are reported by the CLI verb
and by lockcheck after pre-seeding.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .program import FileUnit, Program

#: one extracted acquire-order edge: (outer site, inner site, where) —
#: `where` is "file.py:line" of the inner acquisition or the call that
#: reaches it
Edge = Tuple[str, str, str]


def _short_rel(path: str) -> str:
    parts = path.replace(os.sep, "/").split("/")
    return "/".join(parts[-2:])


def _is_lock_ctor(call: ast.AST) -> bool:
    if not isinstance(call, ast.Call):
        return False
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in ("Lock", "RLock") \
            and isinstance(f.value, ast.Name) \
            and f.value.id == "threading":
        return True
    return isinstance(f, ast.Name) and f.id in ("Lock", "RLock")


def _lock_ref(expr: ast.AST) -> Optional[str]:
    """The ``self.X`` attribute a with-item acquires, or None."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


class _ClassLocks:
    """One class's lock attributes (attr → allocation site) and the
    per-method transitive acquire sets."""

    def __init__(self, node: ast.ClassDef, short: str):
        self.name = node.name
        self.locks: Dict[str, str] = {}
        self.methods: Dict[str, ast.AST] = {}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
                for t in sub.targets:
                    attr = _lock_ref(t)
                    if attr is not None:
                        # runtime keys on the frame line executing the
                        # threading.Lock() call
                        self.locks[attr] = f"{short}:{sub.value.lineno}"
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        self._acquires: Optional[Dict[str, Set[str]]] = None

    # ------------------------------------------------------- fixpoint
    def acquires(self) -> Dict[str, Set[str]]:
        """method name → every lock attr it may acquire, transitively
        through ``self.method()`` calls (cycle-safe fixpoint)."""
        if self._acquires is not None:
            return self._acquires
        direct: Dict[str, Set[str]] = {}
        calls: Dict[str, Set[str]] = {}
        for name, fn in self.methods.items():
            d: Set[str] = set()
            c: Set[str] = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        attr = _lock_ref(item.context_expr)
                        if attr in self.locks:
                            d.add(attr)
                elif isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id == "self" \
                        and sub.func.attr in self.methods:
                    c.add(sub.func.attr)
            direct[name] = d
            calls[name] = c
        acq = {name: set(d) for name, d in direct.items()}
        changed = True
        while changed:
            changed = False
            for name in acq:
                for callee in calls[name]:
                    before = len(acq[name])
                    acq[name] |= acq.get(callee, set())
                    changed = changed or len(acq[name]) > before
        self._acquires = acq
        return acq

    # ---------------------------------------------------------- edges
    def edges(self, short: str) -> List[Edge]:
        out: List[Edge] = []
        acq = self.acquires()

        def inner_acquires(body: List[ast.stmt]):
            """(lock attr, line) acquired anywhere under these
            statements: direct nested withs plus self.method() calls'
            transitive sets."""
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.With):
                        for item in sub.items:
                            attr = _lock_ref(item.context_expr)
                            if attr in self.locks:
                                yield attr, sub.lineno
                    elif isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and isinstance(sub.func.value, ast.Name) \
                            and sub.func.value.id == "self" \
                            and sub.func.attr in self.methods:
                        for attr in acq.get(sub.func.attr, ()):
                            yield attr, sub.lineno

        for fn in self.methods.values():
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.With):
                    continue
                held: List[str] = []
                for item in sub.items:
                    attr = _lock_ref(item.context_expr)
                    if attr not in self.locks:
                        continue
                    # `with a, b:` acquires in item order
                    for h in held:
                        out.append((self.locks[h], self.locks[attr],
                                    f"{short}:{sub.lineno}"))
                    held.append(attr)
                if not held:
                    continue
                for attr, line in inner_acquires(sub.body):
                    for h in held:
                        if attr != h:
                            out.append((self.locks[h], self.locks[attr],
                                        f"{short}:{line}"))
        return out


def extract_edges(unit: FileUnit) -> List[Edge]:
    """All statically-derivable acquire-order edges in one module."""
    if unit.tree is None:
        return []

    def build(u: FileUnit) -> List[Edge]:
        short = _short_rel(u.path)
        out: List[Edge] = []
        seen: Set[Tuple[str, str]] = set()
        for node in ast.walk(u.tree):
            if isinstance(node, ast.ClassDef):
                for a, b, where in _ClassLocks(node, short).edges(short):
                    if (a, b) not in seen:
                        seen.add((a, b))
                        out.append((a, b, where))
        return out

    return unit.cached("lockedges", build)  # type: ignore[return-value]


def analyze(root: Optional[str] = None, *,
            paths: Optional[Iterable[str]] = None,
            program: Optional[Program] = None) -> List[Edge]:
    """Extract acquire-order edges across the tree (or ``paths``)."""
    from .lint import default_root
    program = program if program is not None else Program()
    base = [root if root is not None else default_root()]
    edges: List[Edge] = []
    seen: Set[Tuple[str, str]] = set()
    for unit in program.units(paths if paths is not None else base):
        for a, b, where in extract_edges(unit):
            if (a, b) not in seen:
                seen.add((a, b))
                edges.append((a, b, where))
    return edges


def cycles_among(edges: Iterable[Edge]) -> List[List[str]]:
    """Cycles in the static edge set alone (each reported once)."""
    graph: Dict[str, Set[str]] = {}
    for a, b, _ in edges:
        if a != b:
            graph.setdefault(a, set()).add(b)
    out: List[List[str]] = []
    seen_cycles: Set[frozenset] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        visited = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(path + [start])
                elif nxt not in visited and nxt not in path:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
    return out


def preseed(state=None, edges: Optional[Iterable[Edge]] = None,
            root: Optional[str] = None) -> int:
    """Feed static edges into the runtime detector's graph (the pytest
    plugin's hook).  Returns the number of edges seeded; no-op (0) when
    lockcheck is not installed."""
    from . import lockcheck
    st = state if state is not None else lockcheck.state()
    if st is None:
        return 0
    if edges is None:
        edges = analyze(root)
    return st.preseed_static(edges)
