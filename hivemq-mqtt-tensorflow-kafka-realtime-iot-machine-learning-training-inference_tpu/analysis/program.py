"""Shared parse plane for the whole-program analysis passes.

PR 1's lint re-read and re-parsed every file per invocation, and each
rule family rebuilt its own call graph.  With three more passes
(protocol conformance, trace discipline, registry drift) that cost
multiplies by four — so the parse work is hoisted here: a ``Program``
parses each file exactly once and every pass shares the same
``FileUnit`` (source, AST, line table) plus whatever derived artifacts
(suppression tables, call graphs) the passes memoize onto it via
``FileUnit.cached``.

Nothing here knows about rules; the unit cache is a plain keyed memo so
lint's ``_Suppressions``/``_ModuleCallGraph`` and the new passes'
extractors can all live behind one parse without import cycles.
"""

from __future__ import annotations

import ast
import os
from typing import Callable, Dict, Iterable, List, Optional, Tuple

#: directories never worth parsing
SKIP_DIRS = frozenset({"__pycache__", "build", ".git", ".venv",
                       "node_modules"})


class FileUnit:
    """One parsed source file: path, display-relative path, source text,
    AST (None on syntax error, with the error kept), and a keyed memo
    for pass-specific derived artifacts (suppressions, call graphs,
    extracted tables) so they are computed once per file per process."""

    __slots__ = ("path", "rel", "source", "tree", "parse_error", "_memo")

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(source,
                                                        filename=path)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = e
        self._memo: Dict[str, object] = {}

    def cached(self, key: str, build: Callable[["FileUnit"], object]):
        """Memoized derived artifact: computed once, shared across every
        pass that asks with the same key."""
        if key not in self._memo:
            self._memo[key] = build(self)
        return self._memo[key]


def iter_py_files(paths: Iterable[str]) -> Iterable[Tuple[str, str]]:
    """Yield (abs_path, display_rel_path) for every .py under `paths`."""
    for root in paths:
        root = os.path.abspath(root)
        if os.path.isfile(root):
            yield root, os.path.basename(root)
            continue
        base = os.path.dirname(root)
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    p = os.path.join(dirpath, fn)
                    yield p, os.path.relpath(p, base)


class Program:
    """Parse-once view of a file set, shared across analysis passes.

    ``unit(path)`` parses on first access and memoizes by absolute
    path; ``units(paths)`` walks directories through the same cache, so
    running lint + protocol + tracecheck + drift over one tree parses
    each file exactly once.
    """

    def __init__(self) -> None:
        self._units: Dict[str, FileUnit] = {}

    def unit(self, path: str, rel: Optional[str] = None) -> FileUnit:
        key = os.path.abspath(path)
        u = self._units.get(key)
        if u is None:
            with open(key, "r", encoding="utf-8") as f:
                source = f.read()
            u = FileUnit(key, rel if rel is not None else path, source)
            self._units[key] = u
        return u

    def units(self, paths: Iterable[str]) -> List[FileUnit]:
        return [self.unit(p, rel) for p, rel in iter_py_files(paths)]

    def parsed(self) -> int:
        """Files parsed so far (the CLI summary's cache stat)."""
        return len(self._units)
