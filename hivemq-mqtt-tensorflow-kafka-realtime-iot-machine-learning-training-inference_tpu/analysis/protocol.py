"""Wire-protocol conformance pass (whole-program, four surfaces).

The wire contract lives in five places that nothing used to hold
together: the Python server's dispatch chain and the Python client's
encoders (``stream/kafka_wire.py``), the cluster router's delegations
(``cluster/client.py``), the native client's constants and request
sites (``cpp/kafka_client.cc``, parsed textually — no clang), the R2
lint's idempotency mirror, and the chaos faultpoint registry.  This
pass extracts an api-id↔handler↔encoder↔error-code↔idempotency table
from each surface and checks N-way symmetry.  Findings carry the
finding id plus both file:line anchors (the drifted site and the
authority it drifted from).

Finding ids (suppressible with ``# lint-ok: Pn <reason>``):

  P1  server table integrity: an api in _SUPPORTED with no dispatch
      branch, a dispatch branch for an api _SUPPORTED disowns, or a
      handler emitting a bare numeric error code no ERR_* constant
      names.
  P2  encoder/claim drift: a client encoder naming an api constant
      the table doesn't know, a supported api no Python encoder can
      reach, a cluster delegation (attribute or getattr-string) naming
      a wire method that doesn't exist, or a cluster-expected api the
      router never claims.
  P3  missing typed error mapping: the server can answer a code on an
      api whose Python encoder never compares against it (the generic
      RuntimeError fallback is not a mapping).
  P4  native-surface drift: a C++ API_*/ERR_* constant whose value
      disagrees with Python's, a request() claim with no constant, or
      a claim for an api _SUPPORTED disowns.
  P5  idempotency drift: wire IDEMPOTENT_APIS vs the lint's name
      mirror disagree, or a classification names an unsupported api.
  P6  chaos coverage: an encoder whose request path reaches no
      registered faultpoint (every wire exchange must be injectable),
      or a faultpoint name the chaos registry doesn't know.
  P7  cluster routing: a claim on a NOT_LEADER-capable api outside a
      _routed(...) delegation, or on a NOT_COORDINATOR-capable api
      outside _coordinated(...) — the retry/refresh invariants live in
      those two wrappers only.
"""

from __future__ import annotations

import ast
import os
import re
import types
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .lint import (Finding, _ModuleCallGraph, _Suppressions, _call_name,
                   _str_arg0, call_graph_for, default_root,
                   suppressions_for)
from .program import FileUnit, Program

PASS_RULES: Dict[str, str] = {
    "P1": "server dispatch table drift (supported api without a "
          "handler, handler without a _SUPPORTED row, or a bare "
          "numeric error code)",
    "P2": "encoder/claim drift (unknown api constant, supported api "
          "with no encoder, or a cluster delegation naming a missing "
          "wire method)",
    "P3": "server-emittable error code with no typed client mapping",
    "P4": "C++ surface drift (constant value mismatch or claim "
          "without a table row)",
    "P5": "idempotency classification drift (wire allowlist vs lint "
          "mirror)",
    "P6": "wire exchange unreachable by any chaos faultpoint, or an "
          "unregistered faultpoint name",
    "P7": "cluster claim outside the required _routed/_coordinated "
          "delegation",
}

# Apis the cluster router deliberately does NOT claim: SASL and
# version negotiation are per-connection bootstrap (KafkaWireBroker
# does both inside _connect_any), and CLUSTER_ADMIN is the admin CLI's
# direct verb against a chosen node — routing it through the partition
# map would defeat drain/add of the very node being addressed.
CLUSTER_EXEMPT_APIS = frozenset({
    "SASL_HANDSHAKE", "API_VERSIONS", "CLUSTER_ADMIN"})

# default surface locations (relative to the iotml package root)
WIRE_REL = os.path.join("stream", "kafka_wire.py")
CLUSTER_REL = os.path.join("cluster", "client.py")
CPP_REL = os.path.join("cpp", "kafka_client.cc")
FAULTS_REL = os.path.join("chaos", "faults.py")

_CPP_API_RE = re.compile(r"\b(API_[A-Z_]+)\s*=\s*(-?\d+)")
_CPP_ERR_RE = re.compile(r"\b(ERR_[A-Z_]+)\s*=\s*(-?\d+)")
_CPP_CLAIM_RE = re.compile(r"\brequest\(\s*\w+\s*,\s*(API_[A-Z_]+)")


def _line_node(line: int) -> ast.AST:
    """Anchor shim so table-level findings reuse the lint's
    suppression machinery (which expects an AST node span)."""
    return types.SimpleNamespace(lineno=line, end_lineno=line)


# ------------------------------------------------------------ wire table
class Encoder:
    __slots__ = ("method", "api", "line", "typed")

    def __init__(self, method: str, api: str, line: int):
        self.method = method
        self.api = api
        self.line = line
        self.typed: Dict[str, int] = {}       # ERR name -> compare line


class Handler:
    __slots__ = ("api", "line", "codes", "bare")

    def __init__(self, api: str, line: int):
        self.api = api
        self.line = line
        self.codes: Dict[str, int] = {}       # ERR name -> emit line
        self.bare: List[Tuple[int, int]] = [] # (numeric code, line)


class WireTable:
    """Everything the conformance checks need from kafka_wire.py."""

    def __init__(self) -> None:
        self.consts: Dict[str, Tuple[int, int]] = {}   # name -> (value, line)
        self.supported: Dict[str, int] = {}            # api name -> line
        self.supported_line = 0
        self.idempotent: Dict[str, int] = {}           # api name -> line
        self.handlers: Dict[str, Handler] = {}         # api name -> Handler
        self.encoders: Dict[str, Encoder] = {}         # method -> Encoder
        self.method_points: Dict[str, Set[str]] = {}   # fn -> chaos points
        self.graph: Optional[_ModuleCallGraph] = None

    # ---- derived
    def err_values(self) -> Set[int]:
        return {v for n, (v, _) in self.consts.items()
                if n.startswith("ERR_")}

    def _local_callees(self, method: str) -> Set[str]:
        """Callees resolvable within the module: ``self.x(...)`` and
        bare ``x(...)`` only — an attribute call on a foreign receiver
        (``"".join(...)``, ``r.array(...)``) must NOT resolve to a
        same-named module function, or every method that joins a
        string 'reaches' the group-join encoder."""
        body = self.graph.bodies.get(method) if self.graph else None
        out: Set[str] = set()
        if body is None:
            return out
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                if not (isinstance(f.value, ast.Name)
                        and f.value.id == "self"):
                    continue
                callee = f.attr
            elif isinstance(f, ast.Name):
                callee = f.id
            else:
                continue
            if callee != method and self.graph \
                    and callee in self.graph.bodies:
                out.add(callee)
        return out

    def apis_of_method(self, method: str,
                       _seen: Optional[Set[str]] = None) -> Set[str]:
        """Apis a wire method reaches, transitively (end_offset →
        _list_offset → LIST_OFFSETS)."""
        _seen = _seen if _seen is not None else set()
        if method in _seen:
            return set()
        _seen.add(method)
        out: Set[str] = set()
        if method in self.encoders:
            out.add(self.encoders[method].api)
        for callee in self._local_callees(method):
            out |= self.apis_of_method(callee, _seen)
        return out

    def points_of_method(self, method: str,
                         _seen: Optional[Set[str]] = None) -> Set[str]:
        """Chaos faultpoints a wire method's call tree reaches."""
        _seen = _seen if _seen is not None else set()
        if method in _seen:
            return set()
        _seen.add(method)
        out = set(self.method_points.get(method, ()))
        for callee in self._local_callees(method):
            out |= self.points_of_method(callee, _seen)
        return out


def _collect_consts(tree: ast.Module, table: WireTable) -> None:
    for node in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id.isupper() \
                    and isinstance(value, ast.Constant) \
                    and isinstance(value.value, int):
                table.consts[tgt.id] = (value.value, node.lineno)
            elif isinstance(tgt, ast.Tuple) \
                    and isinstance(value, ast.Tuple) \
                    and len(tgt.elts) == len(value.elts):
                for name, val in zip(tgt.elts, value.elts):
                    if isinstance(name, ast.Name) and name.id.isupper() \
                            and isinstance(val, ast.Constant) \
                            and isinstance(val.value, int):
                        table.consts[name.id] = (val.value, node.lineno)


def _collect_tables(tree: ast.Module, table: WireTable) -> None:
    for node in tree.body:
        tgt = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            tgt = node.target.id
        value = getattr(node, "value", None)
        if tgt == "_SUPPORTED" and isinstance(value, ast.Dict):
            table.supported_line = node.lineno
            for k in value.keys:
                if isinstance(k, ast.Name):
                    table.supported[k.id] = k.lineno
        elif tgt == "IDEMPOTENT_APIS" and value is not None:
            for sub in ast.walk(value):
                # api constants only — not the frozenset builtin itself
                if isinstance(sub, ast.Name) and sub.id.isupper():
                    table.idempotent[sub.id] = sub.lineno


def _int_literal(node: ast.expr) -> Optional[int]:
    """Integer literal value, covering the ``-1`` UnaryOp shape."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub) \
            and isinstance(node.operand, ast.Constant) \
            and isinstance(node.operand.value, int):
        return -node.operand.value
    return None


def _api_names_in_test(test: ast.expr) -> List[Tuple[str, int]]:
    """Api constant names an If test compares ``api_key`` against."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare):
            continue
        left = node.left
        if not (isinstance(left, ast.Name) and left.id == "api_key"):
            continue
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, ast.Eq) and isinstance(comp, ast.Name):
                out.append((comp.id, node.lineno))
            elif isinstance(op, ast.In) \
                    and isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                out.extend((e.id, node.lineno) for e in comp.elts
                           if isinstance(e, ast.Name))
    return out


def _collect_handlers(tree: ast.Module, table: WireTable) -> None:
    """Dispatch branches: every If anywhere inside a ``handle`` /
    ``_dispatch`` method whose test names api constants; its body's
    ERR_* loads (and bare i16 integer writes) are the codes that
    branch can answer."""
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or fn.name not in ("handle", "_dispatch"):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            apis = _api_names_in_test(node.test)
            if not apis:
                continue
            codes: Dict[str, int] = {}
            bare: List[Tuple[int, int]] = []
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Name) \
                            and sub.id.startswith("ERR_"):
                        codes.setdefault(sub.id, sub.lineno)
                    elif isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "i16" and sub.args:
                        val = _int_literal(sub.args[0])
                        if val is not None:
                            bare.append((val, sub.lineno))
            for api, line in apis:
                h = table.handlers.get(api)
                if h is None:
                    h = table.handlers[api] = Handler(api, line)
                for name, ln in codes.items():
                    h.codes.setdefault(name, ln)
                h.bare.extend(bare)


def _collect_encoders(tree: ast.Module, table: WireTable) -> None:
    """Client encoders: methods sending ``self._request(API, ...)`` or
    ``self._exchange(API, ...)``; their typed error mappings are the
    ERR_* names the method (or a local helper it calls) compares the
    response code against."""
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("_request", "_exchange") \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self" \
                    and node.args and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id.isupper():
                enc = table.encoders.setdefault(
                    fn.name, Encoder(fn.name, node.args[0].id,
                                     node.lineno))
                enc.typed.update(_typed_codes(fn))
    # fold in ERR compares from local helpers the encoder calls (one
    # transitive hop covers the response-shape helper idiom)
    bodies = table.graph.bodies if table.graph else {}
    for enc in table.encoders.values():
        body = bodies.get(enc.method)
        if body is None:
            continue
        for node in ast.walk(body):
            if isinstance(node, ast.Call):
                callee = _call_name(node)
                if callee and callee != enc.method and callee in bodies:
                    for name, ln in _typed_codes(bodies[callee]).items():
                        enc.typed.setdefault(name, ln)


def _typed_codes(fn: ast.AST) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id.startswith("ERR_"):
                    out.setdefault(sub.id, node.lineno)
    return out


def _collect_points(tree: ast.Module, table: WireTable) -> None:
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "point" \
                    and _str_arg0(node) is not None:
                table.method_points.setdefault(fn.name, set()).add(
                    _str_arg0(node))


def build_wire_table(unit: FileUnit) -> WireTable:
    def _build(u: FileUnit) -> WireTable:
        table = WireTable()
        if u.tree is None:
            return table
        table.graph = call_graph_for(u)
        _collect_consts(u.tree, table)
        _collect_tables(u.tree, table)
        _collect_handlers(u.tree, table)
        _collect_points(u.tree, table)
        _collect_encoders(u.tree, table)
        return table
    return unit.cached("wiretable", _build)


# ------------------------------------------------------- cluster surface
class ClusterClaim:
    __slots__ = ("method", "kind", "line")

    def __init__(self, method: str, kind: str, line: int):
        self.method = method   # wire-client method name claimed
        self.kind = kind       # routed | coordinated | any | direct
        self.line = line


_DELEGATES = {"_routed": "routed", "_coordinated": "coordinated",
              "_any_conn_call": "any"}


def _scan_op(body: Iterable[ast.AST], param: Optional[str], kind: str,
             out: List[ClusterClaim]) -> None:
    """Claims inside a delegation op: calls/getattr on its conn param."""
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and (param is None or f.value.id == param):
                out.append(ClusterClaim(f.attr, kind, node.lineno))
            elif isinstance(f, ast.Name) and f.id == "getattr" \
                    and len(node.args) >= 2 \
                    and isinstance(node.args[0], ast.Name) \
                    and (param is None or node.args[0].id == param) \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                out.append(ClusterClaim(node.args[1].value, kind,
                                        node.lineno))


def extract_cluster_claims(unit: FileUnit) -> List[ClusterClaim]:
    def _build(u: FileUnit) -> List[ClusterClaim]:
        claims: List[ClusterClaim] = []
        if u.tree is None:
            return claims
        for method in ast.walk(u.tree):
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            local_defs = {n.name: n for n in method.body
                          if isinstance(n, ast.FunctionDef)}
            for node in ast.walk(method):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute) \
                        or node.func.attr not in _DELEGATES:
                    continue
                kind = _DELEGATES[node.func.attr]
                for arg in node.args:
                    if isinstance(arg, ast.Lambda):
                        param = arg.args.args[0].arg \
                            if arg.args.args else None
                        _scan_op([arg.body], param, kind, claims)
                    elif isinstance(arg, ast.Name) \
                            and arg.id in local_defs:
                        op = local_defs[arg.id]
                        param = op.args.args[0].arg \
                            if op.args.args else None
                        _scan_op(op.body, param, kind, claims)
            # direct per-shard calls: self._conn(shard).method(...)
            for node in ast.walk(method):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Call) \
                        and isinstance(node.func.value.func,
                                       ast.Attribute) \
                        and node.func.value.func.attr == "_conn":
                    claims.append(ClusterClaim(node.func.attr, "direct",
                                               node.lineno))
        return claims
    return unit.cached("clusterclaims", _build)


# --------------------------------------------------------- chaos registry
def chaos_registry(unit: FileUnit) -> Dict[str, Dict[str, int]]:
    """{table_name: {point_name: line}} for KNOWN_POINTS /
    RUNNER_POINTS / POINT_ACTIONS, parsed from chaos/faults.py (shared
    with the drift pass)."""
    def _build(u: FileUnit) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        if u.tree is None:
            return out
        for node in u.tree.body:
            tgt = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                tgt = node.target.id
            value = getattr(node, "value", None)
            if tgt in ("KNOWN_POINTS", "RUNNER_POINTS",
                       "POINT_ACTIONS") and isinstance(value, ast.Dict):
                out[tgt] = {k.value: k.lineno for k in value.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str)}
        return out
    return unit.cached("chaosregistry", _build)


# ------------------------------------------------------------- C++ parse
class CppTable:
    __slots__ = ("apis", "errs", "claims")

    def __init__(self) -> None:
        self.apis: Dict[str, Tuple[int, int]] = {}   # name -> (value, line)
        self.errs: Dict[str, Tuple[int, int]] = {}
        self.claims: List[Tuple[str, int]] = []      # (API_ name, line)


def parse_cpp(path: str) -> CppTable:
    table = CppTable()
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for i, line in enumerate(f, start=1):
            for m in _CPP_API_RE.finditer(line):
                table.apis.setdefault(m.group(1), (int(m.group(2)), i))
            for m in _CPP_ERR_RE.finditer(line):
                table.errs.setdefault(m.group(1), (int(m.group(2)), i))
            for m in _CPP_CLAIM_RE.finditer(line):
                table.claims.append((m.group(1), i))
    return table


# --------------------------------------------------------------- checks
class _Pass:
    def __init__(self, wire_unit: FileUnit):
        self.wire = wire_unit
        self.table = build_wire_table(wire_unit)
        self.sup = suppressions_for(wire_unit)
        self.findings: List[Finding] = []
        self.wire_name = os.path.basename(wire_unit.path)

    def emit(self, sup: _Suppressions, path: str, rule: str, line: int,
             message: str) -> None:
        if not sup.suppressed(rule, _line_node(line)):
            self.findings.append(Finding(path, line, rule, message))

    # ---- intra-wire checks (also run standalone on fixtures)
    def check_wire(self) -> None:
        t, sup, path = self.table, self.sup, self.wire.path
        # P1: _SUPPORTED rows vs dispatch branches, both directions
        for api, line in t.supported.items():
            if api not in t.handlers:
                self.emit(sup, path, "P1", line,
                          f"api {api} is in _SUPPORTED but no "
                          "handle()/_dispatch() branch handles it — "
                          "clients negotiating it will hit an "
                          "unanswered request")
        for api, h in t.handlers.items():
            if api not in t.supported:
                self.emit(sup, path, "P1", h.line,
                          f"dispatch branch for {api} but _SUPPORTED "
                          f"(line {t.supported_line}) disowns it — the "
                          "version preamble answers UNSUPPORTED_VERSION "
                          "before this branch can run")
            errvals = t.err_values()
            for val, line in h.bare:
                if val not in errvals:
                    self.emit(sup, path, "P1", line,
                              f"handler for {h.api} emits bare error "
                              f"code {val} that no ERR_* constant "
                              "names — clients cannot write a typed "
                              "mapping for an unnamed code")
        # P2: encoders must name known, supported apis...
        for enc in t.encoders.values():
            if enc.api not in t.consts:
                self.emit(sup, path, "P2", enc.line,
                          f"{enc.method}() requests unknown api "
                          f"constant {enc.api}")
            elif enc.api not in t.supported:
                self.emit(sup, path, "P2", enc.line,
                          f"{enc.method}() requests {enc.api} which "
                          f"_SUPPORTED (line {t.supported_line}) "
                          "disowns")
        # ...and every supported api must have an encoder path
        encoded = {e.api for e in t.encoders.values()}
        for api, line in t.supported.items():
            if api not in encoded:
                self.emit(sup, path, "P2", line,
                          f"api {api} is in _SUPPORTED but no client "
                          "encoder method requests it — the Python "
                          "surface cannot exercise its own contract")
        # P3: typed mapping for every code the server can emit
        for enc in t.encoders.values():
            h = t.handlers.get(enc.api)
            if h is None:
                continue
            for code, src_line in sorted(h.codes.items()):
                if code == "ERR_NONE":
                    continue
                if code not in enc.typed:
                    self.emit(sup, path, "P3", enc.line,
                              f"server can answer {code} on {enc.api} "
                              f"({self.wire_name}:{src_line}) but "
                              f"{enc.method}() never compares against "
                              "it — it would surface as the generic "
                              "RuntimeError fallback, untyped")
        # P5: idempotency classifications name supported apis
        for api, line in t.idempotent.items():
            if api not in t.supported:
                self.emit(sup, path, "P5", line,
                          f"IDEMPOTENT_APIS classifies {api} which "
                          "_SUPPORTED disowns — a retry allowlist for "
                          "an api that cannot be requested")

    def check_idempotency_mirror(self, lint_names: Iterable[str],
                                 lint_path: str) -> None:
        wire_names = set(self.table.idempotent)
        mirror = set(lint_names)
        line = min(self.table.idempotent.values(), default=1)
        for api in sorted(wire_names - mirror):
            self.emit(self.sup, self.wire.path, "P5",
                      self.table.idempotent[api],
                      f"{api} is idempotent on the wire but the lint "
                      f"mirror ({lint_path}) does not list it — R2 "
                      "would flag call sites the client auto-retries")
        for api in sorted(mirror - wire_names):
            self.emit(self.sup, self.wire.path, "P5", line,
                      f"the lint mirror ({lint_path}) classifies {api} "
                      "idempotent but wire IDEMPOTENT_APIS does not — "
                      "R2 would pass a call site the client refuses to "
                      "retry")

    def check_chaos(self, registry: Optional[Dict[str, int]]) -> None:
        t, sup, path = self.table, self.sup, self.wire.path
        for enc in sorted(t.encoders.values(), key=lambda e: e.line):
            points = t.points_of_method(enc.method)
            if not points:
                self.emit(sup, path, "P6", enc.line,
                          f"{enc.method}() ({enc.api}) reaches no "
                          "chaos faultpoint — its wire exchange cannot "
                          "be fault-injected")
            elif registry is not None:
                for p in sorted(points):
                    if p not in registry:
                        self.emit(sup, path, "P6", enc.line,
                                  f"{enc.method}() reaches faultpoint "
                                  f"{p!r} which the chaos registry "
                                  "(KNOWN_POINTS) does not declare")

    def check_cluster(self, cluster_unit: FileUnit) -> None:
        t = self.table
        claims = extract_cluster_claims(cluster_unit)
        sup = suppressions_for(cluster_unit)
        path = cluster_unit.path
        wire_methods = {fn.name for fn in ast.walk(self.wire.tree)
                        if isinstance(fn, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))} \
            if self.wire.tree is not None else set()
        claimed_apis: Set[str] = set()
        for c in claims:
            if c.method not in wire_methods:
                self.emit(sup, path, "P2", c.line,
                          f"cluster delegation names wire method "
                          f"{c.method!r} which {self.wire_name} does "
                          "not define — the claim dispatches to "
                          "nothing")
                continue
            apis = t.apis_of_method(c.method)
            claimed_apis |= apis
            for api in sorted(apis):
                h = t.handlers.get(api)
                if h is None:
                    continue
                if "ERR_NOT_LEADER_FOR_PARTITION" in h.codes \
                        and c.kind != "routed":
                    self.emit(sup, path, "P7", c.line,
                              f"{c.method}() claims {api}, which can "
                              "answer NOT_LEADER_FOR_PARTITION "
                              f"({self.wire_name}:"
                              f"{h.codes['ERR_NOT_LEADER_FOR_PARTITION']}"
                              f"), from a {c.kind!r} context — only "
                              "_routed(...) re-resolves the map and "
                              "redelivers")
                if "ERR_NOT_COORDINATOR" in h.codes \
                        and c.kind != "coordinated":
                    self.emit(sup, path, "P7", c.line,
                              f"{c.method}() claims {api}, which can "
                              "answer NOT_COORDINATOR "
                              f"({self.wire_name}:"
                              f"{h.codes['ERR_NOT_COORDINATOR']}), "
                              f"from a {c.kind!r} context — only "
                              "_coordinated(...) re-finds the "
                              "coordinator")
        for api in sorted(set(t.supported) - claimed_apis
                          - set(CLUSTER_EXEMPT_APIS)):
            self.emit(sup, path, "P2", 1,
                      f"cluster surface claims no path to api {api} "
                      f"(_SUPPORTED {self.wire_name}:"
                      f"{t.supported.get(api, 0)}) — every "
                      "non-bootstrap api must survive sharding")

    def check_cpp(self, cpp_path: str,
                  cpp_table: Optional[CppTable] = None) -> None:
        t, sup = self.table, self.sup
        cpp = cpp_table if cpp_table is not None else parse_cpp(cpp_path)
        for name, (value, line) in sorted(cpp.apis.items()):
            py_name = name[len("API_"):]
            if py_name not in t.consts:
                self.emit(sup, cpp_path, "P4", line,
                          f"C++ constant {name} has no Python "
                          f"counterpart {py_name} in {self.wire_name}")
            elif t.consts[py_name][0] != value:
                self.emit(sup, cpp_path, "P4", line,
                          f"C++ {name} = {value} but {self.wire_name}:"
                          f"{t.consts[py_name][1]} defines {py_name} = "
                          f"{t.consts[py_name][0]} — the native client "
                          "would speak a different api id")
        for name, (value, line) in sorted(cpp.errs.items()):
            if name not in t.consts:
                self.emit(sup, cpp_path, "P4", line,
                          f"C++ error constant {name} has no Python "
                          f"counterpart in {self.wire_name}")
            elif t.consts[name][0] != value:
                self.emit(sup, cpp_path, "P4", line,
                          f"C++ {name} = {value} but {self.wire_name}:"
                          f"{t.consts[name][1]} defines {name} = "
                          f"{t.consts[name][0]} — typed mappings "
                          "would misclassify the wire code")
        for name, line in cpp.claims:
            py_name = name[len("API_"):]
            if name not in cpp.apis:
                self.emit(sup, cpp_path, "P4", line,
                          f"C++ request() claims {name} but no "
                          "constant defines it")
            elif py_name not in t.supported:
                self.emit(sup, cpp_path, "P4", line,
                          f"C++ request() claims {name} but _SUPPORTED "
                          f"({self.wire_name}:{t.supported_line}) "
                          "disowns it — the server answers "
                          "UNSUPPORTED_VERSION")


# ------------------------------------------------------------------ API
def check_wire(wire_path: str,
               program: Optional[Program] = None) -> List[Finding]:
    """Intra-file conformance (P1/P2/P3/P5/P6 without registries) —
    the entry point the seeded fixture corpus runs through."""
    program = program if program is not None else Program()
    p = _Pass(program.unit(wire_path))
    p.check_wire()
    p.check_chaos(None)
    return sorted(p.findings, key=lambda f: (f.path, f.line, f.rule))


def analyze(root: Optional[str] = None, *,
            wire: Optional[str] = None,
            cluster: Optional[str] = None,
            cpp: Optional[str] = None,
            faults: Optional[str] = None,
            lint_idempotent: Optional[Iterable[str]] = None,
            program: Optional[Program] = None) -> List[Finding]:
    """Whole-program conformance across all four surfaces.  Each
    surface path can be overridden independently (the skewed-C++ test
    swaps in a drifted snippet against the real tree)."""
    root = root if root is not None else default_root()
    program = program if program is not None else Program()
    wire = wire or os.path.join(root, WIRE_REL)
    cluster = cluster or os.path.join(root, CLUSTER_REL)
    cpp = cpp or os.path.join(root, CPP_REL)
    faults = faults or os.path.join(root, FAULTS_REL)

    p = _Pass(program.unit(wire))
    p.check_wire()
    if lint_idempotent is None:
        from .lint import IDEMPOTENT_API_NAMES
        lint_idempotent = IDEMPOTENT_API_NAMES
    p.check_idempotency_mirror(lint_idempotent, "analysis/lint.py")
    registry = None
    if os.path.exists(faults):
        registry = chaos_registry(program.unit(faults)).get(
            "KNOWN_POINTS", {})
    p.check_chaos(registry)
    if os.path.exists(cluster):
        p.check_cluster(program.unit(cluster))
    if os.path.exists(cpp):
        p.check_cpp(cpp)
    return sorted(p.findings, key=lambda f: (f.path, f.line, f.rule))
