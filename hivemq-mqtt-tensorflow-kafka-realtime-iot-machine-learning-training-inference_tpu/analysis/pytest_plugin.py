"""pytest plugin: run the suite under the runtime lock-order detector.

Activate with ``-p iotml.analysis.pytest_plugin`` or ``IOTML_LOCKCHECK=1``
(tests/conftest.py registers this module when the env var is set).  The
detector is installed at configure time — before any test constructs a
broker/server — so every lock the stream stack creates is checked.

At session end the collected report is printed; **lock-order cycles fail
the run** (exit status 3).  I/O-under-lock and unguarded-mutation
findings are reported as warnings only, unless ``IOTML_LOCKCHECK_STRICT=1``
promotes them to failures too.
"""

from __future__ import annotations

import os

from . import lockcheck


def pytest_configure(config):
    lockcheck.install()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    st = lockcheck.state()
    if st is None:
        return
    tw = terminalreporter
    tw.section("iotml lockcheck")
    tw.write_line(st.report())


def pytest_sessionfinish(session, exitstatus):
    st = lockcheck.state()
    if st is None:
        return
    strict = os.environ.get("IOTML_LOCKCHECK_STRICT", "") not in ("", "0")
    failures = st.violations if strict else st.cycles()
    if failures:
        session.exitstatus = 3
