"""pytest plugin: runtime halves of the analysis passes.

Two independently-gated detectors:

- **lockcheck** (``IOTML_LOCKCHECK=1`` or
  ``-p iotml.analysis.pytest_plugin``): the suite runs under the
  runtime lock-order & race detector, installed at configure time —
  before any test constructs a broker/server — so every lock the
  stream stack creates is checked.  The acquisition graph is
  PRE-SEEDED with the statically-extracted acquire-order edges
  (analysis.lockorder), so a runtime acquisition that inverts an order
  the code merely *can* express still closes a cycle and fails the
  run, even when this session never executed the opposite path.  At
  session end the collected report is printed; **lock-order cycles
  fail the run** (exit status 3).  I/O-under-lock, unguarded-mutation
  and static-only-cycle findings are warnings unless
  ``IOTML_LOCKCHECK_STRICT=1`` promotes them to failures too.

- **trace guard** (``IOTML_TRACECHECK=1``): the known JAX hot loops
  (``Trainer.fit_compiled``, ``ShardedStreamTrainer.fit_round``,
  ``OnlineLearner._update``) are wrapped with the recompile guard
  (analysis.tracecheck): after a loop's warm-up call, any call with an
  identical shape/dtype signature that triggers a fresh XLA backend
  compile raises ``RecompileError`` and fails that test.  Warm state
  resets per test so one test's warm-up cannot mask another's retrace.
"""

from __future__ import annotations

import os

from . import lockcheck


def _env_on(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0")


def pytest_configure(config):
    trace_on = _env_on("IOTML_TRACECHECK")
    # legacy `-p iotml.analysis.pytest_plugin` means lockcheck; only a
    # tracecheck-only session skips installing it
    if lockcheck.enabled_by_env() or not trace_on:
        st = lockcheck.install()
        from . import lockorder

        lockorder.preseed(st)
    if trace_on:
        from . import tracecheck

        config._iotml_traceguard = tracecheck.install_runtime_guard()


def pytest_runtest_setup(item):
    if _env_on("IOTML_TRACECHECK"):
        from . import tracecheck

        tracecheck.reset_warm()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    tw = terminalreporter
    st = lockcheck.state()
    if st is not None:
        tw.section("iotml lockcheck")
        tw.write_line(st.report())
    patched = getattr(config, "_iotml_traceguard", None)
    if patched is not None:
        tw.section("iotml tracecheck")
        tw.write_line(
            f"recompile guard armed on: {', '.join(patched) or 'nothing'}")


def pytest_sessionfinish(session, exitstatus):
    st = lockcheck.state()
    if st is None:
        return
    strict = os.environ.get("IOTML_LOCKCHECK_STRICT", "") not in ("", "0")
    failures = st.violations if strict else st.cycles()
    if failures:
        session.exitstatus = 3
