"""JAX trace-discipline pass: recompile and host-sync hazards.

Static half
-----------
An interprocedural walk over the jit/scan/shard_map entry points.  Trace
roots are found syntactically — ``jax.jit(f)`` / ``jax.lax.scan(f, …)``
/ ``shard_map(f, …)`` where ``f`` is a local function or lambda, plus
``@jax.jit``-decorated defs — and each root's body (nested defs
included, one transitive hop through same-module functions via the R4
call-graph walker) is checked for the hazards that silently turn a
compiled hot loop into a per-call retrace or a device→host sync stall:

T1  Python-value branching on a traced argument (``if x > 0:`` where
    ``x`` is traced).  Concretises the tracer per call; under jit it
    either fails or forces a recompile per branch arm.  Branching on
    ``.shape``/``.ndim``/``len()``/``is None`` is static and allowed.
T2  Host sync reachable under trace: ``.item()``, ``.tolist()``,
    ``float()``/``int()`` of a traced value, ``np.asarray``/``np.array``
    on a traced value, ``jax.device_get``, ``.block_until_ready()``.
T3  Per-call (re)jit: a ``jax.jit(...)`` whose compiled callable cannot
    outlive the call site — invoked immediately (``jax.jit(f)(x)``), or
    built inside a function that neither returns it, stores it on
    ``self``, nor is a factory (``make_*``; module-level jit is fine).
    jit caches per function object, so a fresh closure per call
    re-traces every time (see train/loop.py's LRU factories).
T4  Traced value in a shape position (``jnp.zeros(n)``, ``x.reshape(n)``
    with traced ``n``): shapes must be static under jit; a traced shape
    is a guaranteed ConcretizationTypeError or per-value recompile.

Static args declared via ``static_argnums``/``static_argnames`` are
excluded from the traced set.  Findings use the shared ``Finding`` type
and honour ``# lint-ok: T<n> <reason>`` suppressions.

Runtime half
------------
``RecompileGuard`` counts XLA backend compiles through
``jax.monitoring`` and ``guard_hot_loop`` wraps a hot-loop callable so
that, once a given (callable, abstract-signature) key has run once
(the warm-up trace), any later call under the same key that triggers a
fresh backend compile raises ``RecompileError``.  The pytest plugin
installs it over ``Trainer.fit_compiled``, ``ShardedStreamTrainer
.fit_round`` and ``OnlineLearner._update`` when ``IOTML_TRACECHECK=1``,
failing any test whose warmed loop retraces.
"""

from __future__ import annotations

import ast
import contextlib
import functools
import os
import threading
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .lint import Finding, call_graph_for, suppressions_for
from .program import FileUnit, Program

PASS_RULES: Dict[str, str] = {
    "T1": "Python-value branch on a traced argument inside a trace",
    "T2": "host sync (.item/float/np.asarray/device_get) under trace",
    "T3": "per-call jax.jit: compiled callable cannot outlive the call",
    "T4": "traced value used in a static shape position",
}

#: the jit/scan/shard_map surfaces this pass walks by default,
#: relative to the iotml package root
TRACE_TARGET_RELS: Tuple[str, ...] = (
    "train/loop.py",
    "parallel/streaming.py",
    "parallel/data_parallel.py",
    "core/normalize.py",
    "online/learner.py",
)

#: enclosing-function names allowed to build jit callables without
#: returning/storing them elsewhere (factory idiom; see train/loop.py)
_FACTORY_PREFIXES = ("make", "_make")

_SHAPE_BUILDERS = frozenset({"zeros", "ones", "full", "empty", "arange",
                             "broadcast_to", "eye", "tri"})
_HOST_SYNC_ATTRS = frozenset({"item", "tolist", "block_until_ready"})


def _line_node(line: int):
    import types
    return types.SimpleNamespace(lineno=line, end_lineno=line)


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` / ``jax.jit`` inside functools.partial."""
    if isinstance(node, ast.Attribute):
        return node.attr == "jit" and isinstance(node.value, ast.Name) \
            and node.value.id == "jax"
    return isinstance(node, ast.Name) and node.id == "jit"


def _static_params(call: ast.Call, fn_args: ast.arguments) -> Set[str]:
    """Param names excluded from tracing by static_argnums/argnames."""
    out: Set[str] = set()
    names = [a.arg for a in fn_args.posonlyargs + fn_args.args]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value,
                                                                str):
                    out.add(sub.value)
        elif kw.arg == "static_argnums":
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, int) \
                        and 0 <= sub.value < len(names):
                    out.add(names[sub.value])
    return out


class _Root:
    """One trace entry point: the function AST plus its traced params."""

    __slots__ = ("fn", "traced", "line")

    def __init__(self, fn, traced: Set[str], line: int):
        self.fn = fn
        self.traced = traced
        self.line = line


def _param_names(args: ast.arguments) -> List[str]:
    return [a.arg for a in args.posonlyargs + args.args
            if a.arg not in ("self", "cls")]


def _collect_roots(tree: ast.Module,
                   bodies: Dict[str, ast.AST]) -> List[_Root]:
    roots: List[_Root] = []
    seen: Set[int] = set()

    def add(fn, static: Set[str]) -> None:
        if id(fn) in seen:
            return
        seen.add(id(fn))
        traced = set(_param_names(fn.args)) - static
        roots.append(_Root(fn, traced, fn.lineno))

    def resolve(node: ast.AST):
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Name):
            body = bodies.get(node.id)
            if isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return body
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.args:
            fn = None
            static: Set[str] = set()
            if _is_jax_jit(node.func):
                fn = resolve(node.args[0])
                if fn is not None:
                    static = _static_params(node, fn.args)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "scan":
                fn = resolve(node.args[0])
            elif isinstance(node.func, ast.Name) \
                    and node.func.id == "shard_map":
                fn = resolve(node.args[0])
            if fn is not None:
                add(fn, static)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                call = dec if isinstance(dec, ast.Call) else None
                target = call.func if call else dec
                # @jax.jit and @partial(jax.jit, ...) both trace the def
                if _is_jax_jit(target):
                    add(node, _static_params(call, node.args)
                        if call else set())
                elif call and isinstance(target, ast.Name) \
                        and target.id == "partial" and call.args \
                        and _is_jax_jit(call.args[0]):
                    add(node, _static_params(call, node.args))
    return roots


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _static_wrapped(test: ast.AST, traced: Set[str]) -> Set[str]:
    """Traced names that only appear in STATIC positions of a branch
    test: ``x is None``, ``x.shape``/``x.ndim``/``x.dtype``,
    ``len(x)``/``isinstance(x, …)`` — all resolved at trace time."""
    ok: Set[str] = set()
    for sub in ast.walk(test):
        if isinstance(sub, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in sub.ops):
            ok |= _names_in(sub) & traced
        elif isinstance(sub, ast.Attribute):
            ok |= _names_in(sub.value) & traced
        elif isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id in ("len", "isinstance", "hasattr",
                                    "getattr", "callable"):
            for a in sub.args:
                ok |= _names_in(a) & traced
    return ok


class _RootChecker:
    """Walks one trace root (nested defs inline, one hop into module
    functions it calls by bare name) and emits T1/T2/T4."""

    def __init__(self, unit: FileUnit, bodies: Dict[str, ast.AST],
                 sup, findings: List[Finding]):
        self.unit = unit
        self.bodies = bodies
        self.sup = sup
        self.findings = findings
        self._visited: Set[int] = set()

    def emit(self, rule: str, line: int, message: str) -> None:
        if self.sup is not None \
                and self.sup.suppressed(rule, _line_node(line)):
            return
        self.findings.append(
            Finding(self.unit.path, line, rule, message))

    def check(self, root: _Root) -> None:
        self._body(root.fn, root.traced, depth=0)

    def _body(self, fn, traced: Set[str], depth: int) -> None:
        if id(fn) in self._visited or depth > 2:
            return
        self._visited.add(id(fn))
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                hot = (_names_in(node.test) & traced) \
                    - _static_wrapped(node.test, traced)
                for name in sorted(hot):
                    self.emit(
                        "T1", node.lineno,
                        f"branch on traced value {name!r} inside a "
                        f"traced function: concretises per call "
                        f"(use jnp.where / lax.cond, or mark it "
                        f"static)")
            elif isinstance(node, ast.Call):
                self._call(node, traced, depth)

    def _call(self, node: ast.Call, traced: Set[str], depth: int) -> None:
        func = node.func
        # T2: host syncs
        if isinstance(func, ast.Attribute):
            if func.attr in _HOST_SYNC_ATTRS:
                self.emit(
                    "T2", node.lineno,
                    f".{func.attr}() under trace forces a device→host "
                    f"sync (move it outside the jitted function)")
                return
            if func.attr in ("asarray", "array") \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id in ("np", "numpy", "onp") \
                    and node.args and _names_in(node.args[0]) & traced:
                self.emit(
                    "T2", node.lineno,
                    f"np.{func.attr}() on traced value under trace "
                    f"pulls the array to host (use jnp)")
                return
            if func.attr == "device_get":
                self.emit(
                    "T2", node.lineno,
                    "jax.device_get under trace is a host sync")
                return
            # T4: traced value in a shape position.  Names that only
            # appear under an attribute access (x.shape, x.ndim) or a
            # len() are static and fine.
            if func.attr in _SHAPE_BUILDERS and node.args:
                hot = (_names_in(node.args[0]) & traced) \
                    - _static_wrapped(node.args[0], traced)
                if hot:
                    self.emit(
                        "T4", node.lineno,
                        f"traced value {sorted(hot)[0]!r} in the shape "
                        f"argument of {func.attr}(): shapes must be "
                        f"static under jit")
                    return
            if func.attr == "reshape":
                hot = set()
                for a in node.args:
                    hot |= (_names_in(a) & traced) \
                        - _static_wrapped(a, traced)
                if hot:
                    self.emit(
                        "T4", node.lineno,
                        f"traced value {sorted(hot)[0]!r} in reshape() "
                        f"target shape: shapes must be static under "
                        f"jit")
                    return
        elif isinstance(func, ast.Name):
            if func.id in ("float", "int", "bool") and node.args \
                    and _names_in(node.args[0]) & traced:
                names = sorted(_names_in(node.args[0]) & traced)
                self.emit(
                    "T2", node.lineno,
                    f"{func.id}() of traced value {names[0]!r} under "
                    f"trace is a host sync (keep it on device)")
                return
            # one transitive hop: a bare-name call into a same-module
            # function traces that function's body too — its params
            # bound to our traced args become traced
            body = self.bodies.get(func.id)
            if isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = _param_names(body.args)
                passed: Set[str] = set()
                for i, a in enumerate(node.args):
                    if i < len(params) and _names_in(a) & traced:
                        passed.add(params[i])
                if passed:
                    self._body(body, passed, depth + 1)


def _check_t3(unit: FileUnit, sup, findings: List[Finding]) -> None:
    """Per-call jit: flag jax.jit calls whose compiled callable cannot
    outlive the call site."""
    tree = unit.tree

    def emit(line: int, message: str) -> None:
        if sup is not None and sup.suppressed("T3", _line_node(line)):
            return
        findings.append(Finding(unit.path, line, "T3", message))

    # map each jit Call to its innermost enclosing function
    encl: Dict[int, ast.AST] = {}

    def index(node: ast.AST, fn) -> None:
        for child in ast.iter_child_nodes(node):
            here = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.Lambda)) else fn
            encl[id(child)] = fn
            index(child, here)

    index(tree, None)

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_jax_jit(node.func)):
            continue
        fn = encl.get(id(node))
        parent = _parent_of(tree, node)
        # jax.jit(f)(x): traced fresh every call, compiled program
        # dropped on the floor
        if isinstance(parent, ast.Call) and parent.func is node:
            emit(node.lineno,
                 "jax.jit(...)(...) invoked immediately: re-traces "
                 "every call — build the jitted callable once (module "
                 "level, factory, or LRU cache)")
            continue
        if fn is None:
            continue  # module level: compiled once per process
        name = getattr(fn, "name", "<lambda>")
        if name == "make" or any(name.startswith(p)
                                 for p in _FACTORY_PREFIXES):
            continue
        if isinstance(parent, ast.Return):
            continue  # returned: the caller owns its lifetime
        if isinstance(parent, ast.Assign) and any(
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"
                for t in parent.targets):
            continue  # stored on the instance: compiled once per object
        emit(node.lineno,
             f"jax.jit built inside {name!r} neither returned, stored "
             f"on self, nor in a make_* factory: a fresh closure per "
             f"call re-traces every time")


_PARENTS: Dict[int, Dict[int, ast.AST]] = {}


def _parent_of(tree: ast.Module, node: ast.AST) -> Optional[ast.AST]:
    table = _PARENTS.get(id(tree))
    if table is None:
        table = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                table[id(child)] = parent
        _PARENTS[id(tree)] = table
    return table.get(id(node))


def check_file(unit: FileUnit) -> List[Finding]:
    """All T-rules over one file; shares the unit's parse + call graph."""
    if unit.tree is None:
        e = unit.parse_error
        return [Finding(unit.path, (e.lineno or 0) if e else 0, "PARSE",
                        f"syntax error: {e.msg if e else 'unparseable'}")]
    findings: List[Finding] = []
    sup = suppressions_for(unit)
    graph = call_graph_for(unit)
    bodies = graph.bodies if graph is not None else {}
    roots = unit.cached("traceroots",
                        lambda u: _collect_roots(u.tree, bodies))
    checker = _RootChecker(unit, bodies, sup, findings)
    for root in roots:
        checker.check(root)
    _check_t3(unit, sup, findings)
    _PARENTS.pop(id(unit.tree), None)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def analyze(root: Optional[str] = None, *,
            paths: Optional[Iterable[str]] = None,
            program: Optional[Program] = None) -> List[Finding]:
    """Run the static trace-discipline pass.

    Default scope is the known jit/scan/shard_map surfaces
    (``TRACE_TARGET_RELS``) under the package root; pass ``paths`` to
    check arbitrary files (fixtures, new modules)."""
    from .lint import default_root
    program = program if program is not None else Program()
    findings: List[Finding] = []
    if paths is not None:
        for unit in program.units(paths):
            findings.extend(check_file(unit))
    else:
        base = root if root is not None else default_root()
        for rel in TRACE_TARGET_RELS:
            p = os.path.join(base, rel)
            if os.path.exists(p):
                findings.extend(check_file(program.unit(p, rel=rel)))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


# --------------------------------------------------------------------------
# runtime half: the recompile guard
# --------------------------------------------------------------------------

class RecompileError(AssertionError):
    """A warmed hot loop triggered a fresh XLA backend compile."""


class RecompileGuard:
    """Process-wide backend-compile counter fed by jax.monitoring.

    ``install()`` registers one event-duration listener (idempotent);
    ``compiles()`` is the count so far.  jax has no unregister API, so
    the listener stays for the process lifetime — it only bumps an int.
    """

    _lock = threading.Lock()
    _installed = False
    _compiles = 0
    #: the jax-internal event key for a real XLA backend compile
    _EVENT = "/jax/core/compile/backend_compile_duration"

    @classmethod
    def install(cls) -> None:
        with cls._lock:
            if cls._installed:
                return
            import jax.monitoring

            def on_event(event: str, duration: float, **kw) -> None:
                if event == cls._EVENT:
                    with cls._lock:
                        cls._compiles += 1

            jax.monitoring.register_event_duration_secs_listener(on_event)
            cls._installed = True

    @classmethod
    def compiles(cls) -> int:
        with cls._lock:
            return cls._compiles


@contextlib.contextmanager
def expect_no_recompile(label: str = "hot loop"):
    """Assert the enclosed block triggers zero backend compiles."""
    RecompileGuard.install()
    before = RecompileGuard.compiles()
    yield
    grew = RecompileGuard.compiles() - before
    if grew:
        raise RecompileError(
            f"{label}: {grew} backend compile(s) inside a block "
            f"expected to be warm")


#: (id(self), label, abstract signature) -> warmed; cleared per test by
#: the pytest plugin so id() reuse across tests cannot alias
_WARMED: Set[tuple] = set()


def reset_warm() -> None:
    _WARMED.clear()


def _abstract_sig(args, kwargs) -> tuple:
    """Shape/dtype signature: two calls with the same signature must
    reuse the compiled program, so a compile on the second is a
    retrace."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten((args, kwargs))
    out = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            out.append((tuple(leaf.shape), str(leaf.dtype)))
        elif isinstance(leaf, (int, float, bool, str, bytes,
                               type(None))):
            # jit treats python scalars as weak-typed values of one
            # abstract type; only static args key on the VALUE, and
            # those change the signature legitimately
            out.append((type(leaf).__name__, leaf
                        if isinstance(leaf, (int, str, bool)) else None))
        else:
            out.append(type(leaf).__name__)
    return tuple(out)


def guard_hot_loop(fn, label: Optional[str] = None):
    """Wrap a hot-loop method: first call per (instance, signature) is
    the warm-up trace; any later same-signature call that triggers a
    backend compile raises RecompileError (fails the test)."""
    RecompileGuard.install()
    tag = label or getattr(fn, "__qualname__", getattr(fn, "__name__",
                                                       "hot-loop"))

    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        key = (id(self), tag, _abstract_sig(args, kwargs))
        before = RecompileGuard.compiles()
        out = fn(self, *args, **kwargs)
        if key in _WARMED and RecompileGuard.compiles() > before:
            raise RecompileError(
                f"{tag}: warmed hot loop re-traced (backend compile "
                f"after the warm-up call with an identical "
                f"shape/dtype signature)")
        _WARMED.add(key)
        return out

    wrapped.__iotml_traceguard__ = True
    wrapped.__wrapped__ = fn
    return wrapped


#: the hot loops the pytest plugin guards under IOTML_TRACECHECK=1
_GUARD_TARGETS = (
    ("iotml.train.loop", "Trainer", "fit_compiled"),
    ("iotml.parallel.streaming", "ShardedStreamTrainer", "fit_round"),
    ("iotml.parallel.streaming", "ShardedStreamTrainer", "fit_compiled"),
    ("iotml.online.learner", "OnlineLearner", "_update"),
)


def install_runtime_guard() -> List[str]:
    """Patch the known hot loops with guard_hot_loop (idempotent).
    Returns the list of patched qualnames (for the plugin's report)."""
    import importlib

    patched: List[str] = []
    for mod_name, cls_name, meth in _GUARD_TARGETS:
        try:
            mod = importlib.import_module(mod_name)
            cls = getattr(mod, cls_name)
            fn = cls.__dict__.get(meth)
        except Exception:
            continue
        if fn is None or getattr(fn, "__iotml_traceguard__", False):
            continue
        setattr(cls, meth, guard_hot_loop(fn, f"{cls_name}.{meth}"))
        patched.append(f"{cls_name}.{meth}")
    return patched
