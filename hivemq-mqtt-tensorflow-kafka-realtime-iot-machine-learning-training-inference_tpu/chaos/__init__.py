"""iotml.chaos — deterministic fault injection for the whole pipeline.

The reference's only failure story is "Kubernetes restarts the pod"
and its own TODO list says "Test HiveMQ and Kafka failover".  This
subsystem is that test, made a first-class tool: named faultpoints
compiled into the stream/mqtt/serve/train hot paths (`faults.point`),
seeded *replayable* fault schedules (`scenarios`), and an in-process
runner that drives devsim → MQTT → bridge → broker(+replica) →
scorer under a scenario and then PROVES the delivery contracts from
the PR 2 span log and broker state (`runner`).

Determinism rules (the whole point — a failure run you cannot replay
is a failure run you cannot debug):

- a schedule is a pure function of (scenario, seed, records): built
  from one `random.Random(seed)`, expressed in *hit counts* of named
  faultpoints and *published-record counts* — never wall-clock time;
- the runner drives every pipeline stage synchronously from one
  thread, so faultpoint hit sequences are reproducible;
- two runs with the same (scenario, seed, records) produce
  byte-identical schedules and identical invariant verdicts.

Production code imports exactly ONE module from this package — the
shim `iotml.chaos.faults` — and only in the allowlisted modules; lint
rule R7 (iotml.analysis) holds both directions of that boundary.
This `__init__` stays import-light for the same reason: the shim
must not drag scenario/runner (and their jax deps) into hot paths.

CLI:  ``python -m iotml.chaos run --scenario leader-kill-mid-drain
--seed 7 --records 2000`` (and ``--list`` / ``schedule``).
"""
