"""``python -m iotml.chaos`` — deterministic fault-injection CLI.

    python -m iotml.chaos run --scenario leader-kill-mid-drain --seed 7 \
                              --records 2000 [--json] [--spans PATH]
    python -m iotml.chaos run --list
    python -m iotml.chaos schedule --scenario mqtt-flap --seed 7 \
                                   --records 2000

``run`` drives the in-process pipeline under the scenario and prints
injected-fault counts, the invariant verdicts (exit status: 0 iff every
invariant PASSed) and — when the topology carries trace headers — the
PR 2 per-stage latency breakdown of the faulted run.  ``schedule``
prints the canonical schedule text: two invocations with the same
(scenario, seed, records) are byte-identical, which is what CI diffs.
"""

from __future__ import annotations

import argparse
import json
import sys

from .scenarios import SCENARIOS, build


def _print_list() -> None:
    for name in sorted(SCENARIOS):
        _builder, topology, desc = SCENARIOS[name]
        print(f"{name:<24} [{topology:>6}]  {desc}")


def cmd_run(args) -> int:
    if args.list:
        _print_list()
        return 0
    if not args.scenario:
        print("run: --scenario NAME required (see --list)",
              file=sys.stderr)
        return 2
    from .runner import ChaosRunner

    runner = ChaosRunner(args.scenario, seed=args.seed,
                         records=args.records, span_path=args.spans)
    if args.spans and runner.schedule.topology == "wire":
        print("note: --spans has no effect on a wire-topology scenario "
              "(trace headers end at the TCP boundary by design)",
              file=sys.stderr)
    report = runner.run()
    if args.json:
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
        return 0 if report.ok else 1

    print(f"scenario {report.scenario}  seed={report.seed}  "
          f"records={report.records}  topology={report.topology}")
    print(f"published={report.published}  scored={report.scored}  "
          f"rewinds={report.rewinds}  "
          f"accounted_drops={report.dropped_accounted}")
    print("\ninjected faults:")
    if report.injected:
        for label, n in report.injected.items():
            print(f"  {n:>6}  {label}")
    else:
        print("  (none fired)")
    print("\ninvariants:")
    for inv in report.invariants:
        print(f"  {inv.verdict()}")
    print("\nstage latency (obs.tracing breakdown of the faulted run):")
    if report.span_path:
        from ..obs.__main__ import load_spans, print_table, summarize

        stages, e2e = load_spans(report.span_path)
        print_table(summarize(stages, e2e))
        print(f"\nspan log: {report.span_path}")
    else:
        print("  (no spans: wire topology — trace headers end at the "
              "TCP boundary by design)")
    print(f"\nverdict: {'PASS' if report.ok else 'FAIL'}")
    return 0 if report.ok else 1


def cmd_schedule(args) -> int:
    sys.stdout.write(build(args.scenario, seed=args.seed,
                           records=args.records).text())
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.chaos",
        description="deterministic fault injection with invariant-"
                    "checked failure scenarios")
    sub = ap.add_subparsers(dest="cmd")

    rp = sub.add_parser("run", help="drive the pipeline under a "
                                    "scenario and check invariants")
    rp.add_argument("--scenario", default="")
    rp.add_argument("--seed", type=int, default=7)
    rp.add_argument("--records", type=int, default=2000)
    rp.add_argument("--spans", default=None,
                    help="keep the JSONL span log at this path")
    rp.add_argument("--json", action="store_true",
                    help="machine-readable report")
    rp.add_argument("--list", action="store_true",
                    help="enumerate built-in scenarios and exit")

    sp = sub.add_parser("schedule", help="print the canonical (byte-"
                                         "reproducible) fault schedule")
    sp.add_argument("--scenario", required=True)
    sp.add_argument("--seed", type=int, default=7)
    sp.add_argument("--records", type=int, default=2000)

    args = ap.parse_args(argv)
    if args.cmd == "run":
        return cmd_run(args)
    if args.cmd == "schedule":
        return cmd_schedule(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
