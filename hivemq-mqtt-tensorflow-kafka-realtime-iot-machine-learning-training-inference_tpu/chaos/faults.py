"""Faultpoint registry + injection engine — the production-facing shim.

This is the ONE chaos module production code may import (lint R7).  A
faultpoint is a named site compiled into a hot path as a one-line
``chaos.point("name")`` call; with chaos disarmed (the default, and the
only state tests/production ever see unless explicitly armed) the call
reads one module global and returns None — no allocation, no lock.

Armed, the engine counts every traversal of every faultpoint and fires
the scenario's action when a site's hit count enters a scheduled
window.  Generic actions are applied right here so call sites stay one
line:

- ``error``  — raise (ConnectionError/OSError/RuntimeError by name):
  simulated crash / dead socket / unavailable partition;
- ``delay``  — ``time.sleep(seconds)``: stall / slow link.

Site-specific actions (``drop``, ``dup``, ``short_write``, ``skip``)
are *returned* to the call site, which knows what dropping or
duplicating means at that point in the protocol.  A ``drop`` is
recorded in the engine's intentional-loss ledger (count + the current
trace id when tracing is live) unless the scenario marks it
unaccounted — the seeded "silent loss" bug the invariant checker must
catch.

Arming: ``arm(ChaosEngine(schedule.events))`` in-process (the runner
does this), or the environment toggles ``IOTML_CHAOS=1`` +
``IOTML_CHAOS_SCENARIO`` / ``IOTML_CHAOS_SEED`` for any iotml process
(registered in ``iotml.config``'s ``non_config`` set — they configure
the harness around the pipeline, not the pipeline).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Set

from ..obs import metrics as _metrics
from ..obs import tracing

#: every compiled-in injection site, name → what firing there means.
#: Scenarios are validated against this registry at engine build time so
#: a typo'd faultpoint fails loudly instead of silently never firing.
KNOWN_POINTS: Dict[str, str] = {
    "kafka_wire.send": "wire-client socket send: drop connection (error), "
                       "delay, short_write",
    "kafka_wire.recv": "wire-client socket recv: drop connection (error), "
                       "delay",
    "broker.produce": "broker append path: produce error, delay",
    "broker.produce_raw": "RAW_PRODUCE pre-framed batch landing: corrupt "
                          "(flip a byte in the in-flight batch — the "
                          "whole batch must be rejected with "
                          "CORRUPT_MESSAGE before any byte lands), "
                          "error, delay",
    "broker.fetch": "broker fetch path: stall (delay), partition "
                    "unavailable (error)",
    "replica.sync": "follower replication round: pause (delay), skip",
    "mqtt.deliver": "MQTT fan-out delivery: drop, dup, delay",
    "scorer.poll": "scorer drain loop: stall (delay), simulated crash "
                   "(error -> rewind-to-committed redelivery)",
    "trainer.poll": "continuous-trainer poll loop: stall (delay), error",
    "ckpt.write": "checkpoint writer, between serialize and the atomic "
                  "registry publication: crash (error) = killed "
                  "mid-checkpoint with host state gone, registry "
                  "untouched; delay = slow disk (drop-oldest backlog)",
    "registry.commit": "registry publish, between artifact staging and "
                       "the manifest write: crash (error) leaves a "
                       "manifest-less (torn) version dir that readers "
                       "skip and recover() sweeps",
    "store.compact_swap": "segment compaction, between the durable "
                          ".cleaned rewrite and its atomic swap over the "
                          "live segment: crash (error) = compactor killed "
                          "mid-pass (stale tmp left, live segment intact, "
                          "a prefix of segments already swapped); delay = "
                          "slow disk",
    "online.update": "online learner's per-window update loop: stall "
                     "(delay) = a slow incremental step, crash (error) "
                     "= the learner dies mid-stream and must resume "
                     "from its committed cursor",
    "store.tier_upload": "tiered-store upload, between the segment blob "
                         "uploads and the remote manifest commit: crash "
                         "(error) = uploader killed mid-upload, leaving "
                         "staged blobs the manifest never references "
                         "(swept later, never served); delay = slow "
                         "object store",
}

#: runner-orchestrated pseudo-points: process-level acts (killing a wire
#: server is not an inline code path) scheduled by published-record
#: count and executed by the chaos runner between ticks.
RUNNER_POINTS: Dict[str, str] = {
    "runner.kill_leader": "abrupt leader wire-server death (accept loop "
                          "+ every live connection) -> client failover "
                          "promotes the follower",
    "runner.crash_broker": "durable broker process death MID-WRITE (torn "
                           "frame left on the active segment) -> remount "
                           "from the store dir, recovery truncates the "
                           "tail, consumers resume from persisted commits",
    "runner.kill_member": "consumer-group member crash (stops polling, "
                          "never leaves) -> coordinator expires it at "
                          "session timeout, survivors inherit its "
                          "partitions at the committed frontier",
    "runner.kill_shard_leader": "abrupt SHARD leader death on the "
                                "partitioned cluster -> its follower is "
                                "promoted at a bumped epoch; one map "
                                "entry moves, the rest keep serving",
    "runner.kill_follower": "abrupt ISR follower death under acks=all "
                            "load -> the ISR evicts it within the "
                            "staleness window and the quorum re-forms "
                            "without it",
}

#: actions each site actually interprets — validated at engine build so
#: a typo'd action fails as loudly as a typo'd faultpoint (it would
#: otherwise count as injected while doing nothing, a lying report).
POINT_ACTIONS: Dict[str, frozenset] = {
    "kafka_wire.send": frozenset({"error", "delay", "short_write"}),
    "kafka_wire.recv": frozenset({"error", "delay"}),
    "broker.produce": frozenset({"error", "delay"}),
    "broker.produce_raw": frozenset({"corrupt", "error", "delay"}),
    "broker.fetch": frozenset({"error", "delay"}),
    "replica.sync": frozenset({"skip", "delay", "error"}),
    "mqtt.deliver": frozenset({"drop", "dup", "delay"}),
    "scorer.poll": frozenset({"error", "delay"}),
    "trainer.poll": frozenset({"error", "delay"}),
    "ckpt.write": frozenset({"error", "delay"}),
    "registry.commit": frozenset({"error", "delay"}),
    "store.compact_swap": frozenset({"error", "delay"}),
    "store.tier_upload": frozenset({"error", "delay"}),
    "online.update": frozenset({"error", "delay"}),
    "runner.kill_leader": frozenset({"kill_leader"}),
    "runner.crash_broker": frozenset({"crash_broker"}),
    "runner.kill_member": frozenset({"kill_member"}),
    "runner.kill_shard_leader": frozenset({"kill_shard_leader"}),
    "runner.kill_follower": frozenset({"kill_follower"}),
}

_EXCEPTIONS = {"ConnectionError": ConnectionError, "OSError": OSError,
               "RuntimeError": RuntimeError}

chaos_injected = _metrics.default_registry.counter(
    "iotml_chaos_injected_total",
    "faults injected by the chaos engine (label fault=point:action)")


class Action(NamedTuple):
    """A fired fault handed back to its call site."""

    kind: str
    params: dict


class ChaosEngine:
    """Hit-counting fault scheduler over a compiled scenario.

    Thread-safe: hit counters and ledgers mutate under one lock; the
    blocking/raising part of an action is applied AFTER the lock is
    released (a chaos delay must stall the faulted path, never every
    thread traversing any faultpoint)."""

    def __init__(self, events):
        self._lock = threading.Lock()
        self._windows: Dict[str, List[tuple]] = {}
        self.runner_events: List = []
        for ev in sorted(events, key=lambda e: (e.at, e.point, e.action)):
            if ev.point not in POINT_ACTIONS:
                raise ValueError(
                    f"unknown faultpoint {ev.point!r} (known: "
                    f"{sorted(KNOWN_POINTS) + sorted(RUNNER_POINTS)})")
            if ev.action not in POINT_ACTIONS[ev.point]:
                raise ValueError(
                    f"faultpoint {ev.point!r} does not interpret action "
                    f"{ev.action!r} (supported: "
                    f"{sorted(POINT_ACTIONS[ev.point])})")
            exc = dict(ev.params).get("exc")
            if exc is not None and exc not in _EXCEPTIONS:
                raise ValueError(
                    f"unknown exception {exc!r} for {ev.point} "
                    f"(have: {sorted(_EXCEPTIONS)})")
            if ev.point in RUNNER_POINTS:
                self.runner_events.append(ev)
            else:
                self._windows.setdefault(ev.point, []).append(
                    (ev.at, ev.at + max(ev.repeat, 1), ev))
        # at most ONE non-delay event may cover any given hit: a call
        # site consumes a single action, so overlapping site-level
        # events would count as injected without executing — the
        # diverging-report lie this engine exists to rule out.  Delays
        # compose with anything (they apply inline, cumulatively).
        for point, windows in self._windows.items():
            hard = sorted(((lo, hi, ev) for lo, hi, ev in windows
                           if ev.action != "delay"),
                          key=lambda w: (w[0], w[1]))
            for (alo, ahi, aev), (blo, bhi, bev) in zip(hard, hard[1:]):
                if blo < ahi:
                    raise ValueError(
                        f"overlapping non-delay events on {point!r}: "
                        f"{aev.action}@[{alo},{ahi}) and "
                        f"{bev.action}@[{blo},{bhi}) — only one "
                        f"site-level action can execute per hit")
        self.hits: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}
        self.dropped_count = 0
        self.dropped_traces: Set[int] = set()

    # ----------------------------------------------------------- firing
    def fire(self, name: str) -> Optional[Action]:
        """EVERY event whose window covers this hit fires — a drop
        scheduled inside a delay window both delays and drops.  The
        canonical schedule is ground truth: what it lists must be what
        runs, or the byte-identical replay guarantee is a lie."""
        matched = []
        with self._lock:
            hit = self.hits.get(name, 0) + 1
            self.hits[name] = hit
            for lo, hi, ev in self._windows.get(name, ()):
                if lo <= hit < hi:
                    matched.append(ev)
            if not matched:
                return None
            for ev in matched:
                label = f"{name}:{ev.action}"
                self.injected[label] = self.injected.get(label, 0) + 1
                if ev.action == "drop" and \
                        dict(ev.params).get("account", True):
                    # intentional loss: ledger it so the invariant
                    # checker can tell "chaos ate it" from "the
                    # pipeline lost it"
                    self.dropped_count += 1
                    ctx = tracing.current()
                    if ctx is not None:
                        self.dropped_traces.add(ctx.trace_id)
        # blocking/raising OUTSIDE the engine lock: delays apply first
        # (cumulatively), then the at-most-one (build-validated)
        # non-delay event raises or is returned to the call site
        site: Optional[Action] = None
        err = None
        for ev in matched:
            chaos_injected.inc(fault=f"{name}:{ev.action}")
            params = dict(ev.params)
            if ev.action == "delay":
                time.sleep(float(params.get("seconds", 0.001)))
            elif ev.action == "error":
                err = _EXCEPTIONS.get(params.get("exc", "ConnectionError"),
                                      ConnectionError)
            else:
                site = Action(ev.action, params)
        if err is not None:
            raise err(f"chaos[{name}]: injected fault")
        return site

    def due_runner_events(self, records_published: int) -> List:
        """Pop runner-orchestrated events whose record count has come."""
        with self._lock:
            due = [e for e in self.runner_events
                   if e.at <= records_published]
            self.runner_events = [e for e in self.runner_events
                                  if e.at > records_published]
        return due

    def note_runner_fired(self, ev) -> None:
        """Count a runner-orchestrated event as injected — the runner,
        not a faultpoint shim, executes process-level actions."""
        label = f"{ev.point}:{ev.action}"
        with self._lock:
            self.injected[label] = self.injected.get(label, 0) + 1
        chaos_injected.inc(fault=label)


#: the armed engine, or None.  Module-global read is the entire
#: disarmed faultpoint cost.
_engine: Optional[ChaosEngine] = None


def point(name: str) -> Optional[Action]:
    """The faultpoint shim compiled into hot paths."""
    eng = _engine
    if eng is None:
        return None
    return eng.fire(name)


def engine() -> Optional[ChaosEngine]:
    return _engine


def arm(eng: ChaosEngine) -> ChaosEngine:
    global _engine
    _engine = eng
    return eng


def disarm() -> None:
    global _engine
    _engine = None


def arm_from_env(env: Optional[dict] = None) -> Optional[ChaosEngine]:
    """Arm from IOTML_CHAOS/IOTML_CHAOS_{SEED,SCENARIO} — lets any iotml
    process (a test run, a CLI) execute under a seeded schedule.  No-op
    unless IOTML_CHAOS is truthy, so importing this module costs one
    env read in normal processes."""
    env = os.environ if env is None else env
    # same truthiness convention as IOTML_TRACE: only an explicit
    # opt-in arms fault injection — IOTML_CHAOS=false/no/off must
    # disable, never arm-with-defaults
    if env.get("IOTML_CHAOS", "").strip().lower() not in \
            ("1", "true", "yes", "on"):
        return None
    from .scenarios import build  # lazy: scenarios never load when disarmed

    schedule = build(env.get("IOTML_CHAOS_SCENARIO", "mqtt-flap"),
                     seed=int(env.get("IOTML_CHAOS_SEED", "7")),
                     records=int(env.get("IOTML_CHAOS_RECORDS", "1000")))
    return arm(ChaosEngine(schedule.events))


arm_from_env()
