"""Scenario runner + invariant verdicts for chaos schedules.

Drives the reproduction's own pipeline — devsim fleet → MQTT broker →
Kafka bridge → stream broker (→ follower replica, wire topology) →
KSQL-equivalent convert → scorer — in-process and single-threaded
under an armed `faults.ChaosEngine`, then PROVES the delivery
contracts the stack documents:

- ``scored_or_accounted``: every trace id born at publish is closed by
  a ``score`` e2e span OR sits in the chaos engine's intentional-loss
  ledger (span log form of "at-least-once or accounted").
- ``at_least_once_counts``: scored >= published − intentionally
  dropped (the count form, the only form on the wire topology — trace
  headers end at the TCP boundary by design).
- ``commits_monotonic``: every committed offset stream, per (broker,
  group, topic, partition), is non-decreasing — a rewinding commit
  would re-deliver unbounded history or, worse, mask a lost fence.
- ``predictions_contiguous``: the predictions topic holds exactly one
  record per scored row (OutputSequence's gap check + the at-least-
  once duplicate window both counted in ``scored``).
- ``final_commit_at_end``: after the final drain, committed offsets
  equal the log end — nothing polled-but-unscored was fenced behind a
  premature commit.
- ``promotion_loss_bounded`` (wire): the records the promoted follower
  is missing at the instant of leader death are at most the measured
  replication lag — with the runner's sync-before-kill, exactly zero.

Determinism: one thread drives every stage (the follower's sync loop
is stepped synchronously, never started as a thread), so faultpoint
hit sequences — and therefore verdicts — replay exactly.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Dict, List, Optional, Tuple

from . import faults, scenarios
from .scenarios import CARS_PER_TICK, Schedule

#: trace-birth stages (PR 2): a trace with one of these spans entered
#: the pipeline and is owed a score or an accounting.
BIRTH_STAGES = ("mqtt_publish", "devsim_publish")

IN_TOPIC = "SENSOR_DATA_S_AVRO"
PRED_TOPIC = "model-predictions"
GROUP = "chaos-scorer"


@dataclasses.dataclass(frozen=True)
class Invariant:
    name: str
    ok: bool
    detail: str

    def verdict(self) -> str:
        return f"{'PASS' if self.ok else 'FAIL'}  {self.name}: {self.detail}"


@dataclasses.dataclass
class ChaosReport:
    scenario: str
    seed: int
    records: int
    topology: str
    published: int
    scored: int
    rewinds: int
    dropped_accounted: int
    injected: Dict[str, int]
    invariants: List[Invariant]
    span_path: Optional[str]

    @property
    def ok(self) -> bool:
        return all(i.ok for i in self.invariants)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ok"] = self.ok
        return d


# ----------------------------------------------------------- invariants
def _check_commits_monotonic(commit_log: List[tuple]) -> Invariant:
    streams: Dict[tuple, int] = {}
    bad = []
    for tag, group, topic, part, off in commit_log:
        key = (tag, group, topic, part)
        if off < streams.get(key, -1):
            bad.append((key, streams[key], off))
        streams[key] = max(streams.get(key, -1), off)
    return Invariant(
        "commits_monotonic", not bad,
        f"{len(commit_log)} commits over {len(streams)} offset streams"
        + (f"; REGRESSIONS {bad[:4]}" if bad else ", all non-decreasing"))


def _check_spans_accounted(span_path: str,
                           dropped_traces) -> Invariant:
    born, closed = set(), set()
    with open(span_path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            if doc.get("kind") == "span" and doc.get("stage") in BIRTH_STAGES:
                born.add(doc["trace"])
            elif doc.get("kind") == "e2e" and doc.get("closer") == "score":
                closed.add(doc["trace"])
    ledger = {f"{tid:016x}" for tid in dropped_traces}
    missing = born - closed - ledger
    return Invariant(
        "scored_or_accounted", not missing,
        f"{len(born)} traces born, {len(closed & born)} scored, "
        f"{len(ledger & born)} accounted as chaos drops"
        + (f"; {len(missing)} SILENTLY LOST "
           f"(e.g. {sorted(missing)[:3]})" if missing else ""))


def _check_counts(published: int, scored: int, dropped: int) -> Invariant:
    ok = scored >= published - dropped
    return Invariant(
        "at_least_once_counts", ok,
        f"published={published} scored={scored} "
        f"intentionally_dropped={dropped}"
        + ("" if ok else f"; {published - dropped - scored} records "
                         f"unaccounted for"))


def _check_predictions(broker, scored: int) -> Invariant:
    end = broker.end_offset(PRED_TOPIC, 0)
    ok = end == scored
    return Invariant(
        "predictions_contiguous", ok,
        f"predictions end offset {end} == rows scored {scored}"
        if ok else f"predictions end offset {end} != rows scored {scored}")


def _check_final_commit(broker, topic: str, parts: int) -> Invariant:
    gaps = []
    for p in range(parts):
        committed = broker.committed(GROUP, topic, p)
        end = broker.end_offset(topic, p)
        if committed != end:
            gaps.append((p, committed, end))
    return Invariant(
        "final_commit_at_end", not gaps,
        "committed == log end on every partition" if not gaps
        else f"partitions behind/ahead at end: {gaps}")


def _record_commits(broker, log: List[tuple], tag: str) -> None:
    """Shadow a Broker instance's commit paths with history-recording
    wrappers — the monotonicity invariant needs the sequence, and the
    broker (correctly) stores only the latest value.  Both entry points
    are wrapped: StreamConsumer.commit prefers the batched commit_many
    when the broker offers it."""
    orig = broker.commit

    def commit(group, topic, partition, next_offset):
        log.append((tag, group, topic, partition, next_offset))
        return orig(group, topic, partition, next_offset)

    broker.commit = commit
    orig_many = getattr(broker, "commit_many", None)
    if orig_many is not None:
        def commit_many(group, topic, entries):
            for p, off in entries:
                log.append((tag, group, topic, p, off))
            return orig_many(group, topic, entries)

        broker.commit_many = commit_many


# --------------------------------------------------------------- runner
class ChaosRunner:
    """Compile a scenario, drive the pipeline under it, return the
    report.  ``span_path`` keeps the JSONL span log (default: a temp
    file, path reported) for the CLI's stage-latency breakdown."""

    def __init__(self, scenario: str, seed: int = 7, records: int = 1000,
                 span_path: Optional[str] = None):
        self.schedule: Schedule = scenarios.build(scenario, seed=seed,
                                                  records=records)
        self.span_path = span_path

    # ------------------------------------------------------------ entry
    def run(self) -> ChaosReport:
        from ..obs import tracing

        eng = faults.arm(faults.ChaosEngine(self.schedule.events))
        # span-log invariants need trace headers end to end: the inproc
        # AND store topologies carry them (the durable log round-trips
        # headers in their transport byte form); only the wire topology
        # loses them at the TCP boundary by design
        trace_inproc = self.schedule.topology in ("inproc", "store",
                                                  "online")
        prev = (tracing.ENABLED, tracing._SAMPLE, tracing._PATH)
        span_path = self.span_path
        if trace_inproc:
            if span_path is None:
                fd, span_path = tempfile.mkstemp(prefix="iotml_chaos_",
                                                 suffix=".jsonl")
                os.close(fd)
            open(span_path, "w").close()  # fresh log per run
            tracing.flush()  # drain any prior spans into the OLD sinks
            tracing.configure(enabled=True, sample=1.0, path=span_path)
            tracing.reset()
        try:
            if self.schedule.topology == "wire":
                report = self._run_wire(eng)
            elif self.schedule.topology == "store":
                report = self._run_store(eng, span_path)
            elif self.schedule.topology == "cluster":
                report = self._run_cluster(eng)
            elif self.schedule.topology == "replication":
                report = self._run_replication(eng)
            elif self.schedule.topology == "mlops":
                report = self._run_mlops(eng)
            elif self.schedule.topology == "online":
                report = self._run_online(eng, span_path)
            elif self.schedule.topology == "obs":
                report = self._run_obs(eng)
            else:
                report = self._run_inproc(eng, span_path)
        finally:
            faults.disarm()
            if trace_inproc:
                tracing.flush()
                tracing.configure(enabled=prev[0], sample=prev[1],
                                  path=prev[2] if prev[2] else "")
        return report

    # ------------------------------------------------- shared pipeline
    @staticmethod
    def _make_scorer(broker, consumer):
        import numpy as np

        from ..data.dataset import SensorBatches
        from ..models.autoencoder import CAR_AUTOENCODER
        from ..serve.scorer import StreamScorer
        from ..stream.producer import OutputSequence
        from ..train.loop import Trainer

        trainer = Trainer(CAR_AUTOENCODER)
        trainer._ensure_state(np.zeros((100, 18), np.float32))
        batches = SensorBatches(consumer, batch_size=100)
        out = OutputSequence(broker, PRED_TOPIC, partition=0)
        return StreamScorer(CAR_AUTOENCODER, trainer.state.params,
                            batches, out)

    # ---------------------------------------------------------- inproc
    def _run_inproc(self, eng: faults.ChaosEngine,
                    span_path: str) -> ChaosReport:
        from ..gen.simulator import FleetGenerator, FleetScenario
        from ..mqtt.bridge import KafkaBridge
        from ..mqtt.broker import MqttBroker
        from ..obs import tracing
        from ..stream.broker import Broker
        from ..stream.consumer import StreamConsumer
        from ..streamproc.tasks import JsonToAvro

        mqtt = MqttBroker()
        stream = Broker()
        commit_log: List[tuple] = []
        _record_commits(stream, commit_log, "stream")
        KafkaBridge(mqtt, stream, partitions=2)
        task = JsonToAvro(stream, src="sensor-data", dst=IN_TOPIC,
                          partitions=2)
        parts = stream.topic(IN_TOPIC).partitions
        consumer = StreamConsumer(
            stream, [f"{IN_TOPIC}:{p}:0" for p in range(parts)],
            group=GROUP)
        scorer = self._make_scorer(stream, consumer)

        gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK,
                                           seed=self.schedule.seed))
        published = rewinds = 0
        ticks = max(1, -(-self.schedule.records // CARS_PER_TICK))

        def drive_once():
            nonlocal rewinds
            try:
                task.process_available()
            except ConnectionError:
                task.consumer.rewind_to_committed()
                rewinds += 1
            try:
                return scorer.score_available()
            except ConnectionError:
                consumer.rewind_to_committed()
                rewinds += 1
                return -1

        for _ in range(ticks):
            published += self._publish_tick_mqtt(gen, mqtt)
            drive_once()
            tracing.flush()  # incremental: bound the per-thread buffers
        for _ in range(64):  # final drain: outlast any remaining window
            n = drive_once()
            if n == 0 and consumer.at_end() and task.consumer.at_end():
                break
        tracing.flush()

        invariants = [
            _check_spans_accounted(span_path, eng.dropped_traces),
            _check_counts(published, scorer.scored, eng.dropped_count),
            _check_commits_monotonic(commit_log),
            _check_predictions(stream, scorer.scored),
            _check_final_commit(stream, IN_TOPIC, parts),
        ]
        return ChaosReport(
            scenario=self.schedule.name, seed=self.schedule.seed,
            records=self.schedule.records, topology="inproc",
            published=published, scored=scorer.scored, rewinds=rewinds,
            dropped_accounted=eng.dropped_count,
            injected=dict(sorted(eng.injected.items())),
            invariants=invariants, span_path=span_path)

    # ------------------------------------------------------------ online
    def _run_online(self, eng: faults.ChaosEngine,
                    span_path: str) -> ChaosReport:
        """drift-storm: regional drift + mqtt-flap concurrently, over
        the full MQTT → bridge → convert → online-learner + scorer
        pipeline with a live registry between them.

        The drift half is seeded topology state (an AdversarialFleet
        whose cohorts all shift at mid-stream); the schedule injects
        the flap half at ``mqtt.deliver``.  Invariants: the learner
        detects the drift and its adaptation CONVERGES, the adapted
        model reaches the scorer through the registry (hot-swap), and
        the swap costs nothing — every surviving record is scored
        exactly once (scored_or_accounted + contiguous predictions +
        monotonic commits across both consumer groups)."""
        import shutil
        import tempfile

        from ..gen.scenarios import AdversarialFleet
        from ..gen.scenarios import condition as fleet_condition
        from ..gen.simulator import FleetScenario
        from ..mlops import ModelRegistry, RegistryWatcher
        from ..mqtt.bridge import KafkaBridge
        from ..mqtt.broker import MqttBroker
        from ..obs import tracing
        from ..online.learner import OnlineLearner
        from ..stream.broker import Broker
        from ..stream.consumer import StreamConsumer

        mqtt = MqttBroker()
        stream = Broker()
        commit_log: List[tuple] = []
        _record_commits(stream, commit_log, "stream")
        KafkaBridge(mqtt, stream, partitions=2)
        from ..streamproc.tasks import JsonToAvro

        task = JsonToAvro(stream, src="sensor-data", dst=IN_TOPIC,
                          partitions=2)
        parts = stream.topic(IN_TOPIC).partitions
        ticks = max(1, -(-self.schedule.records // CARS_PER_TICK))
        fleet = AdversarialFleet(
            FleetScenario(num_cars=CARS_PER_TICK,
                          seed=self.schedule.seed, failure_rate=0.02),
            fleet_condition("drift-storm", drift_tick=ticks // 2))
        root = tempfile.mkdtemp(prefix="iotml_chaos_online_")
        try:
            registry = ModelRegistry(root)
            learner = OnlineLearner(stream, IN_TOPIC,
                                    registry=registry,
                                    group="chaos-online",
                                    window=CARS_PER_TICK,
                                    publish_every=8)
            consumer = StreamConsumer(
                stream, [f"{IN_TOPIC}:{p}:0" for p in range(parts)],
                group=GROUP)
            scorer = self._make_scorer(stream, consumer)
            watcher = RegistryWatcher(registry, scorers=[scorer])

            published = rewinds = 0

            def drive_once():
                nonlocal rewinds
                try:
                    task.process_available()
                except ConnectionError:
                    task.consumer.rewind_to_committed()
                    rewinds += 1
                learner.process_available()
                learner.write_published()
                watcher.poll_once()
                try:
                    return scorer.score_available()
                except ConnectionError:
                    consumer.rewind_to_committed()
                    rewinds += 1
                    return -1

            for _ in range(ticks):
                published += fleet.publish_mqtt(mqtt, n_ticks=1)
                drive_once()
                tracing.flush()
            for _ in range(64):  # final drain
                n = drive_once()
                if n == 0 and consumer.at_end() \
                        and task.consumer.at_end() \
                        and learner.consumer.at_end():
                    break
            learner.write_published()
            watcher.poll_once()
            tracing.flush()

            mon = learner.monitor
            detections = [a for a in learner.adaptations]
            latest = registry.latest()
            invariants = [
                Invariant(
                    "drift_detected",
                    mon.drifts >= 1 and bool(detections),
                    f"{mon.drifts} drift episode(s) on the error "
                    f"signal (adaptations: {detections[:4]})"),
                Invariant(
                    "adaptation_converged",
                    mon.converged >= 1,
                    f"{mon.converged} episode(s) converged "
                    f"(state {mon.state!r}, baseline "
                    f"{mon.baseline and round(mon.baseline, 4)})"),
                Invariant(
                    "adapted_model_swapped",
                    latest is not None and latest >= 1
                    and scorer.model_version == latest
                    and watcher.swaps >= 1,
                    f"scorer serving registry v{scorer.model_version} "
                    f"== tip v{latest} after {watcher.swaps} hot-"
                    f"swap(s) under the storm"),
                _check_spans_accounted(span_path, eng.dropped_traces),
                _check_counts(published, scorer.scored,
                              eng.dropped_count),
                _check_commits_monotonic(commit_log),
                _check_predictions(stream, scorer.scored),
                _check_final_commit(stream, IN_TOPIC, parts),
            ]
            return ChaosReport(
                scenario=self.schedule.name, seed=self.schedule.seed,
                records=self.schedule.records, topology="online",
                published=published, scored=scorer.scored,
                rewinds=rewinds, dropped_accounted=eng.dropped_count,
                injected=dict(sorted(eng.injected.items())),
                invariants=invariants, span_path=span_path)
        finally:
            shutil.rmtree(root, ignore_errors=True)

    # ------------------------------------------------------------- store
    def _run_store(self, eng: faults.ChaosEngine,
                   span_path: str) -> ChaosReport:
        """The durable-broker crash drill: the same inproc pipeline over
        a Broker mounted on the segmented store (``fsync=always``), the
        process "killed" mid-write at the scheduled record count (the
        broker object is abandoned un-flushed with a torn frame left on
        the active segment — the exact on-disk artifact of a real kill),
        then REMOUNTED: recovery truncates the torn tail, every record
        acked before the kill must re-serve byte-identically, and a
        restarted pipeline (fresh task/consumer/scorer, cursors
        ``from_committed``) finishes the stream with the PR 3 delivery
        invariants intact."""
        import shutil
        import tempfile

        store_dir = tempfile.mkdtemp(prefix="iotml_chaos_store_")
        try:
            if self.schedule.name == "compaction-under-crash":
                return self._run_compact_in(eng, span_path, store_dir)
            if self.schedule.name == "tier-upload-crash":
                return self._run_tiered_in(eng, span_path, store_dir)
            return self._run_store_in(eng, span_path, store_dir)
        finally:
            # CI/smoke run this scenario repeatedly; a leaked segment
            # dir per run is unbounded /tmp growth
            shutil.rmtree(store_dir, ignore_errors=True)

    def _run_store_in(self, eng: faults.ChaosEngine, span_path: str,
                      store_dir: str) -> ChaosReport:
        from ..gen.simulator import FleetGenerator, FleetScenario
        from ..mqtt.bridge import KafkaBridge
        from ..mqtt.broker import MqttBroker
        from ..obs import tracing
        from ..store import StorePolicy
        from ..stream.broker import Broker
        from ..stream.consumer import StreamConsumer
        from ..streamproc.tasks import JsonToAvro

        # small segments so the crash lands on a log with real rolls
        # behind it; fsync=always is the acked=durable contract the
        # zero-loss invariant rides on
        policy = dict(fsync="always", segment_bytes=64 * 1024)
        commit_log: List[tuple] = []
        rewinds = 0
        published = 0
        gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK,
                                           seed=self.schedule.seed))
        ticks = max(1, -(-self.schedule.records // CARS_PER_TICK))

        def build_pipeline(broker):
            """One process incarnation: ingress + transform + scorer,
            every cursor resuming from the broker's committed offsets."""
            mqtt = MqttBroker()
            KafkaBridge(mqtt, broker, partitions=2)
            task = JsonToAvro(broker, src="sensor-data", dst=IN_TOPIC,
                              partitions=2)
            parts = broker.topic(IN_TOPIC).partitions
            consumer = StreamConsumer.from_committed(
                broker, IN_TOPIC, range(parts), group=GROUP)
            scorer = self._make_scorer(broker, consumer)
            return mqtt, task, consumer, scorer, parts

        broker = Broker(store_dir=store_dir,
                        store_policy=StorePolicy(**policy))
        _record_commits(broker, commit_log, "stream")
        mqtt, task, consumer, scorer, parts = build_pipeline(broker)

        def drive_once():
            nonlocal rewinds
            try:
                task.process_available()
            except ConnectionError:
                task.consumer.rewind_to_committed()
                rewinds += 1
            try:
                return scorer.score_available()
            except ConnectionError:
                consumer.rewind_to_committed()
                rewinds += 1
                return -1

        crash = {"done": False, "torn": 0, "acked": {}, "committed": {},
                 "recovered_end": {}, "truncated": 0, "resumed_at": {},
                 "scored_pre": 0, "replayed_match": False}
        scored_total = 0

        def crash_and_recover():
            nonlocal broker, mqtt, task, consumer, scorer, parts
            nonlocal scored_total
            # --- the kill: snapshot what was ACKED, leave a torn frame
            for t in (IN_TOPIC, PRED_TOPIC, "sensor-data"):
                for p in range(broker.topic(t).partitions):
                    crash["acked"][(t, p)] = broker.end_offset(t, p)
            for p in range(parts):
                crash["committed"][p] = broker.committed(GROUP, IN_TOPIC, p)
            pre_crash = broker.fetch(IN_TOPIC, 0,
                                     broker.begin_offset(IN_TOPIC, 0), 10**6)
            crash["torn"] = broker.store.log_for(
                IN_TOPIC, 0).simulate_torn_write()
            crash["scored_pre"] = scorer.scored
            scored_total += scorer.scored
            # the old incarnation is DEAD: nothing flushes, nothing
            # closes — fsync=always already made every ack durable
            broker = Broker(store_dir=store_dir,
                            store_policy=StorePolicy(**policy))
            _record_commits(broker, commit_log, "stream")
            crash["truncated"] = broker.store.recovered_truncated_bytes()
            for (t, p), end in crash["acked"].items():
                crash["recovered_end"][(t, p)] = broker.end_offset(t, p)
            # byte-identical replay: the full pre-crash read repeats
            post_crash = broker.fetch(IN_TOPIC, 0,
                                      broker.begin_offset(IN_TOPIC, 0),
                                      10**6)
            crash["replayed_match"] = \
                [(m.offset, m.key, m.value, m.timestamp_ms)
                 for m in pre_crash] == \
                [(m.offset, m.key, m.value, m.timestamp_ms)
                 for m in post_crash]
            mqtt, task, consumer, scorer, parts = build_pipeline(broker)
            crash["resumed_at"] = {p: off for _t, p, off
                                   in consumer.positions()}
            crash["done"] = True

        def run_due_events():
            for ev in eng.due_runner_events(published):
                if ev.action == "crash_broker" and not crash["done"]:
                    crash_and_recover()
                    eng.note_runner_fired(ev)

        for _ in range(ticks):
            run_due_events()
            published += self._publish_tick_mqtt(gen, mqtt)
            drive_once()
            tracing.flush()
        run_due_events()
        for _ in range(64):
            n = drive_once()
            if n == 0 and consumer.at_end() and task.consumer.at_end():
                break
        tracing.flush()
        scored_total += scorer.scored
        broker.close()

        lost = {k: (acked, crash["recovered_end"].get(k))
                for k, acked in crash["acked"].items()
                if crash["recovered_end"].get(k) != acked}
        resumed_bad = {p: (crash["resumed_at"].get(p), committed)
                       for p, committed in crash["committed"].items()
                       if committed is not None
                       and crash["resumed_at"].get(p) != committed}
        invariants = [
            _check_spans_accounted(span_path, eng.dropped_traces),
            _check_counts(published, scored_total, eng.dropped_count),
            _check_commits_monotonic(commit_log),
            _check_predictions(broker, scored_total),
            _check_final_commit(broker, IN_TOPIC, parts),
            Invariant(
                "acked_records_survive_crash",
                crash["done"] and not lost,
                ("broker never crashed" if not crash["done"] else
                 f"every pre-kill acked offset re-served after remount "
                 f"({sum(crash['acked'].values())} records across "
                 f"{len(crash['acked'])} partitions)" if not lost else
                 f"ACKED RECORDS LOST after recovery: {lost}")),
            Invariant(
                "replay_byte_identical",
                crash["replayed_match"],
                "pre-crash read == post-recovery read (offset, key, "
                "value, timestamp all equal)" if crash["replayed_match"]
                else "post-recovery replay DIVERGED from the acked read"),
            Invariant(
                "torn_tail_truncated",
                crash["done"] and crash["truncated"] == crash["torn"],
                f"recovery truncated {crash['truncated']} bytes == the "
                f"{crash['torn']} torn bytes the kill left "
                f"(iotml_store_recovery_truncated_bytes)"
                if crash["truncated"] == crash["torn"] else
                f"recovery truncated {crash['truncated']} bytes, kill "
                f"left {crash['torn']}"),
            Invariant(
                "consumer_resumed_from_committed",
                crash["done"] and not resumed_bad,
                "restarted consumer cursors == persisted committed "
                "offsets" if not resumed_bad else
                f"cursors diverged from persisted commits: {resumed_bad}"),
        ]
        return ChaosReport(
            scenario=self.schedule.name, seed=self.schedule.seed,
            records=self.schedule.records, topology="store",
            published=published, scored=scored_total, rewinds=rewinds,
            dropped_accounted=eng.dropped_count,
            injected=dict(sorted(eng.injected.items())),
            invariants=invariants, span_path=span_path)

    # --------------------------------------------------------- compaction
    def _run_compact_in(self, eng: faults.ChaosEngine, span_path: str,
                        store_dir: str) -> ChaosReport:
        """The compaction-under-crash drill: a TwinService changelogs
        per-car state into the compacted ``CAR_TWIN`` topic on a durable
        broker, then the compactor is KILLED at a scheduled mid-pass
        segment swap (injected error at ``store.compact_swap``: the
        ``.cleaned`` rewrite is durable, the live segment untouched, a
        prefix of earlier segments already swapped) and the store is
        REMOUNTED.  Proven: the stale tmp is swept at mount, no key (or
        tombstone) is lost, every surviving record re-serves
        byte-identically, and a finished pass stays byte-stable across a
        second remount."""
        import glob

        from ..gen.simulator import FleetGenerator, FleetScenario
        from ..store import StorePolicy
        from ..store.compact import CLEANED_SUFFIX
        from ..stream.broker import Broker
        from ..twin import CHANGELOG_TOPIC, TwinService

        policy = dict(fsync="interval", segment_bytes=16 * 1024,
                      compact_grace_ms=10 ** 9)
        parts = 2

        def read_all(b):
            """Every live changelog record, as comparable tuples (fetch
            batches end at compaction holes; the loop walks across)."""
            out = {}
            for p in range(parts):
                recs = []
                off = b.begin_offset(CHANGELOG_TOPIC, p)
                end = b.end_offset(CHANGELOG_TOPIC, p)
                while off < end:
                    batch = b.fetch(CHANGELOG_TOPIC, p, off, 1 << 20)
                    if not batch:
                        break
                    recs.extend((m.offset, m.key, m.value, m.timestamp_ms)
                                for m in batch)
                    off = batch[-1].offset + 1
                out[p] = recs
            return out

        def latest_per_key(reads):
            latest = {}
            for p, recs in reads.items():
                for off, key, value, ts in recs:
                    latest[(p, key)] = (off, value, ts)
            return latest

        def cleaned_tmps():
            return sum(
                len(glob.glob(os.path.join(d, "*" + CLEANED_SUFFIX)))
                for d in part_dirs)

        broker = Broker(store_dir=store_dir,
                        store_policy=StorePolicy(**policy))
        broker.create_topic(IN_TOPIC, partitions=parts)
        svc = TwinService(broker)
        gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK,
                                           seed=self.schedule.seed))
        ticks = max(2, -(-self.schedule.records // CARS_PER_TICK))
        published = 0
        for _ in range(ticks):
            published += gen.publish(broker, IN_TOPIC, n_ticks=1,
                                     partitions=parts)
            svc.pump_once()
        while svc.pump_once():
            pass
        svc.retire(svc.cars()[-1])  # a tombstone rides the changelog
        table_snapshot = svc.table.snapshot()
        part_dirs = [broker.store.log_for(CHANGELOG_TOPIC, p).dir
                     for p in range(parts)]

        pre_kill = read_all(broker)
        latest_pre = latest_per_key(pre_kill)
        for p in range(parts):
            broker.store.log_for(CHANGELOG_TOPIC, p).roll()

        # --- the kill: the scheduled error fires INSIDE the pass, at
        # the gap between the durable rewrite and its atomic swap
        crashed = False
        try:
            broker.run_compaction(force=True)
        except RuntimeError:
            crashed = True
        tmps_left = cleaned_tmps()
        # the crashed incarnation is DEAD: nothing flushed, nothing
        # closed.  Remount from disk.
        broker2 = Broker(store_dir=store_dir,
                         store_policy=StorePolicy(**policy))
        tmps_after = cleaned_tmps()
        post_kill = read_all(broker2)
        pre_sets = {p: set(recs) for p, recs in pre_kill.items()}
        foreign = [r for p, recs in post_kill.items() for r in recs
                   if r not in pre_sets[p]]

        # finish the interrupted job on the remounted store, then
        # remount AGAIN: the finished pass must be byte-stable
        stats = broker2.run_compaction(force=True)
        removed = sum(s.records_removed for s in stats.values())
        done = read_all(broker2)
        broker3 = Broker(store_dir=store_dir,
                         store_policy=StorePolicy(**policy))
        stable = read_all(broker3)
        svc2 = TwinService(broker3)
        rebuilt = svc2.table.snapshot()
        broker3.close()

        keys_ok = (latest_per_key(post_kill) == latest_pre
                   and latest_per_key(done) == latest_pre
                   and latest_per_key(stable) == latest_pre)
        invariants = [
            Invariant(
                "crash_injected",
                crashed and tmps_left > 0,
                f"compactor killed mid-pass with {tmps_left} durable "
                f".cleaned tmp(s) left unswapped" if crashed else
                "the scheduled store.compact_swap error NEVER FIRED"),
            Invariant(
                "cleaned_tmp_swept",
                tmps_after == 0,
                "remount swept every stale .cleaned rewrite tmp"
                if tmps_after == 0 else
                f"{tmps_after} stale .cleaned tmp(s) SURVIVED the mount"),
            Invariant(
                "no_key_lost",
                keys_ok,
                f"latest-per-key table identical across kill, remount "
                f"and finished compaction ({len(latest_pre)} keys incl. "
                f"the tombstone)" if keys_ok else
                "latest-per-key table DIVERGED across the crash"),
            Invariant(
                "survivors_byte_identical",
                not foreign,
                "every post-remount record existed pre-kill with "
                "identical (offset, key, value, timestamp) — compaction "
                "only ever removes" if not foreign else
                f"{len(foreign)} record(s) MUTATED by the crashed pass"),
            Invariant(
                "compacted_reads_byte_stable",
                done == stable and removed > 0,
                f"finished pass removed {removed} shadowed records and "
                f"reads are byte-identical across a remount"
                if done == stable and removed > 0 else
                f"compacted reads NOT byte-stable (removed={removed})"),
            Invariant(
                "twin_rebuild_equals_snapshot",
                rebuilt == table_snapshot,
                f"twin table rebuilt from the compacted changelog == the "
                f"live service's snapshot ({len(table_snapshot)} cars)"
                if rebuilt == table_snapshot else
                "rebuilt twin table DIVERGED from the live snapshot"),
        ]
        return ChaosReport(
            scenario=self.schedule.name, seed=self.schedule.seed,
            records=self.schedule.records, topology="store",
            published=published, scored=svc.applied, rewinds=0,
            dropped_accounted=eng.dropped_count,
            injected=dict(sorted(eng.injected.items())),
            invariants=invariants, span_path=span_path)

    # ------------------------------------------------------------- tiered
    def _run_tiered_in(self, eng: faults.ChaosEngine, span_path: str,
                       store_dir: str) -> ChaosReport:
        """The tier-upload-crash drill: a durable broker tiers sealed
        segments into a local-directory ArtifactStore, and the uploader
        is KILLED at the scheduled ``store.tier_upload`` traversal —
        the gap between the segment blob uploads and the remote
        manifest commit (staged blobs exist remotely, nothing
        references them).  Proven: a cold reader trusting only the
        manifest serves EXACTLY the committed prefix (never the torn
        upload), the local copy stays byte-authoritative across the
        kill, the finished pass sweeps the garbage, and — after the hot
        tier is fully evicted — the whole history replays through the
        REMOTE leg byte-identical to the pre-kill reads, surviving a
        remount too."""
        import shutil
        import tempfile

        from ..gen.simulator import FleetGenerator, FleetScenario
        from ..store import (RemoteTier, StorePolicy, TieredLog,
                             TierPolicy)
        from ..stream.broker import Broker

        remote_dir = tempfile.mkdtemp(prefix="iotml_chaos_tier_")
        cold_dir = tempfile.mkdtemp(prefix="iotml_chaos_cold_")
        policy = dict(fsync="interval", segment_bytes=16 * 1024)
        parts = 2

        def read_all(b):
            """Every live record per partition as comparable tuples
            (fetch batches end at tier boundaries; the loop crosses)."""
            out = {}
            for p in range(parts):
                recs = []
                off = b.begin_offset(IN_TOPIC, p)
                end = b.end_offset(IN_TOPIC, p)
                while off < end:
                    batch = b.fetch(IN_TOPIC, p, off, 1 << 20)
                    if not batch:
                        break
                    recs.extend((m.offset, m.key, m.value, m.timestamp_ms)
                                for m in batch)
                    off = batch[-1].offset + 1
                out[p] = recs
            return out

        try:
            broker = Broker(store_dir=store_dir,
                            store_policy=StorePolicy(**policy),
                            tier=TierPolicy(uri=remote_dir))
            broker.create_topic(IN_TOPIC, partitions=parts)
            gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK,
                                               seed=self.schedule.seed))
            ticks = max(2, -(-self.schedule.records // CARS_PER_TICK))
            published = 0
            for _ in range(ticks):
                published += gen.publish(broker, IN_TOPIC, n_ticks=1,
                                         partitions=parts)
            logs = [broker.store.log_for(IN_TOPIC, p) for p in range(parts)]
            for log in logs:
                log.roll()  # sealed segments exist before the first pass
            pre_kill = read_all(broker)
            store_obj = broker.store._tier_store

            def unreferenced(p):
                """Blobs under partition p's prefix the manifest does
                not name — the torn upload's remote footprint."""
                tierp = logs[p].remote
                referenced = {tierp._manifest_name}
                for m in tierp.load():
                    for sfx in (".log", ".index", ".timeindex"):
                        referenced.add(tierp._blob(m.base, sfx))
                return [n for n in store_obj.list(tierp.prefix)
                        if n not in referenced]

            # --- the kill: the scheduled error fires INSIDE an upload,
            # after the blobs landed and before the manifest commit
            crashed = False
            try:
                broker.run_tiering()
            except RuntimeError:
                crashed = True
            committed = {p: logs[p].remote_metas() for p in range(parts)}
            torn = {p: unreferenced(p) for p in range(parts)}
            any_torn = any(torn.values())

            # local authority: every pre-kill byte still re-serves
            local_ok = read_all(broker) == pre_kill

            # a COLD reader (fresh empty dir, manifest-only trust — the
            # follower-bootstrap path) must serve exactly the committed
            # prefix, every segment CRC-verified, and nothing staged
            cold_ok = True
            for p in range(parts):
                cold = TieredLog(
                    os.path.join(cold_dir, str(p)),
                    policy=StorePolicy(fsync="never"),
                    remote=RemoteTier(store_obj, prefix=logs[p].remote.prefix),
                    tier=TierPolicy(uri=remote_dir))
                recs = []
                off = cold.base_offset
                end = max((m.next for m in cold.remote_metas()),
                          default=off)
                while off < end:
                    batch = cold.read_from(off, 4096)
                    if not batch:
                        break
                    recs.extend((o, k, v, ts) for o, k, v, ts, _h in batch)
                    off = recs[-1][0] + 1
                cold.close()
                want = [r for r in pre_kill[p]
                        if committed[p]
                        and r[0] < max(m.next for m in committed[p])]
                if recs != want:
                    cold_ok = False

            # --- finish the job: the spent event doesn't re-fire; the
            # completed pass commits everything and sweeps the garbage
            finished = True
            try:
                stats = broker.run_tiering()
            except RuntimeError:
                finished, stats = False, {}
            garbage_left = sum(len(unreferenced(p)) for p in range(parts))

            # hot tier fully evicted: history now serves through the
            # REMOTE leg only (plus the live active segment locally)
            for log in logs:
                log.evict_hot(budget_bytes=0)
            evicted = all(log.local_base_offset > log.base_offset
                          for log in logs)
            remote_replay = read_all(broker)
            remote_used = any(len(log.cache) for log in logs)
            replay_ok = remote_replay == pre_kill and evicted and remote_used

            # ...and a remount sees the same bytes (manifest + local
            # tail recompose the one log)
            broker.close()
            broker2 = Broker(store_dir=store_dir,
                             store_policy=StorePolicy(**policy),
                             tier=TierPolicy(uri=remote_dir))
            stable = read_all(broker2) == pre_kill
            broker2.close()
        finally:
            shutil.rmtree(remote_dir, ignore_errors=True)
            shutil.rmtree(cold_dir, ignore_errors=True)

        invariants = [
            Invariant(
                "crash_injected",
                crashed and any_torn,
                f"uploader killed between blob puts and manifest commit "
                f"({sum(len(v) for v in torn.values())} unreferenced "
                f"staged blob(s) left remotely)" if crashed and any_torn
                else "the scheduled store.tier_upload error NEVER FIRED "
                     "(or left no staged garbage)"),
            Invariant(
                "torn_upload_never_served",
                cold_ok,
                "cold manifest-only reader served exactly the committed "
                "prefix, every segment CRC-verified" if cold_ok else
                "cold reader DIVERGED from the committed prefix (torn "
                "or missing bytes served)"),
            Invariant(
                "local_authoritative_across_kill",
                local_ok,
                "every pre-kill record re-served locally after the "
                "crashed pass" if local_ok else
                "local reads DIVERGED after the crashed upload pass"),
            Invariant(
                "resumed_pass_commits_and_sweeps",
                finished and garbage_left == 0,
                f"re-run pass committed the interrupted segment and "
                f"swept the stage garbage (sweep total "
                f"{sum(s.get('swept', 0) for s in stats.values())})"
                if finished and garbage_left == 0 else
                f"resumed pass failed or left {garbage_left} "
                f"unreferenced blob(s)"),
            Invariant(
                "remote_replay_byte_identical",
                replay_ok,
                "hot tier evicted; full history replayed THROUGH THE "
                "REMOTE TIER byte-identical to pre-kill reads"
                if replay_ok else
                f"remote replay diverged (evicted={evicted}, "
                f"remote_cache_used={remote_used})"),
            Invariant(
                "remount_byte_stable",
                stable,
                "a remounted broker re-serves the identical history "
                "from manifest + local tail" if stable else
                "post-remount reads DIVERGED"),
        ]
        return ChaosReport(
            scenario=self.schedule.name, seed=self.schedule.seed,
            records=self.schedule.records, topology="store",
            published=published, scored=0, rewinds=0,
            dropped_accounted=eng.dropped_count,
            injected=dict(sorted(eng.injected.items())),
            invariants=invariants, span_path=span_path)

    # ---------------------------------------------------------------- obs
    def _run_obs(self, eng: faults.ChaosEngine) -> ChaosReport:
        """alert-burn: the telemetry-plane drill (iotml.obs.drill) under
        the runner harness.  The drill owns fault arming itself — its
        sustained degradation must land in the DEGRADED phase, not at
        t=0 — so the runner's pre-armed engine is stood down and the
        schedule's events handed over; the drill also configures its
        own tracing (canary e2e must be span-sourced)."""
        from ..obs.drill import drill_alert_burn

        faults.disarm()
        rep = drill_alert_burn(seed=self.schedule.seed,
                               records=self.schedule.records,
                               events=self.schedule.events)
        return ChaosReport(
            scenario=self.schedule.name, seed=self.schedule.seed,
            records=self.schedule.records, topology="obs",
            published=rep.published, scored=rep.scored,
            rewinds=0, dropped_accounted=0,
            injected=dict(rep.injected), invariants=list(rep.invariants),
            span_path=None)

    # -------------------------------------------------------------- mlops
    def _run_mlops(self, eng: faults.ChaosEngine) -> ChaosReport:
        """Model-lifecycle scenarios (iotml.mlops) on a temp registry."""
        import shutil
        import tempfile

        root = tempfile.mkdtemp(prefix="iotml_chaos_mlops_")
        try:
            if self.schedule.name == "rollout-regression-rollback":
                return self._run_mlops_rollback(eng, root)
            return self._run_mlops_trainer_crash(eng, root)
        finally:
            shutil.rmtree(root, ignore_errors=True)

    def _run_mlops_trainer_crash(self, eng: faults.ChaosEngine,
                                 root: str) -> ChaosReport:
        """Trainer killed INSIDE a registry publication.

        The checkpoint writer is driven deterministically (write_once on
        the drive thread) so the scheduled ``registry.commit`` error
        lands on an exact publish: artifacts visible, manifest never
        written — the torn version dir a real kill leaves.  The "process"
        then dies (trainer/checkpointer objects abandoned, host state
        gone) and a second incarnation mounts the same registry root:
        recover() must sweep exactly the torn dir, readers must never
        have seen it, and the restarted trainer must resume model AND
        stream cursors from the last DURABLE manifest — re-consuming
        forward from its stamped offsets (no gap), never behind them
        (no double-train)."""
        import os as _os

        from ..gen.simulator import FleetGenerator, FleetScenario
        from ..mlops import AsyncCheckpointer, ModelRegistry
        from ..stream.broker import Broker
        from ..train.live import ContinuousTrainer

        group = "chaos-mlops-train"
        broker = Broker()
        commit_log: List[tuple] = []
        _record_commits(broker, commit_log, "stream")
        gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK,
                                           seed=self.schedule.seed,
                                           failure_rate=0.02))
        ticks = max(1, -(-self.schedule.records // CARS_PER_TICK))
        published = gen.publish(broker, IN_TOPIC, n_ticks=ticks,
                                partitions=2)

        def incarnation():
            reg = ModelRegistry(root)
            swept = reg.recover()
            ck = AsyncCheckpointer(reg)
            tr = ContinuousTrainer(broker, IN_TOPIC, None, checkpointer=ck,
                                   group=group, batch_size=25,
                                   take_batches=2, only_normal=False)
            return reg, ck, tr, swept

        def version_dirs(reg):
            return sorted(n for n in _os.listdir(
                _os.path.join(root, "versions")) if n.startswith("v"))

        reg, ck, tr, _ = incarnation()
        crash: dict = {"round": None}
        trained = 0
        while tr.available() >= tr.min_available:
            stats = tr.train_round()
            trained += stats.get("records", 0)
            try:
                ck.write_once()
            except RuntimeError:
                # the kill: snapshot the on-disk evidence the "dead
                # process" leaves, then abandon every live object
                crash["round"] = tr.rounds
                crash["versions_visible"] = reg.versions()
                crash["dirs"] = version_dirs(reg)
                crash["committed"] = {
                    p: broker.committed(group, IN_TOPIC, p)
                    for p in range(2)}
                break

        committed_names = {f"v{v:010d}" for v
                           in crash.get("versions_visible", [])}
        torn_dirs = sorted(set(crash.get("dirs", [])) - committed_names)

        # ---- restart: fresh mount of the same registry root
        reg2, ck2, tr2, swept = incarnation()
        post_recover_versions = reg2.versions()
        last_durable = reg2.latest()
        manifest = reg2.manifest(last_durable) \
            if last_durable is not None else None
        resumed = {p: off for _t, p, off in tr2.consumer.positions()}
        post_crash_versions = []
        while tr2.available() >= tr2.min_available:
            stats = tr2.train_round()
            trained += stats.get("records", 0)
            v = ck2.write_once()
            if v is not None:
                post_crash_versions.append(v)
        final_versions = reg2.versions()

        manifest_offsets = {p: off for _t, p, off
                            in (manifest.offsets if manifest else [])}
        commit_behind = all(
            (crash["committed"].get(p) or 0) <= manifest_offsets.get(p, 0)
            for p in range(2)) if manifest else False
        final_manifest = reg2.manifest(final_versions[-1]) \
            if final_versions else None
        final_committed_ok = final_manifest is not None and all(
            broker.committed(group, t, p) == off
            for t, p, off in final_manifest.offsets)
        invariants = [
            Invariant(
                "crash_injected_mid_publish",
                crash["round"] is not None,
                f"registry.commit crash landed on round {crash['round']}"
                if crash["round"] is not None else
                "the scheduled mid-publish crash never fired"),
            Invariant(
                "torn_version_never_served",
                len(torn_dirs) == 1 and not any(
                    int(torn_dirs[0][1:]) in vs for vs in
                    (crash.get("versions_visible", []),)),
                f"torn dir {torn_dirs} existed on disk, invisible to "
                f"versions() before AND after recovery" if torn_dirs else
                "no torn version dir found — the crash left no artifact"),
            Invariant(
                "recover_swept_torn_only",
                swept == len(torn_dirs) and
                post_recover_versions == crash.get("versions_visible", []),
                f"recover() swept {swept} dir(s) == the {len(torn_dirs)} "
                f"torn; committed set unchanged"),
            Invariant(
                "commit_trails_checkpoint",
                commit_behind,
                "committed offsets at crash <= last durable manifest's "
                "stamped offsets on every partition" if commit_behind else
                f"COMMITTED RAN AHEAD of durable state: "
                f"committed={crash.get('committed')} "
                f"manifest={manifest_offsets}"),
            Invariant(
                "resumed_exactly_at_manifest",
                manifest is not None and resumed == manifest_offsets,
                f"restart cursors {resumed} == durable manifest offsets "
                f"{manifest_offsets} (no gap, no double-train)"
                if resumed == manifest_offsets else
                f"restart cursors {resumed} DIVERGED from manifest "
                f"{manifest_offsets}"),
            Invariant(
                "version_ids_number_commits",
                bool(final_versions) and final_versions == list(
                    range(1, len(final_versions) + 1)),
                f"{len(final_versions)} committed versions, contiguous "
                f"ids (the torn publish's id was reused)"),
            Invariant(
                "training_resumed_to_end",
                bool(post_crash_versions)
                and tr2.available() < tr2.min_available
                and final_committed_ok,
                f"{len(post_crash_versions)} post-crash versions "
                f"published; stream consumed to the round boundary; "
                f"final committed == final manifest offsets"),
            _check_commits_monotonic(commit_log),
        ]
        return ChaosReport(
            scenario=self.schedule.name, seed=self.schedule.seed,
            records=self.schedule.records, topology="mlops",
            published=published, scored=trained, rewinds=0,
            dropped_accounted=eng.dropped_count,
            injected=dict(sorted(eng.injected.items())),
            invariants=invariants, span_path=None)

    def _run_mlops_rollback(self, eng: faults.ChaosEngine,
                            root: str) -> ChaosReport:
        """Deploy a degraded candidate; the gate must roll it back.

        Baseline = a quickly-trained autoencoder published through the
        async checkpointer; candidate = the same weights wrecked with
        seeded noise, published and DEPLOYED (serving points at it for
        the evaluation window).  Both score the full seeded stream into
        their own prediction topics; the r04 detection-quality gate
        must detect the AUC/F1 regression and re-point serving at the
        baseline — within one pass over the stream."""
        import numpy as np

        from ..gen.simulator import FleetGenerator, FleetScenario
        from ..mlops import (ABRollout, AsyncCheckpointer, ModelRegistry,
                             RolloutGate)
        from ..mlops.checkpoint import (params_from_h5_bytes,
                                        params_to_h5_bytes)
        from ..stream.broker import Broker
        from ..train.live import ContinuousTrainer

        broker = Broker()
        commit_log: List[tuple] = []
        _record_commits(broker, commit_log, "stream")
        gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK,
                                           seed=self.schedule.seed,
                                           failure_rate=0.05))
        ticks = max(1, -(-self.schedule.records // CARS_PER_TICK))
        published = gen.publish(broker, IN_TOPIC, n_ticks=ticks,
                                partitions=2)

        reg = ModelRegistry(root)
        tr = ContinuousTrainer(
            broker, IN_TOPIC, None, checkpointer=AsyncCheckpointer(reg),
            group="chaos-ab-train", batch_size=50,
            take_batches=max(2, min(8, published // 60)),
            epochs_per_round=3)
        tr.train_round()
        tr.checkpointer.write_once()
        baseline = reg.latest()

        import jax

        params = params_from_h5_bytes(reg.load_bytes(baseline, "model.h5"))
        noise = np.random.RandomState(self.schedule.seed)
        bad = jax.tree_util.tree_map(
            lambda a: np.asarray(a)
            + noise.normal(0, 1.0, np.shape(a)).astype(np.float32),
            params)
        candidate = reg.publish(
            {"model.h5": params_to_h5_bytes(bad)},
            metrics={"degraded": 1.0}).version

        gate = RolloutGate(
            min_records=max(50, min(300, published // 2)), epsilon=0.02)
        ab = ABRollout(broker, IN_TOPIC, reg, baseline, candidate,
                       gate=gate, threshold=5.0, deploy_candidate=True,
                       from_start=True)
        serving_during = reg.channel("serving")
        # one deterministic pass: both sides drain the retained stream;
        # the gate must settle before the data runs out
        for _ in range(512):
            if ab.step(max_rows=5_000) == 0:
                break
        scored = sum(s.scored for s in ab.sides.values())
        qb, qc = ab.quality("baseline"), ab.quality("candidate")
        serving_after = reg.channel("serving")
        events = [e["event"] for e in reg.history()]
        pred_ok = all(
            broker.end_offset(f"model-predictions.v{v}", 0) == s.scored
            for v, s in ((baseline, ab.sides["baseline"]),
                         (candidate, ab.sides["candidate"])))
        invariants = [
            Invariant(
                "regression_rolled_back",
                ab.decision == "rollback",
                f"gate verdict: {ab.decision!r} "
                f"(baseline auc={qb['auc']}, candidate auc={qc['auc']})"),
            Invariant(
                "candidate_served_during_eval",
                serving_during == candidate,
                f"serving pointed at the candidate (v{serving_during}) "
                f"for the evaluation window"),
            Invariant(
                "serving_restored_to_baseline",
                serving_after == baseline and "rollback" in events,
                f"serving back at v{serving_after} == baseline "
                f"v{baseline}; history records the rollback"),
            Invariant(
                "quality_gap_real",
                qb["auc"] is not None and qc["auc"] is not None
                and qb["auc"] > qc["auc"] + gate.epsilon,
                f"measured regression: baseline auc {qb['auc']} vs "
                f"candidate {qc['auc']} (epsilon {gate.epsilon})"),
            Invariant(
                "decided_within_one_pass",
                ab.decision is not None and all(
                    s.scored <= published for s in ab.sides.values()),
                f"verdict after {max(s.scored for s in ab.sides.values())}"
                f"/{published} records per side — no replay needed"),
            Invariant(
                "ab_prediction_streams_on_log",
                pred_ok,
                "both versions' prediction topics hold exactly one "
                "record per scored row (the comparison artifact is "
                "itself replayable)" if pred_ok else
                "prediction topic row counts diverge from scored rows"),
            _check_commits_monotonic(commit_log),
        ]
        return ChaosReport(
            scenario=self.schedule.name, seed=self.schedule.seed,
            records=self.schedule.records, topology="mlops",
            published=published, scored=scored, rewinds=0,
            dropped_accounted=eng.dropped_count,
            injected=dict(sorted(eng.injected.items())),
            invariants=invariants, span_path=None)

    @staticmethod
    def _publish_tick_mqtt(gen, mqtt) -> int:
        cols = gen.step_columns()
        from ..core.schema import CAR_SCHEMA

        n = len(cols["car"])
        for i in range(n):
            rec = gen.row_record(cols, i, CAR_SCHEMA)
            rec["failure_occurred"] = str(cols["failure_occurred"][i])
            mqtt.publish(
                f"vehicles/sensor/data/{gen.scenario.car_id(i)}",
                json.dumps(rec).encode(), qos=1)
        return n

    # --------------------------------------------------------- cluster
    def _run_cluster(self, eng: faults.ChaosEngine) -> ChaosReport:
        """Rebalance-under-chaos on a partitioned 3-broker cluster.

        Three group members score a 6-partition topic through routed
        ``ClusterClient``s (group protocol pinned to the coordinator
        broker).  Mid-epoch a member is killed (crash semantics: stops
        polling, never leaves; the coordinator expires it and survivors
        inherit its partitions at the committed frontier), then a shard
        LEADER is killed after replication drains to zero lag and its
        follower is promoted at a bumped epoch — one shard's map entry
        moves, nothing else.  The proof is record-identity exact-once:
        the multiset of (partition, offset) scored across all members
        equals the set of records in the logs — zero lost, zero
        double-scored — plus monotonic commits and the epoch/assignment
        evidence of both failures actually happening."""
        import time as _time

        from ..cluster import ClusterController
        from ..stream.group import GroupConsumer
        from ..stream.kafka_wire import RemoteGroupCoordinator

        n_parts, n_members = 6, 3
        victim_shard = 2  # a non-coordinator shard (coordinator death
        # is tested separately; here the GROUP must survive both kills)
        ctl = ClusterController(brokers=3, replicated=True,
                                replica_sync="manual",
                                mirror_groups=(GROUP,))
        ctl.start()
        commit_log: List[tuple] = []
        # group commits land on the COORDINATOR broker (shard 0):
        # fenced commits route through its GroupCoordinator
        _record_commits(ctl.brokers[0], commit_log, "coordinator")
        published = rewinds = 0
        scored: List[List[Tuple[int, int]]] = [[] for _ in range(n_members)]
        clients = []
        members: List[Optional[GroupConsumer]] = []
        try:
            ctl.create_topic(IN_TOPIC, partitions=n_parts)
            ctl.create_topic(PRED_TOPIC, partitions=n_members)
            producer = ctl.client(client_id="chaos-cluster-producer")
            clients.append(producer)
            for m in range(n_members):
                c = ctl.client(client_id=f"chaos-cluster-m{m}")
                clients.append(c)
                coord = RemoteGroupCoordinator(c, GROUP,
                                               session_timeout_ms=1500)
                members.append(GroupConsumer(coord, [IN_TOPIC]))

            killed_member: Optional[int] = None
            killed_shard = False

            def drive_member(m: int) -> int:
                nonlocal rewinds
                gc = members[m]
                if gc is None:
                    return 0
                try:
                    batch = gc.poll(4096)
                    if not batch:
                        return 0
                    for msg in batch:
                        scored[m].append((msg.partition, msg.offset))
                        clients[m + 1].produce(
                            PRED_TOPIC,
                            f"{msg.partition}:{msg.offset}".encode(),
                            key=msg.key, partition=m)
                    # commit AFTER scoring the whole poll: the member's
                    # committed frontier == its scored frontier, so an
                    # inheritor never re-scores (the zero-dup invariant)
                    gc.commit()
                    return len(batch)
                except ConnectionError:
                    gc.rewind_to_committed()
                    rewinds += 1
                    return 0

            def run_due_events():
                nonlocal killed_member, killed_shard
                for ev in eng.due_runner_events(published):
                    if ev.action == "kill_member" and killed_member is None:
                        # crash, not leave: stop polling member 2 — the
                        # coordinator expires it at session timeout and
                        # survivors inherit its committed frontier
                        killed_member = n_members - 1
                        members[killed_member] = None
                        eng.note_runner_fired(ev)
                    elif ev.action == "kill_shard_leader" \
                            and not killed_shard:
                        # zero-lag handoff (the wire drill's contract):
                        # drain replication, then kill the leader and
                        # promote its follower at a bumped epoch
                        while ctl.sync_replicas_once() > 0:
                            pass
                        ctl.fail_shard(victim_shard)
                        killed_shard = True
                        eng.note_runner_fired(ev)

            def produce_tick(tick: int) -> int:
                entries = [(f"car_{tick}_{i}".encode(),
                            f"r{tick}:{i}".encode(), 0)
                           for i in range(CARS_PER_TICK)]
                for attempt in range(3):
                    try:
                        producer.produce_many(IN_TOPIC, entries)
                        return len(entries)
                    except ConnectionError:
                        # kills land between ticks: the dead broker
                        # cannot have applied this batch — re-route and
                        # redeliver (NOT_LEADER re-routes internally)
                        if attempt == 2:
                            raise
                return 0

            ticks = max(1, -(-self.schedule.records // CARS_PER_TICK))
            for tick in range(ticks):
                run_due_events()
                published += produce_tick(tick)
                if not killed_shard:
                    ctl.sync_replicas_once()
                for m in range(n_members):
                    drive_member(m)
            run_due_events()
            # final drain: outlast the dead member's session timeout so
            # survivors inherit and finish its partitions
            deadline = _time.monotonic() + 30.0
            while _time.monotonic() < deadline:
                moved = sum(drive_member(m) for m in range(n_members))
                live = [gc for gc in members if gc is not None]
                if not moved and all(gc.at_end() for gc in live):
                    # at_end is only trustworthy once every partition is
                    # assigned to a survivor (the dead member's
                    # partitions reassign after expiry)
                    assigned = set()
                    for gc in live:
                        assigned.update(gc.assignment)
                    if assigned == {(IN_TOPIC, p)
                                    for p in range(n_parts)}:
                        break
                _time.sleep(0.05)
        finally:
            for c in clients:
                try:
                    c.close()
                except OSError:
                    pass
            ctl.stop()

        # exact-once over record identities: everything in the logs,
        # once each, across all members
        expected = set()
        for p in range(n_parts):
            end = ctl.serving[ctl.pmap.shard_for(IN_TOPIC, p)] \
                .end_offset(IN_TOPIC, p)
            expected.update((p, o) for o in range(end))
        flat = [ident for member in scored for ident in member]
        dupes = len(flat) - len(set(flat))
        missing = expected - set(flat)
        extra = set(flat) - expected
        total_scored = len(flat)
        invariants = [
            _check_counts(published, total_scored, eng.dropped_count),
            _check_commits_monotonic(commit_log),
            Invariant(
                "zero_records_lost",
                not missing and not extra,
                f"all {len(expected)} log records scored"
                if not missing and not extra else
                f"{len(missing)} records NEVER SCORED "
                f"(e.g. {sorted(missing)[:3]}); {len(extra)} phantom"),
            Invariant(
                "zero_double_scored",
                dupes == 0,
                f"{total_scored} scores over {len(set(flat))} unique "
                f"records" + ("" if dupes == 0 else
                              f"; {dupes} DOUBLE-SCORED")),
            Invariant(
                "member_death_rebalanced",
                killed_member is not None and any(
                    gc is not None and gc.rebalances > 0
                    for gc in members),
                "survivors rebalanced and inherited the dead member's "
                "partitions" if killed_member is not None else
                "member was never killed"),
            Invariant(
                "shard_failover_one_shard_only",
                killed_shard and ctl.pmap.epoch(victim_shard) == 1
                and all(ctl.pmap.epoch(s) == 0 for s in range(3)
                        if s != victim_shard),
                f"shard {victim_shard} at epoch "
                f"{ctl.pmap.epoch(victim_shard)}, every other shard "
                f"untouched at epoch 0" if killed_shard else
                "shard leader was never killed"),
        ]
        return ChaosReport(
            scenario=self.schedule.name, seed=self.schedule.seed,
            records=self.schedule.records, topology="cluster",
            published=published, scored=total_scored, rewinds=rewinds,
            dropped_accounted=eng.dropped_count,
            injected=dict(sorted(eng.injected.items())),
            invariants=invariants, span_path=None)

    # ------------------------------------------------------ replication
    def _run_replication(self, eng: faults.ChaosEngine) -> ChaosReport:
        """Double-fault under sustained acks=all load (ISSUE 14).

        A leader + two ISR-tracked followers (quorum min_isr=2) serve a
        2-partition topic; every produce is acks=all (the classic wire
        client default against a quorum broker), issued from a worker
        thread while this thread steps the followers' sync rounds —
        the quorum wait resolves deterministically against stepped
        replication.  Mid-epoch one FOLLOWER dies abruptly (ISR evicts
        it within the staleness window; the quorum re-forms at width
        2), then the LEADER dies with NO pre-kill drain.  The runner
        promotes an ISR member at epoch+1 — election is ISR-restricted
        — heals the set with a brand-new follower bootstrapped from the
        promoted leader, and finishes the stream.

        The proof: ZERO acked-record loss, byte-identically — every
        (partition, offset, value) acked before the leader death reads
        back identical from the promoted log (acked ⇒ below the quorum
        HWM ⇒ on every ISR member); the consumer (bounded by the quorum
        HWM, so it can never observe a record a failover could
        un-write) scores the final log exactly once; commits stay
        monotonic across the promotion; and the new leader provably sat
        in the ISR at the kill."""
        import threading
        import time as _time

        from ..replication import ReplicaSet
        from ..stream.broker import Broker
        from ..stream.consumer import StreamConsumer
        from ..stream.kafka_wire import KafkaWireBroker, KafkaWireServer
        from ..supervise.registry import register_thread

        parts = 2
        leader = Broker()
        leader.create_topic(IN_TOPIC, partitions=parts)
        commit_log: List[tuple] = []
        _record_commits(leader, commit_log, "leader")
        lsrv = KafkaWireServer(leader).start()
        rs = ReplicaSet(leader_broker=leader, leader_server=lsrv,
                        n_followers=2, min_isr=2, max_lag_s=0.25,
                        topics=[IN_TOPIC], groups=(GROUP,))
        for rid, rep in rs.followers.items():
            _record_commits(rep.local, commit_log, f"follower-{rid}")
        rs.start(sync="manual")  # stepped: determinism over realism
        bootstrap = ",".join(
            [f"127.0.0.1:{lsrv.port}"]
            + [f"127.0.0.1:{rep.port}" for rep in rs.followers.values()])
        producer = KafkaWireBroker(bootstrap, client_id="chaos-repl-prod")
        consumer_client = KafkaWireBroker(bootstrap,
                                          client_id="chaos-repl-scorer")
        consumer = StreamConsumer(
            consumer_client, [f"{IN_TOPIC}:{p}:0" for p in range(parts)],
            group=GROUP)

        published = rewinds = 0
        acked: Dict[Tuple[int, int], bytes] = {}   # (part, offset) -> value
        consumed: List[Tuple[int, int, bytes]] = []
        killed_follower: Optional[int] = None
        killed_leader = False
        isr_at_kill: List[int] = []
        promoted_rid: Optional[int] = None
        healed_rid: Optional[int] = None

        # ISR formation before load: acks=all refuses below min_isr by
        # contract, and the drill is about LOSING quorum, not forming it
        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline and not all(
                rs.state.isr_size(IN_TOPIC, p) == 3 for p in range(parts)):
            rs.sync_once()

        def produce_tick(tick: int) -> int:
            """One tick of acks=all load: the produce blocks in the wire
            server until the quorum HWM covers it, so it runs on a
            worker thread while THIS thread steps replication."""
            nonlocal published
            n = 0
            for p in range(parts):
                values = [f"t{tick}r{i}p{p}".encode()
                          for i in range(CARS_PER_TICK // parts)]
                result: dict = {}

                def attempt_produce(res=result, _p=p, _vals=values):
                    try:
                        res["last"] = producer.produce_many(
                            IN_TOPIC,
                            [(None, v, 0) for v in _vals],
                            partition=_p, timeout_ms=8000)
                    except Exception as e:  # noqa: BLE001 - verdict data
                        res["err"] = e

                for attempt in range(12):  # redelivery (caller-owns)
                    result.clear()
                    t = register_thread(threading.Thread(
                        target=attempt_produce, daemon=True,
                        name="iotml-chaos-repl-producer"))
                    t.start()
                    while t.is_alive():
                        rs.sync_once()
                        _time.sleep(0.002)
                    t.join(1.0)
                    if "last" in result:
                        last = result["last"]
                        for i, v in enumerate(values):
                            acked[(p, last - len(values) + 1 + i)] = v
                        n += len(values)
                        break
                    err = result.get("err")
                    if err is not None and not isinstance(
                            err, ConnectionError):
                        raise err
                    # ConnectionError family (incl. NotEnoughReplicas /
                    # ProduceTimedOut): step replication and redeliver
                    for _ in range(5):
                        rs.sync_once()
                    _time.sleep(0.05)
                else:
                    # NEVER give up silently: a dropped batch would
                    # weaken the drill while the invariants pass
                    # vacuously — the schedule promised this load
                    raise RuntimeError(
                        f"acks=all batch for partition {p} undeliverable "
                        f"after 12 redelivery attempts: {result.get('err')}")
            published += n
            return n

        def drain() -> int:
            nonlocal rewinds
            try:
                batch = consumer.poll(4096)
            except ConnectionError:
                consumer.rewind_to_committed()
                rewinds += 1
                return 0
            for m in batch:
                consumed.append((m.partition, m.offset, m.value))
            if batch:
                consumer.commit()
            return len(batch)

        def run_due_events():
            nonlocal killed_follower, killed_leader, promoted_rid, \
                healed_rid, isr_at_kill
            for ev in eng.due_runner_events(published):
                if ev.action == "kill_follower" and \
                        killed_follower is None:
                    killed_follower = sorted(rs.followers)[0]
                    rs.kill_follower(killed_follower)
                    eng.note_runner_fired(ev)
                elif ev.action == "kill_leader" and not killed_leader:
                    # retire the dead follower BEFORE electing: if both
                    # kills land in one event batch, no staleness
                    # window has elapsed and the corpse would still sit
                    # in the ISR — the election must never pick it
                    if killed_follower is not None:
                        rs.retire_follower(killed_follower)
                    # NO pre-kill drain: the un-acked tail may die with
                    # the leader — acks=all means the ACKED records
                    # cannot (they are on every ISR member)
                    isr_at_kill = sorted(rs.state.isr_follower_ids())
                    lsrv.kill()
                    killed_leader = True
                    promoted_rid, _addr = rs.promote(epoch=1)
                    # elastic heal: a fresh follower bootstraps the
                    # whole log from the promoted leader over RAW_FETCH
                    # and re-forms the 2-wide quorum so acks=all resumes
                    healed_rid = rs.add_follower(sync="manual")
                    _record_commits(rs.followers[healed_rid].local,
                                    commit_log, "healed")
                    deadline = _time.monotonic() + 10.0
                    while _time.monotonic() < deadline and \
                            healed_rid not in rs.state.isr_follower_ids():
                        rs.sync_once()
                    eng.note_runner_fired(ev)

        ticks = max(1, -(-self.schedule.records // CARS_PER_TICK))
        try:
            for tick in range(ticks):
                run_due_events()
                produce_tick(tick)
                rs.sync_once()
                drain()
            run_due_events()
            # final drain to the quorum frontier (== log end once the
            # healed follower is in sync)
            for _ in range(200):
                rs.sync_once()
                if drain() == 0 and consumer.at_end():
                    break
        finally:
            for client in (producer, consumer_client):
                try:
                    client.close()
                except OSError:
                    pass
            rs.stop()
            if not killed_leader:
                lsrv.kill()

        live = rs.leader  # the promoted broker serves the end state
        # zero acked loss, byte-identical: every acked (p, off) -> value
        # reads back identical from the promoted log
        lost = []
        mismatched = []
        for (p, off), value in sorted(acked.items()):
            got = {m.offset: m.value
                   for m in live.fetch_tail(IN_TOPIC, p, off, 1)}
            if off not in got:
                lost.append((p, off))
            elif got[off] != value:
                mismatched.append((p, off))
        # consumer exact-once over the final log
        expected = set()
        for p in range(parts):
            expected.update((p, o)
                            for o in range(live.end_offset(IN_TOPIC, p)))
        seen = [(p, o) for p, o, _v in consumed]
        dupes = len(seen) - len(set(seen))
        missing = expected - set(seen)
        invariants = [
            _check_commits_monotonic(commit_log),
            Invariant(
                "zero_acked_loss",
                killed_leader and not lost and not mismatched,
                (f"all {len(acked)} acked records present "
                 f"byte-identically at identical offsets after the "
                 f"double fault" if killed_leader and not lost
                 and not mismatched else
                 "leader was never killed" if not killed_leader else
                 f"{len(lost)} ACKED RECORDS LOST "
                 f"(e.g. {lost[:3]}), {len(mismatched)} mismatched")),
            Invariant(
                "new_leader_in_isr",
                promoted_rid is not None and promoted_rid in isr_at_kill,
                f"promoted replica {promoted_rid} was in the ISR "
                f"{isr_at_kill} at the kill" if promoted_rid is not None
                else "no promotion happened"),
            Invariant(
                "double_fault_injected",
                killed_follower is not None and killed_leader,
                f"follower {killed_follower} and the leader both died"
                if killed_follower is not None and killed_leader else
                "both faults must fire"),
            Invariant(
                "consumer_exact_once",
                not missing and dupes == 0,
                f"{len(seen)} consumed rows cover all "
                f"{len(expected)} log records exactly once"
                if not missing and dupes == 0 else
                f"{len(missing)} never consumed, {dupes} duplicated"),
            Invariant(
                "quorum_healed",
                healed_rid is not None and
                healed_rid in rs.state.isr_follower_ids(),
                f"replica {healed_rid} bootstrapped from the promoted "
                f"leader and re-joined the ISR (raw-mirrored "
                f"{rs.followers[healed_rid].raw_mirrored} records)"
                if healed_rid is not None and
                healed_rid in rs.state.isr_follower_ids() else
                "the elastic heal never completed"),
        ]
        return ChaosReport(
            scenario=self.schedule.name, seed=self.schedule.seed,
            records=self.schedule.records, topology="replication",
            published=published, scored=len(consumed), rewinds=rewinds,
            dropped_accounted=eng.dropped_count,
            injected=dict(sorted(eng.injected.items())),
            invariants=invariants, span_path=None)

    # ------------------------------------------------------------ wire
    def _run_wire(self, eng: faults.ChaosEngine) -> ChaosReport:
        from ..core.schema import KSQL_CAR_SCHEMA
        from ..gen.simulator import FleetGenerator, FleetScenario
        from ..ops.avro import AvroCodec
        from ..ops.framing import frame
        from ..stream.broker import Broker
        from ..stream.consumer import StreamConsumer
        from ..stream.kafka_wire import KafkaWireBroker, KafkaWireServer
        from ..stream.replica import FollowerReplica

        leader = Broker()
        commit_log: List[tuple] = []
        _record_commits(leader, commit_log, "leader")
        lsrv = KafkaWireServer(leader).start()
        rep = FollowerReplica(f"127.0.0.1:{lsrv.port}",
                              topics=[IN_TOPIC, PRED_TOPIC],
                              groups=(GROUP,))
        _record_commits(rep.local, commit_log, "follower")
        # the follower SERVES from the start, but its sync loop is
        # stepped synchronously by this thread — determinism over
        # realism (the background loop is exercised by tests/test_replica)
        rep.server.start()
        bootstrap = f"127.0.0.1:{lsrv.port},127.0.0.1:{rep.port}"
        producer = KafkaWireBroker(bootstrap, client_id="chaos-devsim")
        consumer_client = KafkaWireBroker(bootstrap,
                                          client_id="chaos-scorer")
        parts = 2
        producer.create_topic(IN_TOPIC, partitions=parts)
        producer.create_topic(PRED_TOPIC, partitions=1)
        consumer = StreamConsumer(
            consumer_client, [f"{IN_TOPIC}:{p}:0" for p in range(parts)],
            group=GROUP)
        scorer = self._make_scorer(producer, consumer)

        gen = FleetGenerator(FleetScenario(num_cars=CARS_PER_TICK,
                                           seed=self.schedule.seed))
        codec = AvroCodec(KSQL_CAR_SCHEMA)
        published = rewinds = 0
        killed = False
        promotion: Optional[Tuple[int, int]] = None
        ticks = max(1, -(-self.schedule.records // CARS_PER_TICK))

        def run_due_events():
            nonlocal killed, promotion
            for ev in eng.due_runner_events(published):
                if ev.action == "kill_leader" and not killed:
                    # deterministic failover: drain replication to zero
                    # lag (direct sync mirrors the commit tables too),
                    # measure the loss window, then die abruptly
                    while rep.sync_once() > 0:
                        pass
                    lag = sum(rep.lag().values())
                    tail = sum(
                        leader.end_offset(t, p) - rep.local.end_offset(t, p)
                        for t in (IN_TOPIC, PRED_TOPIC)
                        for p in range(leader.topic(t).partitions))
                    promotion = (lag, tail)
                    lsrv.kill()
                    killed = True
                    eng.note_runner_fired(ev)

        def drive_once():
            nonlocal rewinds
            try:
                return scorer.score_available()
            except ConnectionError:
                consumer.rewind_to_committed()
                rewinds += 1
                return -1

        try:
            for _ in range(ticks):
                run_due_events()
                cols = gen.step_columns()
                entries = []
                for i in range(len(cols["car"])):
                    rec = gen.row_record(cols, i, KSQL_CAR_SCHEMA)
                    entries.append(
                        (gen.scenario.car_id(i).encode(),
                         frame(codec.encode(rec)), 0))
                for attempt in range(3):
                    try:
                        producer.produce_many(IN_TOPIC, entries)
                        break
                    except ConnectionError:
                        # the client has already failed over; redeliver
                        # to the promoted follower.  Kills land between
                        # ticks so the dead leader cannot have applied
                        # the batch; a scenario that injects wire errors
                        # mid-produce gets at-least-once (a duplicated
                        # batch inflates `scored` past `published`,
                        # which every invariant tolerates by contract)
                        if attempt == 2:
                            raise
                published += len(entries)
                if not killed:
                    rep.sync_once()
                drive_once()
            run_due_events()
            for _ in range(64):
                n = drive_once()
                if n == 0 and consumer.at_end():
                    break
        finally:
            for client in (producer, consumer_client):
                try:
                    client.close()
                except OSError:
                    pass
            rep.stop()
            if not killed:
                lsrv.kill()

        live = rep.local  # the promoted broker serves the end state
        lag, tail = promotion if promotion is not None else (-1, -1)
        invariants = [
            _check_counts(published, scorer.scored, eng.dropped_count),
            _check_commits_monotonic(commit_log),
            _check_predictions(live, scorer.scored),
            _check_final_commit(live, IN_TOPIC, parts),
            Invariant(
                "promotion_loss_bounded",
                killed and 0 <= tail <= max(lag, 0),
                ("leader was never killed" if not killed else
                 f"unreplicated tail at leader death: {tail} records "
                 f"within the measured lag {lag} (the runner's "
                 f"sync-before-kill drives both to zero)"
                 if 0 <= tail <= max(lag, 0) else
                 f"unreplicated tail at leader death: {tail} records "
                 f"EXCEEDS the measured lag {lag} — records the "
                 f"promoted follower never saw")),
        ]
        return ChaosReport(
            scenario=self.schedule.name, seed=self.schedule.seed,
            records=self.schedule.records, topology="wire",
            published=published, scored=scorer.scored, rewinds=rewinds,
            dropped_accounted=eng.dropped_count,
            injected=dict(sorted(eng.injected.items())),
            invariants=invariants, span_path=None)
