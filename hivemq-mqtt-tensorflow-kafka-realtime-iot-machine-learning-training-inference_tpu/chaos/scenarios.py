"""Seeded, declarative fault schedules — replayable bit-for-bit.

A scenario is a *builder*: ``(random.Random(seed), records) -> events``.
Events are expressed in deterministic counters only — the Nth traversal
of a faultpoint (``at`` = hit index, optionally covering ``repeat``
consecutive hits) or, for runner-orchestrated pseudo-points, the Nth
published record.  No wall clock anywhere: the same (scenario, seed,
records) triple always compiles to the byte-identical schedule
(``Schedule.text()`` is the canonical form CI diffs), which is the same
reproducibility discipline the data-pipeline literature applies to
training input (PAPERS.md: a run you can't replay is a run you can't
debug).

Built-ins:

- ``leader-kill-mid-drain`` (wire): the follower syncs, the leader
  wire-server dies abruptly mid-stream, clients fail over.
- ``mqtt-flap``: flapping device links — seeded MQTT delivery drops
  (accounted as intentional loss) plus short delay bursts.
- ``slow-bridge``: sustained delay windows on the MQTT→stream hop.
- ``dup-storm``: duplicate deliveries — at-least-once must absorb them.
- ``partition-blackout``: a window of consecutive broker fetches fails
  with ConnectionError (partition unavailable) and must be retried
  through.
- ``scorer-crash-resume``: the scorer's drain loop dies mid-stream and
  must resume via rewind-to-committed redelivery.
- ``loss-bug-fixture``: a seeded SILENT drop (not ledgered) — exists so
  tests can prove the invariant checker actually fails on real loss.
- ``broker-crash-recover`` (store): the durable broker dies mid-write
  (torn frame on the active segment); remount recovers, acked records
  re-serve, consumers resume from their persisted committed offsets.
- ``rebalance-under-chaos`` (cluster): on a 3-broker partitioned
  cluster, a consumer-group member dies mid-epoch and then a shard
  leader dies mid-epoch; the runner proves every produced record was
  scored exactly once (zero lost, zero double-scored) across the
  rebalance and the per-shard failover.
- ``compaction-under-crash`` (store): the segment compactor is killed
  at a mid-pass swap on the twin's compacted changelog (durable
  ``.cleaned`` rewrite written, live segment not yet replaced); the
  remount must sweep the tmp, lose no key, serve byte-identical
  compacted reads, and a finished pass must stay byte-stable across a
  second remount.
- ``tier-upload-crash`` (store): the tier uploader is killed between
  the segment blob uploads and the remote manifest commit (staged
  blobs exist, nothing references them); remount + a cold reader over
  the remote tier must never serve the torn upload, local bytes stay
  authoritative, and the finished re-upload must replay byte-identical
  through the remote leg.
- ``trainer-crash-mid-checkpoint`` (mlops): the checkpoint writer dies
  inside a registry publication (torn version dir left behind); a
  restarted trainer must resume model + stream offsets from the last
  durable manifest with the torn state swept, never served.
- ``rollout-regression-rollback`` (mlops): a deliberately degraded
  candidate model is deployed to serving; the A/B quality gate must
  detect the live regression and roll serving back to the baseline.
- ``drift-storm`` (online): seeded regional drift and flapping device
  links CONCURRENTLY — the online learner must detect the drift on
  its error signal, adapt and converge, the adapted model must
  hot-swap the scorer fleet through the registry, and no record may
  be lost or double-scored across the swap.
- ``alert-burn`` (obs): sustained slow-bridge degradation under live
  synthetic canaries; the SLO engine's fast burn-rate pair must fire,
  land in ``_IOTML_ALERTS`` + ``/healthz``, and resolve on recovery.
"""

from __future__ import annotations

import dataclasses
import json
import random
from typing import Callable, Dict, List, Tuple

#: fleet size per simulator tick — shared with the runner so builders
#: can reason in ticks (records / CARS_PER_TICK) when a faultpoint is
#: hit once per tick (scorer.poll) rather than once per record.
CARS_PER_TICK = 25


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: fire `action` at the `at`-th traversal of
    `point` (1-based), covering `repeat` consecutive traversals.  For
    runner pseudo-points `at` is a published-record count."""

    at: int
    point: str
    action: str
    params: Tuple[Tuple[str, object], ...] = ()
    repeat: int = 1

    def line(self) -> str:
        """Canonical text form — what byte-identical schedules diff."""
        p = json.dumps(dict(self.params), sort_keys=True,
                       separators=(",", ":"))
        return f"{self.at:>8} x{self.repeat:<4} {self.point} " \
               f"{self.action} {p}"


@dataclasses.dataclass(frozen=True)
class Schedule:
    name: str
    seed: int
    records: int
    topology: str  # "inproc" | "wire" | "store" | "cluster"
    events: Tuple[FaultEvent, ...]

    def lines(self) -> List[str]:
        head = [f"# scenario={self.name} seed={self.seed} "
                f"records={self.records} topology={self.topology}"]
        return head + [e.line() for e in self.events]

    def text(self) -> str:
        return "\n".join(self.lines()) + "\n"


# ------------------------------------------------------------- builders
def _leader_kill(rng: random.Random, records: int) -> list:
    lo, hi = max(1, records // 3), max(2, (2 * records) // 3)
    events = [FaultEvent(rng.randint(lo, hi), "runner.kill_leader",
                         "kill_leader")]
    # flavor: a few slow client recvs around the failover window
    for _ in range(3):
        events.append(FaultEvent(rng.randint(1, max(2, records // 20)),
                                 "kafka_wire.recv", "delay",
                                 params=(("seconds", 0.001),)))
    return events


def _mqtt_flap(rng: random.Random, records: int) -> list:
    n_drops = max(2, records // 100)
    hits = sorted(rng.sample(range(1, records + 1),
                             min(n_drops, records)))
    events = [FaultEvent(h, "mqtt.deliver", "drop") for h in hits]
    for _ in range(2):  # short link stalls riding along
        events.append(FaultEvent(rng.randint(1, max(2, records - 10)),
                                 "mqtt.deliver", "delay",
                                 params=(("seconds", 0.001),), repeat=5))
    return events


def _slow_bridge(rng: random.Random, records: int) -> list:
    events = []
    at = 1
    for _ in range(3):
        at = rng.randint(at, max(at + 1, min(records, at + records // 3)))
        win = rng.randint(10, 30)
        events.append(FaultEvent(at, "mqtt.deliver", "delay",
                                 params=(("seconds", 0.002),), repeat=win))
        at += win + 1
    return events


def _dup_storm(rng: random.Random, records: int) -> list:
    n = max(5, records // 50)
    hits = sorted(rng.sample(range(1, records + 1), min(n, records)))
    return [FaultEvent(h, "mqtt.deliver", "dup") for h in hits]


def _partition_blackout(rng: random.Random, records: int) -> list:
    # a contiguous window of broker fetches fails (fetch hits accrue
    # fast: every poll round fetches each partition)
    at = rng.randint(5, 40)
    return [FaultEvent(at, "broker.fetch", "error",
                       params=(("exc", "ConnectionError"),),
                       repeat=rng.randint(6, 12))]


def _scorer_crash_resume(rng: random.Random, records: int) -> list:
    # scorer.poll is hit once per drain chunk (~once per tick)
    ticks = max(4, records // CARS_PER_TICK)
    h1 = rng.randint(2, max(3, ticks // 2))
    h2 = h1 + rng.randint(2, max(3, ticks // 2))
    return [FaultEvent(h1, "scorer.poll", "error"),
            FaultEvent(h2, "scorer.poll", "error")]


def _broker_crash_recover(rng: random.Random, records: int) -> list:
    # the durable broker dies MID-WRITE somewhere in the middle third of
    # the stream (torn frame on the active segment); the runner remounts
    # from disk and the restarted pipeline must finish the stream with
    # every pre-crash acked record re-served.  A couple of fetch stalls
    # ride along so recovery is proven under an unquiet consumer.
    lo, hi = max(1, records // 3), max(2, (2 * records) // 3)
    events = [FaultEvent(rng.randint(lo, hi), "runner.crash_broker",
                         "crash_broker")]
    for _ in range(2):
        events.append(FaultEvent(rng.randint(1, max(2, records // 20)),
                                 "broker.fetch", "delay",
                                 params=(("seconds", 0.001),)))
    return events


def _compaction_under_crash(rng: random.Random, records: int) -> list:
    # the compactor dies at its Nth segment SWAP: the .cleaned rewrite
    # is durable, the live segment still holds the old bytes, and a
    # prefix of earlier segments already swapped — the worst mid-pass
    # shape.  The runner remounts and proves no key lost + byte-stable
    # compacted reads.  A couple of fetch stalls ride along so the
    # pre-kill reads happen under an unquiet consumer.
    events = [FaultEvent(rng.randint(1, 3), "store.compact_swap", "error",
                         params=(("exc", "RuntimeError"),))]
    for _ in range(2):
        events.append(FaultEvent(rng.randint(1, max(2, records // 20)),
                                 "broker.fetch", "delay",
                                 params=(("seconds", 0.001),)))
    return events


def _tier_upload_crash(rng: random.Random, records: int) -> list:
    # the tier uploader dies BETWEEN the segment blob uploads and the
    # remote manifest commit — staged blobs exist remotely but nothing
    # references them, the worst mid-upload shape.  The runner remounts
    # (local AND a fresh cold reader against the remote tier) and
    # proves the torn upload is never served, local bytes stayed
    # authoritative, and the finished re-upload replays byte-identical
    # through the remote leg.  A couple of fetch stalls ride along so
    # pre-kill reads happen under an unquiet consumer.
    events = [FaultEvent(rng.randint(1, 3), "store.tier_upload", "error",
                         params=(("exc", "RuntimeError"),))]
    for _ in range(2):
        events.append(FaultEvent(rng.randint(1, max(2, records // 20)),
                                 "broker.fetch", "delay",
                                 params=(("seconds", 0.001),)))
    return events


def _rebalance_under_chaos(rng: random.Random, records: int) -> list:
    # the cluster drill: a consumer-group member dies mid-epoch, then a
    # SHARD leader dies mid-epoch (after the member's rebalance window
    # opens) — the runner asserts every produced record is scored
    # EXACTLY once across both: survivors inherit the dead member's
    # partitions at its committed frontier, and the promoted shard
    # follower serves identical offsets.  A few wire recv delays ride
    # along so routing retries happen under an unquiet clock.
    lo, hi = max(1, records // 3), max(2, (2 * records) // 3)
    mid = (lo + hi) // 2
    events = [
        FaultEvent(rng.randint(lo, max(lo + 1, mid)),
                   "runner.kill_member", "kill_member"),
        FaultEvent(rng.randint(mid + 1, max(mid + 2, hi)),
                   "runner.kill_shard_leader", "kill_shard_leader"),
    ]
    for _ in range(3):
        events.append(FaultEvent(rng.randint(1, max(2, records // 20)),
                                 "kafka_wire.recv", "delay",
                                 params=(("seconds", 0.001),)))
    return events


def _trainer_crash_mid_checkpoint(rng: random.Random, records: int) -> list:
    # the continuous trainer's checkpoint writer dies INSIDE a registry
    # publication — after the artifacts became visible, before the
    # manifest (the commit marker) landed.  That is the worst spot: a
    # naive registry would serve the torn version.  The runner then
    # "restarts the process" (fresh registry mount + trainer warm start)
    # and proves: readers never saw the torn version, recover() swept
    # exactly it, and training resumed from the last DURABLE manifest's
    # stamped offsets — no gap, no double-train.  registry.commit is hit
    # once per publish (~one per ~50-record round in the runner), so the
    # crash lands on an early-but-not-first checkpoint.
    publishes = max(3, records // 60)
    crash_at = rng.randint(2, max(2, min(4, publishes - 1)))
    events = [FaultEvent(crash_at, "registry.commit", "error",
                         params=(("exc", "RuntimeError"),))]
    # slow-disk flavor on a couple of OTHER writes: serialize/fsync
    # stalls must degrade checkpoint freshness, never training
    for _ in range(2):
        events.append(FaultEvent(rng.randint(1, max(2, publishes)),
                                 "ckpt.write", "delay",
                                 params=(("seconds", 0.002),)))
    return events


def _rollout_regression_rollback(rng: random.Random, records: int) -> list:
    # no injected faults needed — the "failure" is a deliberately
    # degraded CANDIDATE MODEL deployed to serving (deploy-during-eval),
    # and the system under test is the A/B quality gate: it must detect
    # the regression from live scored quality and re-point serving at
    # the baseline within the drill budget.  A couple of scorer stalls
    # ride along so the gate decides under an unquiet clock.
    ticks = max(4, records // CARS_PER_TICK)
    events = []
    for _ in range(2):
        events.append(FaultEvent(rng.randint(1, max(2, ticks)),
                                 "scorer.poll", "delay",
                                 params=(("seconds", 0.001),)))
    return events


def _drift_storm(rng: random.Random, records: int) -> list:
    # seeded regional drift AND flapping device links CONCURRENTLY:
    # the drift itself is runner-topology state (an AdversarialFleet
    # with every cohort shifting at mid-stream, seeded by the schedule
    # seed); the schedule carries the mqtt-flap half — delivery drops
    # (accounted as intentional loss) plus short delay bursts landing
    # while the online learner is mid-adaptation.  The runner proves
    # the learner still detects, adapts, converges and publishes, the
    # scorer fleet hot-swaps, and no record is lost or double-scored
    # across the swap.
    n_drops = max(2, records // 100)
    hits = sorted(rng.sample(range(1, records + 1),
                             min(n_drops, records)))
    events = [FaultEvent(h, "mqtt.deliver", "drop") for h in hits]
    for _ in range(2):
        events.append(FaultEvent(rng.randint(1, max(2, records - 10)),
                                 "mqtt.deliver", "delay",
                                 params=(("seconds", 0.001),), repeat=5))
    return events


def _double_fault(rng: random.Random, records: int) -> list:
    # the quorum-durability drill (ISSUE 14): under sustained acks=all
    # load against a leader + two ISR followers, ONE FOLLOWER dies
    # abruptly (the ISR must evict it within the staleness window and
    # the quorum re-form at width 2), then the LEADER dies mid-epoch
    # with no pre-kill drain — the runner promotes an ISR member at
    # epoch+1 and proves ZERO acked-record loss byte-identically: every
    # produce acked before the kill sits below the quorum HWM, so the
    # surviving ISR member holds it at the identical offset.  A new
    # follower then bootstraps from the promoted leader (the elastic
    # heal) so acks=all resumes for the rest of the stream.  Wire recv
    # delays ride along so failover retries run under an unquiet clock.
    lo, hi = max(1, records // 3), max(2, (2 * records) // 3)
    mid = (lo + hi) // 2
    events = [
        FaultEvent(rng.randint(lo, max(lo + 1, mid)),
                   "runner.kill_follower", "kill_follower"),
        FaultEvent(rng.randint(mid + 1, max(mid + 2, hi)),
                   "runner.kill_leader", "kill_leader"),
    ]
    for _ in range(3):
        events.append(FaultEvent(rng.randint(1, max(2, records // 20)),
                                 "kafka_wire.recv", "delay",
                                 params=(("seconds", 0.001),)))
    return events


def _alert_burn(rng: random.Random, records: int) -> list:
    # the telemetry-plane drill (ISSUE 17): a SUSTAINED slow-bridge
    # degradation — every MQTT delivery delayed well past the canary
    # latency SLO threshold — armed only for the drill's degraded
    # phase.  The system under test is the alerting loop itself: the
    # canary probes must measure the slowdown through the real path,
    # the TSDB must carry it, and the SLO engine's FAST burn-rate pair
    # must fire within the drill budget (then resolve after recovery).
    # A couple of accounted drops ride along so the delivery SLO sees
    # real loss too.
    # far past the drill SLO threshold (0.1 s) so the degraded e2e
    # separates cleanly from the healthy floor (~tens of ms of polling)
    delay_s = round(rng.uniform(0.35, 0.5), 3)
    events = [FaultEvent(1, "mqtt.deliver", "delay",
                         params=(("seconds", delay_s),),
                         repeat=1_000_000)]
    for _ in range(2):
        events.append(FaultEvent(rng.randint(1, max(2, records // 10)),
                                 "mqtt.deliver", "drop"))
    return events


def _loss_bug_fixture(rng: random.Random, records: int) -> list:
    # the seeded bug: one delivery silently lost — NOT ledgered, so the
    # scored-or-accounted invariant must fail (the checker's own test)
    at = rng.randint(2, max(3, records - 2))
    return [FaultEvent(at, "mqtt.deliver", "drop",
                       params=(("account", False),))]


#: name -> (builder, topology, description).  Topology is a static
#: property of each scenario (which runner harness drives it), not
#: something worth compiling a schedule to discover.
SCENARIOS: Dict[str, Tuple[Callable, str, str]] = {
    "leader-kill-mid-drain": (
        _leader_kill, "wire",
        "leader wire-server dies mid-stream; follower replica promoted "
        "via client failover"),
    "mqtt-flap": (
        _mqtt_flap, "inproc",
        "flapping device links: seeded MQTT delivery drops (accounted) "
        "+ delay bursts"),
    "slow-bridge": (
        _slow_bridge, "inproc",
        "sustained delay windows on the MQTT->stream hop"),
    "dup-storm": (
        _dup_storm, "inproc",
        "duplicate MQTT deliveries; at-least-once must absorb them"),
    "partition-blackout": (
        _partition_blackout, "inproc",
        "a window of broker fetches fails with ConnectionError; "
        "consumers retry through"),
    "scorer-crash-resume": (
        _scorer_crash_resume, "inproc",
        "scorer drain dies mid-stream; resumes via rewind-to-committed "
        "redelivery"),
    "loss-bug-fixture": (
        _loss_bug_fixture, "inproc",
        "SEEDED BUG: one silent (unledgered) drop — the invariant "
        "checker must FAIL on it"),
    "broker-crash-recover": (
        _broker_crash_recover, "store",
        "durable broker killed mid-write; remount recovers the torn "
        "tail, acked records re-serve, consumers resume from committed"),
    "compaction-under-crash": (
        _compaction_under_crash, "store",
        "segment compactor killed mid-swap on the compacted twin "
        "changelog; remount sweeps the tmp, loses no key, and compacted "
        "reads stay byte-stable across a second remount"),
    "tier-upload-crash": (
        _tier_upload_crash, "store",
        "tier uploader killed between segment blob uploads and the "
        "remote manifest commit; remount + cold remote reader prove no "
        "torn segment serves, local stays authoritative, and the "
        "finished re-upload replays byte-identical through the remote "
        "tier"),
    "rebalance-under-chaos": (
        _rebalance_under_chaos, "cluster",
        "3-broker cluster: a group member AND a shard leader die "
        "mid-epoch; every record scored exactly once across the "
        "rebalance + per-shard failover"),
    "trainer-crash-mid-checkpoint": (
        _trainer_crash_mid_checkpoint, "mlops",
        "checkpoint writer killed INSIDE a registry publication (torn "
        "version dir); restart resumes model+offsets from the last "
        "durable manifest — no torn state served, no gap, no "
        "double-train"),
    "rollout-regression-rollback": (
        _rollout_regression_rollback, "mlops",
        "a degraded candidate model is deployed to serving; the A/B "
        "quality gate must detect the regression live and roll serving "
        "back to the baseline within the drill budget"),
    "double-fault": (
        _double_fault, "replication",
        "leader + one follower die mid-epoch under sustained acks=all "
        "load: ISR evicts the dead follower, an ISR member is promoted "
        "at epoch+1 with ZERO acked-record loss (byte-identical "
        "offsets), a new follower heals the set and acks=all resumes"),
    "alert-burn": (
        _alert_burn, "obs",
        "sustained slow-bridge degradation under live canary probes: "
        "the e2e latency SLO's FAST burn-rate pair must fire within "
        "budget, land in _IOTML_ALERTS + /healthz, and resolve after "
        "recovery"),
    "drift-storm": (
        _drift_storm, "online",
        "seeded regional drift + flapping links concurrently: the "
        "online learner must detect and adapt, the adapted model must "
        "hot-swap the scorer fleet, and no record is lost or double-"
        "scored across the swap"),
}


def build(name: str, seed: int = 7, records: int = 1000) -> Schedule:
    """Compile a scenario into its deterministic schedule."""
    if name not in SCENARIOS:
        raise KeyError(f"unknown scenario {name!r} "
                       f"(have: {sorted(SCENARIOS)})")
    if records < CARS_PER_TICK:
        raise ValueError(f"records must be >= {CARS_PER_TICK} "
                         f"(one fleet tick), got {records}")
    builder, topology, _ = SCENARIOS[name]
    events = builder(random.Random(seed), records)
    events = tuple(sorted(events, key=lambda e: (e.at, e.point, e.action)))
    return Schedule(name=name, seed=seed, records=records,
                    topology=topology, events=events)
