from .cardata import main  # noqa: F401
