"""Shared scaffolding for the reference-contract streaming CLIs.

Both reference ML apps (`cardata-v3.py`, LSTM `cardata-v2.py`) are the same
program with a different model: positional args, a train mode that fits on
a stream slice and uploads the checkpoint, and a predict mode that restores
it and writes ordered predictions back.  `run_streaming_app` is that
program once; `cli.cardata` and `cli.lstm` supply the model and knobs.

The typed config layer (`iotml.config`) fronts the positional contract:
`--section.field=...` flags and `IOTML_*` env vars override an app's
defaults (epochs, batch size, topics, SASL credentials for the wire
client), and positionals pass through untouched — so the reference's K8s
manifests work verbatim while everything stays configurable without code
edits.
"""

from __future__ import annotations

import os
import sys
import tempfile
from typing import Callable, Optional


def _broker_for(servers: str, topic: str, cfg) -> object:
    """Resolve <servers>: 'emulator[:n]' seeds an in-process broker with
    generated fleet data; 'host:port[,...]' speaks the Kafka wire protocol
    (stream.kafka_wire) to a real cluster or the framework's wire server."""
    from ..stream.broker import Broker

    if servers.startswith("emulator"):
        n = int(servers.split(":", 1)[1]) if ":" in servers else 30_000
        from ..gen.simulator import FleetGenerator, FleetScenario

        broker = Broker()
        gen = FleetGenerator(FleetScenario(num_cars=100, failure_rate=0.01))
        gen.publish(broker, topic, n_ticks=max(1, n // 100))
        broker.create_topic("model-predictions")
        return broker
    from ..stream.kafka_wire import KafkaWireBroker

    return KafkaWireBroker(servers,
                           sasl_username=cfg.broker.sasl_username or None,
                           sasl_password=cfg.broker.sasl_password or None)


def run_streaming_app(argv, *, prog: str, usage: str, make_model: Callable,
                      group: str, epochs: int, batch_size: int,
                      take_batches: int, predict_skip: int,
                      predict_take: int, supervised: bool = False,
                      window: Optional[int] = None,
                      h5_interop: bool = False) -> int:
    from ..config import load_config

    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        cfg, argv = load_config(argv)
    except ValueError as e:
        print(f"config error: {e}")
        return 1
    print("Options: ", argv)
    if len(argv) != 7:
        print(usage)
        return 1
    servers, topic, offset, result_topic, mode, model_file, artifact_root = argv
    mode = mode.strip().lower()
    if mode not in ("train", "predict"):
        print(f"Mode is invalid, must be either 'train' or 'predict': {mode}")
        return 1
    if model_file.endswith(".h5") and not h5_interop:
        # fail BEFORE training, not after: the Keras-h5 exporter maps the
        # 4-Dense autoencoder stack only — an LSTM run ending in a failed
        # export would lose the whole training run
        print(f"{prog}: '.h5' model files (Keras interop) are supported "
              f"for the autoencoder CLI only; use a plain name for an "
              f"orbax checkpoint")
        return 1
    offset = offset.strip().lower()
    if offset != "committed":
        offset = int(offset)

    applied = getattr(cfg, "applied", set())
    if "train.epochs" in applied:
        epochs = cfg.train.epochs
    if "train.batch_size" in applied:
        batch_size = cfg.train.batch_size
    if "train.take_batches" in applied:
        take_batches = cfg.train.take_batches

    from ..data.dataset import SensorBatches
    from ..stream.consumer import StreamConsumer
    from ..train.artifacts import ArtifactStore
    from ..train.checkpoint import CheckpointManager
    from ..train.loop import Trainer

    broker = _broker_for(servers, topic, cfg)
    store = ArtifactStore(artifact_root)

    # This host's partition share: on an indexed multi-host Job each pod
    # consumes a disjoint subset (reference: Kafka partitions × pods,
    # SURVEY §2.7); single-host consumes every partition.  `committed`
    # resumes from the group's offset cursor instead of an absolute offset.
    from ..parallel.distributed import assign_partitions

    try:
        n_parts = broker.topic(topic).partitions
    except KeyError:
        n_parts = 1  # topic not created yet: subscribe partition 0
    n_hosts = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    host_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    # an empty share is legitimate (more hosts than partitions): that host
    # trains on nothing rather than duplicating partition 0 under the same
    # group (which would make shards overlap and offset commits clobber)
    parts = assign_partitions(n_parts, n_hosts, host_id)
    if offset == "committed":
        consumer = StreamConsumer.from_committed(broker, topic, parts,
                                                 group=group)
    else:
        consumer = StreamConsumer(broker,
                                  [f"{topic}:{p}:{offset}" for p in parts],
                                  group=group)
    if not parts:
        print(f"host {host_id}/{n_hosts}: no partition share of "
              f"{n_parts}-partition topic {topic}; idle")
    model = make_model()

    # an explicitly-configured mesh (--mesh.* flags / config file, or the
    # IOTML_MESH_DATA process knob) means the operator reserved multiple
    # chips: train sharded over a ('data', 'model') mesh instead of
    # single-device
    use_mesh = bool({"mesh.data", "mesh.model"} & applied)
    # IOTML_MESH_DATA moved into the process-knob family (ISSUE 15,
    # data/pipeline.py non_config) and no longer reaches cfg through the
    # env resolver — but the deploy manifests' contract (that env var =
    # data-axis chip count, deploy/model-training*.yaml) must keep
    # holding, so the knob feeds the same decision here
    from ..data.pipeline import mesh_data as _mesh_data_knob

    knob = _mesh_data_knob()
    if knob >= 2 and "mesh.data" not in applied:
        # >= 2, matching the knob's contract ("1 behaves like 0") and
        # cli.live's threshold — one env var, one meaning everywhere
        cfg.mesh.data = knob
        use_mesh = True
    if use_mesh:
        import jax

        from ..parallel.data_parallel import ShardedTrainer
        from ..parallel.mesh import auto_mesh

        model_par = max(cfg.mesh.model, 1)
        n_dev = len(jax.devices()) if cfg.mesh.data in (-1, 0) \
            else cfg.mesh.data * model_par
        mesh = auto_mesh(n_dev, model_parallel=model_par)
        print(f"mesh: {dict(mesh.shape)} over {n_dev} devices")
        trainer = ShardedTrainer(model, mesh, supervised=supervised,
                                 learning_rate=cfg.train.learning_rate)
    else:
        trainer = Trainer(model, supervised=supervised,
                          learning_rate=cfg.train.learning_rate)

    if mode == "train":
        batches = SensorBatches(consumer, batch_size=batch_size,
                                take=take_batches, window=window,
                                only_normal=not supervised and
                                cfg.train.only_normal)
        history = trainer.fit(batches, epochs=epochs) if use_mesh \
            else trainer.fit_compiled(batches, epochs=epochs)
        # empty stream: fit_compiled returns an empty history; the step-loop
        # fits return placeholder losses but never initialize state — either
        # way there is nothing worth checkpointing
        if not history["loss"] or trainer.state is None:
            print("No records in this host's partition share; nothing "
                  "trained, nothing stored")
            return 0
        print(f"Training complete, final loss {history['loss'][-1]:.6f}")
        # unique dir: concurrent jobs on one host must not trample each other
        ckpt_dir = tempfile.mkdtemp(prefix=f"iotml_{prog}_ckpt_")
        if model_file.endswith(".h5"):
            # reference artifact-format parity: its CLI moves Keras h5
            # blobs through the store (cardata-v3.py:227-231, model file
            # arg "model1.h5") — an .h5 name keeps that contract, so a
            # consumer still on the reference stack can load models
            # trained here
            import jax
            import numpy as _np

            from ..models.h5_export import autoencoder_params_to_h5

            local_h5 = os.path.join(ckpt_dir, "model.h5")
            autoencoder_params_to_h5(
                jax.tree.map(_np.asarray, trainer.state.params), local_h5)
            store.upload(local_h5, model_file)
        else:
            mgr = CheckpointManager(ckpt_dir)
            path = mgr.save(trainer.state, cursors=consumer.positions())
            store.upload_tree(path, model_file)
        # commit AFTER the checkpoint is durable: the group cursor is the
        # resume point the '<offset>=committed' rerun contract promises
        consumer.commit()
        print("Model stored successfully", model_file)
        return 0

    # predict
    print("Downloading model", model_file)
    local = os.path.join(tempfile.mkdtemp(prefix=f"iotml_{prog}_restore_"),
                         "ckpt")
    if model_file.endswith(".h5"):
        from ..models.h5_import import autoencoder_params_from_h5

        store.download(model_file, local)
        payload = {"params": autoencoder_params_from_h5(local)}
    else:
        store.download_tree(model_file, local)
        import orbax.checkpoint as ocp

        payload = ocp.PyTreeCheckpointer().restore(local)
    print("Loading model")
    from ..serve.scorer import StreamScorer
    from ..stream.producer import OutputSequence

    batches = SensorBatches(consumer, batch_size=batch_size,
                            window=window, skip=predict_skip,
                            take=predict_take)
    out = OutputSequence(broker, result_topic, partition=0)
    scorer = StreamScorer(model, payload["params"], batches, out)
    n = scorer.score_available()
    print(f"predict complete: {n} records → {result_topic} "
          f"(end offset {broker.end_offset(result_topic, 0)})")
    return 0
