"""Reference-compatible CLI for the car-sensor train/predict jobs.

The reference entry point is
`python3 cardata-v3.py <servers> <topic> <offset> <result_topic> <mode>
<model-file> <project>` (cardata-v3.py:24-37).  This CLI keeps that
positional contract (so the reference's K8s manifests translate 1:1) with
one extension: `<servers>` may be `emulator[:n_records]` to run against the
in-process broker with generated fleet data — the cluster-free path used by
tests, demos and benches.  `<project>` becomes the artifact-store root
(local dir or gs:// bucket), replacing the hard-coded GCS bucket scheme.

Train mode mirrors cardata-v3 exactly: filter label=="false", batch 100,
take 100 batches, 20 epochs, then store the model.  Predict mode loads the
stored model, scores batches 100..200, and writes np.array2string rows to
<result_topic> in stream order.
"""

from __future__ import annotations

import os
import sys

NB_EPOCH = 20
BATCH_SIZE = 100
TAKE_BATCHES = 100
PREDICT_SKIP = 100  # data_offset in the reference (cardata-v3.py:269)

USAGE = ("usage: python -m iotml.cli.cardata <servers> <topic> <offset> "
         "<result_topic> <mode:train|predict> <model-file> <artifact-root>\n"
         "  servers: emulator[:n_records] | host:port[,host:port...]")


def _broker_for(servers: str, topic: str, offset: int):
    """Resolve <servers>: the emulator scheme seeds an in-process broker;
    anything else requires the native Kafka client (not yet wired — the
    C++ data plane lands in cpp/stream)."""
    from ..stream.broker import Broker

    if servers.startswith("emulator"):
        n = int(servers.split(":", 1)[1]) if ":" in servers else 30_000
        from ..gen.simulator import FleetGenerator, FleetScenario

        broker = Broker()
        gen = FleetGenerator(FleetScenario(num_cars=100, failure_rate=0.01))
        gen.publish(broker, topic, n_ticks=max(1, n // 100))
        broker.create_topic("model-predictions")
        return broker
    raise SystemExit(
        f"servers={servers!r}: external Kafka requires the native stream "
        f"engine (cpp/stream); use 'emulator[:n]' for the in-process broker")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    print("Options: ", argv)
    if len(argv) != 7:
        print(USAGE)
        return 1
    servers, topic, offset, result_topic, mode, model_file, artifact_root = argv
    mode = mode.strip().lower()
    if mode not in ("train", "predict"):
        print(f"Mode is invalid, must be either 'train' or 'predict': {mode}")
        return 1
    offset = int(offset)

    from ..data.dataset import SensorBatches
    from ..models.autoencoder import CAR_AUTOENCODER
    from ..stream.consumer import StreamConsumer
    from ..train.artifacts import ArtifactStore
    from ..train.checkpoint import CheckpointManager
    from ..train.loop import Trainer

    broker = _broker_for(servers, topic, offset)
    store = ArtifactStore(artifact_root)
    consumer = StreamConsumer(broker, [f"{topic}:0:{offset}"],
                              group="cardata-autoencoder")
    trainer = Trainer(CAR_AUTOENCODER)

    if mode == "train":
        batches = SensorBatches(consumer, batch_size=BATCH_SIZE,
                                take=TAKE_BATCHES, only_normal=True)
        history = trainer.fit_compiled(batches, epochs=NB_EPOCH)
        print(f"Training complete, final loss {history['loss'][-1]:.6f}")
        ckpt_dir = os.path.join("/tmp", "iotml_cli_ckpt")
        mgr = CheckpointManager(ckpt_dir)
        path = mgr.save(trainer.state, cursors=consumer.positions())
        store.upload_tree(path, model_file)
        print("Model stored successfully", model_file)
        return 0

    # predict
    print("Downloading model", model_file)
    local = os.path.join("/tmp", "iotml_cli_restore")
    store.download_tree(model_file, local)
    import orbax.checkpoint as ocp

    payload = ocp.PyTreeCheckpointer().restore(local)
    print("Loading model")
    from ..serve.scorer import StreamScorer
    from ..stream.producer import OutputSequence

    batches = SensorBatches(consumer, batch_size=BATCH_SIZE,
                            skip=PREDICT_SKIP, take=TAKE_BATCHES)
    out = OutputSequence(broker, result_topic, partition=0)
    scorer = StreamScorer(CAR_AUTOENCODER, payload["params"], batches, out)
    n = scorer.score_available()
    print(f"predict complete: {n} records → {result_topic} "
          f"(end offset {broker.end_offset(result_topic, 0)})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
