"""Reference-compatible CLI for the car-sensor autoencoder train/predict jobs.

The reference entry point is
`python3 cardata-v3.py <servers> <topic> <offset> <result_topic> <mode>
<model-file> <project>` (cardata-v3.py:24-37).  This CLI keeps that
positional contract (so the reference's K8s manifests translate 1:1) with
two extensions: `<servers>` may be `emulator[:n_records]` for the
in-process broker with generated fleet data (the cluster-free path used by
tests, demos and benches) or `host:port` for a Kafka-wire-protocol broker;
and `--section.field=...` flags / `IOTML_*` env override the reference's
hard-coded knobs (see `iotml.config`).  `<project>` becomes the
artifact-store root (local dir or gs:// bucket).

Train mode mirrors cardata-v3 exactly: filter label=="false", batch 100,
take 100 batches, 20 epochs, then store the model.  Predict mode loads the
stored model, scores batches 100..200, and writes np.array2string rows to
<result_topic> in stream order.
"""

from __future__ import annotations

from ._app import _broker_for, run_streaming_app  # noqa: F401 (re-export)

NB_EPOCH = 20
BATCH_SIZE = 100
TAKE_BATCHES = 100
PREDICT_SKIP = 100  # data_offset in the reference (cardata-v3.py:269)

USAGE = ("usage: python -m iotml.cli.cardata <servers> <topic> <offset> "
         "<result_topic> <mode:train|predict> <model-file> <artifact-root>\n"
         "  servers: emulator[:n_records] | host:port[,host:port...]")


def _make_model():
    from ..models.autoencoder import CAR_AUTOENCODER

    return CAR_AUTOENCODER


def main(argv=None) -> int:
    return run_streaming_app(
        argv, prog="cardata", usage=USAGE, make_model=_make_model,
        group="cardata-autoencoder", epochs=NB_EPOCH, batch_size=BATCH_SIZE,
        take_batches=TAKE_BATCHES, predict_skip=PREDICT_SKIP,
        predict_take=TAKE_BATCHES, h5_interop=True)


if __name__ == "__main__":
    raise SystemExit(main())
