"""Creditcard fraud demo CLI — producer + consumer + eval in one process.

The reference splits this across two scripts and a notebook
(`Sensor-Kafka-Producer-From-CSV.py`, `Sensor-Kafka-Consumer-and-TensorFlow-
Model-Training.py`, eval cells 21-26 of the fraud notebook).  One command
here runs the same pipeline against the in-process broker: CSV → topic
(raw lines) → decode → scale → filter(Class==0) → train the 30-dim
autoencoder → score the full stream → threshold/ROC/AUC report.

    python -m iotml.cli.creditcard synth                  # synthetic data
    python -m iotml.cli.creditcard /path/creditcard.csv   # the Kaggle file
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="iotml.cli.creditcard", description=__doc__)
    p.add_argument("csv", help="path to creditcard.csv, or 'synth[:n_rows]'")
    p.add_argument("--epochs", type=int, default=5,
                   help="reference consumer: nb_epoch=5")
    p.add_argument("--batch-size", type=int, default=32,
                   help="reference consumer: batch_size=32")
    p.add_argument("--threshold", type=float, default=5.0,
                   help="reference notebook decision threshold (cell 24)")
    p.add_argument("--no-scale", action="store_true",
                   help="skip Time/Amount standardization (the reference "
                        "streaming consumer's unscaled behavior)")
    p.add_argument("--topic", default="creditcard")
    return p


def run(argv=None) -> dict:
    from ..data.creditcard import (SCALED_COLUMNS, CreditcardBatches,
                                   StandardScaler, decode_csv_batch,
                                   produce_csv_lines, synth_creditcard_csv)
    from ..evaluate import evaluate_detector, reconstruction_errors
    from ..models.autoencoder import CREDITCARD_AUTOENCODER
    from ..stream.broker import Broker
    from ..stream.consumer import StreamConsumer
    from ..train.loop import Trainer

    args = build_parser().parse_args(argv)

    tmp = None
    csv_path = args.csv
    if csv_path.startswith("synth"):
        n_rows = int(csv_path.split(":", 1)[1]) if ":" in csv_path else 2000
        tmp = tempfile.NamedTemporaryFile(suffix=".csv", delete=False)
        tmp.close()
        csv_path = tmp.name
        synth_creditcard_csv(csv_path, n_rows=n_rows)

    try:
        broker = Broker()
        n = produce_csv_lines(broker, args.topic, csv_path)

        scaler = None if args.no_scale else StandardScaler(columns=SCALED_COLUMNS)
        train_batches = CreditcardBatches(
            StreamConsumer(broker, [f"{args.topic}:0:0"], group="creditcard"),
            batch_size=args.batch_size, only_normal=True, scaler=scaler)
        trainer = Trainer(CREDITCARD_AUTOENCODER)
        history = trainer.fit_compiled(train_batches, epochs=args.epochs)

        # score the *whole* stream (frauds included) with the TRAINING
        # moments frozen — eval must see the same scale the model trained on
        if scaler is not None:
            scaler.freeze()
        eval_batches = CreditcardBatches(
            StreamConsumer(broker, [f"{args.topic}:0:0"], group="creditcard-eval"),
            batch_size=args.batch_size, scaler=scaler)
        xs, ys = [], []
        for b in eval_batches:
            xs.append(b.x[: b.n_valid])
            ys.append(b.labels[: b.n_valid])
        import numpy as np
        x = np.concatenate(xs)
        y = np.concatenate(ys)
        report = evaluate_detector(CREDITCARD_AUTOENCODER, trainer.state.params,
                                   x, y, threshold=args.threshold)
        out = {"records": n, "final_loss": history["loss"][-1],
               "report": report.as_dict()}
        print(json.dumps(out))
        return out
    finally:
        if tmp is not None:
            os.unlink(tmp.name)


if __name__ == "__main__":
    run()
