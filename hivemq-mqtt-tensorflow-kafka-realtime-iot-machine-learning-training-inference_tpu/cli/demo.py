"""End-to-end demo: the reference's whole pipeline in one command.

The reference demo needs a GKE cluster, Terraform, seven Helm releases, a
device-simulator fleet, a KSQL install, and two K8s app deployments before
the first anomaly score appears (reference `infrastructure/README.md`).
This command runs the same story in one process on one TPU chip:

  fleet (MQTT TCP) → bridge → sensor-data → KSQL pipeline → framed Avro →
  streaming train (fused Pallas fit) → orbax checkpoint → artifact store →
  continuous scorer → ordered predictions + anomaly verdicts → metrics

    python -m iotml.cli.demo [--cars 50] [--seconds 10] [--epochs 5]

Prints a JSON summary (records through each stage, final loss, anomaly
counts) and exits cleanly — also usable as the framework's integration
smoke test.
"""

from __future__ import annotations

import argparse
import json
import tempfile
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m iotml.cli.demo",
                                 description=__doc__)
    ap.add_argument("--cars", type=int, default=50)
    ap.add_argument("--seconds", type=float, default=8.0,
                    help="how long the fleet publishes before training")
    ap.add_argument("--rate", type=float, default=10.0, help="msgs/car/s")
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--failure-rate", type=float, default=0.02)
    ap.add_argument("--threshold", type=float, default=None,
                    help="anomaly threshold on reconstruction error "
                         "(default: 99th percentile of training errors)")
    args = ap.parse_args(argv)

    from ..cli.up import Platform
    from ..data.dataset import SensorBatches
    from ..evaluate.anomaly import reconstruction_errors
    from ..models.autoencoder import CAR_AUTOENCODER
    from ..serve.scorer import StreamScorer
    from ..stream.consumer import StreamConsumer
    from ..stream.producer import OutputSequence
    from ..train.artifacts import ArtifactStore
    from ..train.checkpoint import CheckpointManager
    from ..train.loop import Trainer

    t_start = time.perf_counter()
    plat = Platform(partitions=4).start()
    try:
        # ---- L1/L2: fleet publishes over real MQTT for a while
        plat.start_fleet(args.cars, rate_hz=args.rate,
                         failure_rate=args.failure_rate)
        print(f"fleet: {args.cars} cars @ {args.rate}/s over MQTT "
              f"for {args.seconds}s ...")
        deadline = time.time() + args.seconds
        while time.time() < deadline:
            time.sleep(0.25)
            plat.pump()  # L4: KSQL pipeline keeps up with the stream
        plat.stop_fleet()  # joins the publisher: stream is quiescent now
        plat.pump()
        ingested = plat.bridge.forwarded()

        # ---- L5 train: consume the KSQL output topic, fused Pallas fit
        spec = plat.broker.topic("SENSOR_DATA_S_AVRO")
        consumer = StreamConsumer(
            plat.broker,
            [f"SENSOR_DATA_S_AVRO:{p}:0" for p in range(spec.partitions)],
            group="demo-train")
        batches = SensorBatches(consumer, batch_size=100, only_normal=True)
        trainer = Trainer(CAR_AUTOENCODER)
        history = trainer.fit_compiled(batches, epochs=args.epochs)
        if not history["loss"]:
            print("no records ingested; is the fleet publishing?")
            return 1

        # ---- checkpoint → artifact store (the train→bucket→serve handoff)
        root = tempfile.mkdtemp(prefix="iotml_demo_store_")
        ckpt = CheckpointManager(tempfile.mkdtemp(prefix="iotml_demo_ck_"))
        path = ckpt.save(trainer.state, cursors=consumer.positions())
        ArtifactStore(root).upload_tree(path, "demo-model")

        # ---- threshold from training reconstruction errors
        threshold = args.threshold
        if threshold is None:
            import numpy as np

            consumer.seek_to_start()
            # normal rows only: an anomaly-contaminated percentile would
            # inflate the threshold past the very anomalies it must catch
            sample = next(iter(SensorBatches(consumer, batch_size=512,
                                             only_normal=True)))
            errs = reconstruction_errors(CAR_AUTOENCODER,
                                         trainer.state.params,
                                         sample.x[: sample.n_valid])
            threshold = float(np.percentile(np.asarray(errs), 99.0))

        # ---- L5 serve: score everything, ordered write-back + verdicts
        consumer2 = StreamConsumer(
            plat.broker,
            [f"SENSOR_DATA_S_AVRO:{p}:0" for p in range(spec.partitions)],
            group="demo-serve")
        scorer = StreamScorer(
            CAR_AUTOENCODER, trainer.state.params,
            SensorBatches(consumer2, batch_size=100),
            OutputSequence(plat.broker, "model-predictions", partition=0),
            threshold=threshold)
        scored = scorer.score_available()

        anomalies = 0
        n_pred = plat.broker.end_offset("model-predictions", 0)
        off = plat.broker.begin_offset("model-predictions", 0)
        while off < n_pred:
            for m in plat.broker.fetch("model-predictions", 0, off, 2048):
                anomalies += b"|anomaly|" in m.value
                off = m.offset + 1

        # ---- persisted model-quality report beside the model (the
        # notebook's ROC/PR/threshold cells as report.json + report.svg)
        import numpy as np

        from ..evaluate.anomaly import evaluate_detector
        from ..evaluate.report import write_report

        # re-read through the serve consumer (rewound) rather than a third
        # consumer group; the one extra batched forward pass computes the
        # labeled scores the scorer does not retain
        consumer2.seek_to_start()
        xs, ys = [], []
        for b in SensorBatches(consumer2, batch_size=512, keep_labels=True):
            xs.append(b.x[: b.n_valid])
            ys.append(b.labels[: b.n_valid])
        x_eval = np.concatenate(xs)
        y_eval = np.concatenate(ys) != "false"
        eval_scores = np.asarray(reconstruction_errors(
            CAR_AUTOENCODER, trainer.state.params, x_eval))
        eval_report = evaluate_detector(CAR_AUTOENCODER, trainer.state.params,
                                        x_eval, y_eval, threshold=threshold,
                                        scores=eval_scores)
        report_paths = write_report(
            eval_report, eval_scores, y_eval,
            tempfile.mkdtemp(prefix="iotml_demo_report_"),
            store=ArtifactStore(root), name="demo-model-eval")

        summary = {
            "cars": args.cars,
            "mqtt_messages_bridged": ingested,
            "ksql_avro_records": sum(
                plat.broker.end_offset("SENSOR_DATA_S_AVRO", p)
                for p in range(spec.partitions)),
            "trained_records_per_epoch": history["records"][0],
            "epochs": args.epochs,
            "loss_first_to_last": [round(history["loss"][0], 4),
                                   round(history["loss"][-1], 4)],
            "anomaly_threshold": round(threshold, 4),
            "scored": scored,
            "anomalies_flagged": int(anomalies),
            "roc_auc": round(eval_report.roc_auc, 4),
            "avg_precision": round(eval_report.avg_precision, 4),
            "eval_report": report_paths["uploaded"] or report_paths["json"],
            "wall_seconds": round(time.perf_counter() - t_start, 2),
        }
        print(json.dumps(summary, indent=2))
        return 0
    finally:
        plat.stop()


if __name__ == "__main__":
    raise SystemExit(main())
