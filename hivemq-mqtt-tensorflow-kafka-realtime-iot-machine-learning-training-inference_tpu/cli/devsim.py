"""Device-simulator CLI — the `kubectl devsim` plugin, natively.

The reference manages its load-generator fleet with a 500-line kubectl
plugin (`infrastructure/test-generator/kube-cli.sh`): `run` creates a
commander pod from a scenario XML, `jobs/show/log` inspect running
simulations, `abort` tears one down, `example` prints a starter scenario
(usage: `kube-cli.sh:26-47`).  This CLI provides the same verbs with
processes instead of pods:

    python -m iotml.cli.devsim run -s scenario.xml [options]
    python -m iotml.cli.devsim jobs
    python -m iotml.cli.devsim show  <job>
    python -m iotml.cli.devsim log   <job>
    python -m iotml.cli.devsim abort <job>
    python -m iotml.cli.devsim example

`run` executes the scenario against an in-process MQTT broker by default
(deterministic fast mode), or against a real MQTT endpoint with
`--tcp HOST:PORT` (e.g. the broker from `python -m iotml.cli.up`).
`--detach` runs it as a background job tracked under `$IOTML_DEVSIM_DIR`
(default `~/.iotml/devsim`), which is what jobs/show/log/abort manage —
the state directory plays the role the Kubernetes API plays for the
reference plugin.

Scale-down and full scenarios matching the reference's
`scenario_evaluation.xml` / `scenario.xml` ship in
`iotml/gen/scenarios/`.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import uuid

STATE_DIR_ENV = "IOTML_DEVSIM_DIR"

EXAMPLE_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "gen", "scenarios",
    "scenario_evaluation.xml")


def _state_dir() -> str:
    d = os.environ.get(STATE_DIR_ENV) or \
        os.path.join(os.path.expanduser("~"), ".iotml", "devsim")
    os.makedirs(d, exist_ok=True)
    return d


def _job_dir(job: str) -> str:
    return os.path.join(_state_dir(), job)


def _load_meta(job: str) -> dict:
    path = os.path.join(_job_dir(job), "job.json")
    if not os.path.exists(path):
        raise SystemExit(f"no such job: {job}")
    with open(path) as fh:
        return json.load(fh)


def _save_meta(job: str, meta: dict) -> None:
    with open(os.path.join(_job_dir(job), "job.json"), "w") as fh:
        json.dump(meta, fh, indent=2)


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def _job_state(meta: dict) -> str:
    if meta.get("aborted"):
        return "Aborted"
    if _alive(meta["pid"]):
        return "Running"
    return "Completed"


# ------------------------------------------------------------------- verbs

def cmd_run(args) -> int:
    with open(args.scenario) as fh:
        xml_text = fh.read()

    if args.detach:
        job = f"devsim-{time.strftime('%Y%m%d-%H%M%S')}-{uuid.uuid4().hex[:4]}"
        jd = _job_dir(job)
        os.makedirs(jd, exist_ok=True)
        log_path = os.path.join(jd, "job.log")
        child_args = [sys.executable, "-m", "iotml.cli.devsim", "run",
                      "-s", os.path.abspath(args.scenario),
                      "--time-scale", str(args.time_scale),
                      "--encoding", args.encoding]
        if args.tcp:
            child_args += ["--tcp", args.tcp]
        if args.cap:
            child_args += ["--cap", str(args.cap)]
        if args.metrics_port:
            child_args += ["--metrics-port", str(args.metrics_port)]
        with open(log_path, "ab") as log:
            proc = subprocess.Popen(child_args, stdout=log, stderr=log,
                                    start_new_session=True)
        _save_meta(job, {"job": job, "pid": proc.pid,
                         "scenario": os.path.abspath(args.scenario),
                         "tcp": args.tcp, "started": time.time(),
                         "aborted": False})
        print(job)
        return 0

    from ..mqtt.broker import MqttBroker
    from ..mqtt.scenario import ScenarioRunner, parse_scenario

    if args.metrics_port:
        # agent_connect_*/agent_publish_* land in the default registry
        # (reference devsim.json metric families); expose them for scrapes
        from ..obs.metrics import start_http_server
        start_http_server(args.metrics_port)

    scenario = parse_scenario(xml_text)
    if args.cap:
        # scale-down cap, like running the reference scenario under the
        # free license: clamp every group's client/topic/message count
        for g in scenario.client_groups.values():
            g.count = min(g.count, args.cap)
        for g in scenario.topic_groups.values():
            g.count = min(g.count, args.cap)

    transport, port, broker = "inproc", None, MqttBroker()
    if args.tcp:
        host, _, p = args.tcp.rpartition(":")
        scenario.broker_address, scenario.broker_port = host, int(p)
        transport, port = "tcp", int(p)
    runner = ScenarioRunner(scenario, broker, transport=transport, port=port,
                            time_scale=args.time_scale)
    t0 = time.time()
    counts = runner.run(payload_encoding=args.encoding)
    wall = time.time() - t0
    summary = {"scenario": os.path.basename(args.scenario),
               "wall_s": round(wall, 3), **counts,
               "consumers": runner.consumer_counts}
    print(json.dumps(summary))
    return 0


def cmd_jobs(args) -> int:
    rows = []
    for job in sorted(os.listdir(_state_dir())):
        try:
            meta = _load_meta(job)
        except SystemExit:
            continue
        rows.append((job, _job_state(meta),
                     time.strftime("%H:%M:%S",
                                   time.localtime(meta["started"])),
                     meta.get("tcp") or "inproc"))
    if not rows:
        print("no jobs")
        return 0
    print(f"{'JOB':42s} {'STATE':10s} {'STARTED':9s} BROKER")
    for r in rows:
        print(f"{r[0]:42s} {r[1]:10s} {r[2]:9s} {r[3]}")
    return 0


def cmd_show(args) -> int:
    meta = _load_meta(args.job)
    meta["state"] = _job_state(meta)
    log_path = os.path.join(_job_dir(args.job), "job.log")
    if os.path.exists(log_path):
        with open(log_path) as fh:
            tail = fh.readlines()[-5:]
        meta["log_tail"] = [ln.rstrip() for ln in tail]
    print(json.dumps(meta, indent=2))
    return 0


def cmd_log(args) -> int:
    _load_meta(args.job)  # existence check
    log_path = os.path.join(_job_dir(args.job), "job.log")
    if os.path.exists(log_path):
        with open(log_path) as fh:
            sys.stdout.write(fh.read())
    return 0


def cmd_abort(args) -> int:
    meta = _load_meta(args.job)
    if _alive(meta["pid"]):
        try:
            # the detached job leads its own session; signal the whole group
            os.killpg(meta["pid"], signal.SIGTERM)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(meta["pid"], signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
    meta["aborted"] = True
    _save_meta(args.job, meta)
    print(f"aborted {args.job}")
    return 0


def cmd_example(args) -> int:
    with open(EXAMPLE_PATH) as fh:
        sys.stdout.write(fh.read())
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.cli.devsim",
        description="Scenario-driven device-fleet simulator "
                    "(the reference's kubectl devsim plugin, as processes)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("run", help="run a scenario")
    p.add_argument("-s", "--scenario", required=True)
    p.add_argument("--tcp", metavar="HOST:PORT", default=None,
                   help="publish over real MQTT to this endpoint "
                        "(default: in-process broker, fast mode)")
    p.add_argument("--time-scale", type=float, default=0.0,
                   help="0 = as fast as possible; 1 = real-time rates")
    p.add_argument("--encoding", choices=("json", "avro"), default="json")
    p.add_argument("--cap", type=int, default=0, metavar="N",
                   help="clamp client/topic counts to N (scale-down mode)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve agent_* metrics in Prometheus format")
    p.add_argument("--detach", action="store_true",
                   help="run as a background job (see jobs/show/log/abort)")
    p.set_defaults(fn=cmd_run)

    sub.add_parser("jobs", help="list jobs").set_defaults(fn=cmd_jobs)
    for verb, fn in (("show", cmd_show), ("log", cmd_log),
                     ("abort", cmd_abort)):
        pv = sub.add_parser(verb, help=f"{verb} a job")
        pv.add_argument("job")
        pv.set_defaults(fn=fn)
    sub.add_parser("example", help="print an example scenario XML") \
        .set_defaults(fn=cmd_example)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
