"""Long-lived train / score services over the Kafka wire — the continuous
twin of the batch CLIs.

The reference runs training as a restarted Job and prediction as a
restarted Deployment (`run.sh:16-91`, python-scripts/README.md:24-26 calls
the restart loop out as "not an ideal architecture").  These entry points
are the long-lived form, one process each, matching the deploy manifests'
pod separation (`deploy/model-training.yaml`, `deploy/model-predictions.yaml`):

    python -m iotml.cli.live train  <servers> <topic> <artifact_root>
    python -m iotml.cli.live score  <servers> <topic> <result_topic> <artifact_root>

Both connect over the real Kafka wire protocol (native C++ client when
built, pure-Python fallback).  `--stats` prints one JSON line per round /
drain on stdout for an orchestrating process; both exit cleanly when stdin
closes or receives a STOP line (the supervisor contract), or after
`--max-seconds`.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time


def _wire_broker(servers: str, sasl: str):
    user, pw = (sasl.split(":", 1) if sasl else (None, None))
    try:
        from ..stream.native_kafka import NativeKafkaBroker

        return NativeKafkaBroker(servers, sasl_username=user,
                                 sasl_password=pw)
    except Exception as e:
        # The fallback exists for boxes without the C++ engine; anything
        # else (bad SASL, unreachable host) will fail again in the pure
        # client with less context — say why we fell back.
        print(json.dumps({"event": "native_kafka_fallback",
                          "error": f"{type(e).__name__}: {e}"}),
              file=sys.stderr, flush=True)
        from ..stream.kafka_wire import KafkaWireBroker

        return KafkaWireBroker(servers, sasl_username=user, sasl_password=pw)


def _stopper(max_seconds: float):
    """stop() that trips on stdin EOF / a STOP line / the deadline."""
    ev = threading.Event()

    def watch_stdin():
        for line in sys.stdin:
            if line.strip() == "STOP":
                break
        ev.set()

    from ..supervise.registry import register_thread

    register_thread(threading.Thread(target=watch_stdin, daemon=True,
                                     name="iotml-stdin-watch")).start()
    deadline = time.time() + max_seconds if max_seconds else None
    return lambda: ev.is_set() or (deadline is not None
                                   and time.time() > deadline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.cli.live",
        description="continuous train/score services over the Kafka wire")
    sub = ap.add_subparsers(dest="cmd", required=True)

    tr = sub.add_parser("train", help="continuous trainer → artifacts")
    tr.add_argument("servers")
    tr.add_argument("topic")
    tr.add_argument("artifact_root")
    tr.add_argument("--model-name", default="cardata-live.h5")
    tr.add_argument("--group", default="cardata-live-train")
    tr.add_argument("--take-batches", type=int, default=20)
    tr.add_argument("--batch-size", type=int, default=100)
    tr.add_argument("--epochs-per-round", type=int, default=1)
    tr.add_argument("--checkpoint-interval-s", type=float, default=0.5,
                    help="async-checkpoint cadence with --registry: "
                         "snapshots arriving faster are coalesced "
                         "(newest wins); 0 archives every round")
    tr.add_argument("--backfill-since-ms", type=int, default=None,
                    help="cold start: begin from the first retained "
                         "record at/after this timestamp (durable-store "
                         "replay API) instead of offset 0; partitions "
                         "with a committed cursor still resume from it")

    sc = sub.add_parser("score", help="continuous scorer with hot-swap")
    sc.add_argument("servers")
    sc.add_argument("topic")
    sc.add_argument("result_topic")
    sc.add_argument("artifact_root")
    sc.add_argument("--model-name", default="cardata-live.h5")
    sc.add_argument("--group", default="cardata-live-score")
    sc.add_argument("--threshold", type=float, default=5.0)
    sc.add_argument("--car-threshold", default="0.38",
                    help="per-car EMA alert level, or 'auto' "
                         "(fleet-quantile calibration; needs a stable "
                         "model)")
    sc.add_argument("--car-feature-heads", action="store_true",
                    help="per-feature error + value-drift heads on the "
                         "car detector (weak failure modes; pair with "
                         "--normalize full — see serve/carhealth.py)")
    sc.add_argument("--batch-size", type=int, default=100)
    sc.add_argument("--wait-model-seconds", type=float, default=120.0)

    for p in (tr, sc):
        p.add_argument("--registry", default=None, metavar="DIR",
                       help="versioned model registry root (iotml.mlops): "
                            "train publishes async checkpoints stamped "
                            "with stream offsets (crash-consistent "
                            "resume, no training stall); score follows "
                            "the registry's serving channel — promote/"
                            "rollback flips hot-swap the scorer")
        p.add_argument("--normalize", choices=("parity", "full"),
                       default="parity",
                       help="parity = the reference's normalization "
                            "(its four TODO fields zeroed); full = all "
                            "18 fields live (detection-grade — battery "
                            "faults are invisible under parity).  Train "
                            "and score must match.")
        p.add_argument("--sasl", default=None, metavar="USER:PASS")
        p.add_argument("--stats", action="store_true",
                       help="print one JSON line per round/drain")
        p.add_argument("--max-seconds", type=float, default=0.0,
                       help="exit after this long (0 = until stdin closes)")
        p.add_argument("--wait-topic-seconds", type=float, default=60.0,
                       help="wait this long for the input topic to appear")
        p.add_argument("--prefetch-depth", type=int, default=None,
                       help="host→device prefetch queue depth (sets "
                            "IOTML_PREFETCH_DEPTH; default 2)")
        p.add_argument("--decode-ring-buffers", type=int, default=None,
                       help="reusable columnar decode buffers (sets "
                            "IOTML_DECODE_RING_BUFFERS; default 4)")
        p.add_argument("--raw-batch-bytes", type=int, default=None,
                       help="max bytes per raw frame fetch (sets "
                            "IOTML_RAW_BATCH_BYTES; default 1 MiB)")
        p.add_argument("--raw-produce", default=None,
                       choices=("auto", "on", "off"),
                       help="zero-copy produce plane (sets "
                            "IOTML_RAW_PRODUCE; default auto)")
        p.add_argument("--produce-batch-bytes", type=int, default=None,
                       help="max frame bytes per RAW_PRODUCE request "
                            "(sets IOTML_PRODUCE_BATCH_BYTES; default "
                            "1 MiB)")
        p.add_argument("--metrics-port", type=int, default=0,
                       help="serve /metrics + /healthz on this port "
                            "(0 = off); with IOTML_OBS_ENDPOINTS set "
                            "the endpoint auto-joins the fleet's "
                            "federation manifest (iotml.obs fleet)")
        p.add_argument("--mesh-data", type=int, default=None,
                       help="multi-chip streaming training: data-axis "
                            "size of the device mesh (sets "
                            "IOTML_MESH_DATA; 0/absent = single-chip). "
                            "Each device consumes its own partition "
                            "subset and the jitted step all-reduces "
                            "gradients over the mesh (train only)")
        p.add_argument("--device-normalize", default=None,
                       choices=("0", "1"),
                       help="fold the affine normalization into the "
                            "jitted step so the host ships raw columns "
                            "(sets IOTML_DEVICE_NORMALIZE; needs "
                            "--mesh-data >= 2)")

    args = ap.parse_args(argv)
    from ..data.pipeline import device_normalize as _dev_norm_knob
    from ..data.pipeline import mesh_data as _mesh_knob
    from ..data.pipeline import set_knobs

    try:
        set_knobs(prefetch_depth=args.prefetch_depth,
                  decode_ring_buffers=args.decode_ring_buffers,
                  raw_batch_bytes=args.raw_batch_bytes,
                  produce_batch_bytes=args.produce_batch_bytes,
                  raw_produce=args.raw_produce,
                  mesh_data=args.mesh_data,
                  device_normalize=None if args.device_normalize is None
                  else args.device_normalize == "1")
        mesh_devices = _mesh_knob()
        dev_norm = _dev_norm_knob()
    except ValueError as e:
        ap.error(str(e))
    if dev_norm and mesh_devices < 2:
        ap.error("IOTML_DEVICE_NORMALIZE=1 needs IOTML_MESH_DATA >= 2 "
                 "(the affine fold lives in the sharded step)")
    if args.metrics_port:
        from ..obs.metrics import start_http_server

        start_http_server(args.metrics_port)
    broker = _wire_broker(args.servers, args.sasl)
    stop = _stopper(args.max_seconds)

    # the input topic may be created by an upstream stage (the KSQL CSAS
    # materializes SENSOR_DATA_S_AVRO only once records flow): wait for it
    deadline = time.time() + args.wait_topic_seconds
    while True:
        try:
            refresh = getattr(broker, "refresh_topic", None)
            if (refresh(args.topic) if refresh is not None
                    else broker.topic(args.topic)) is not None:
                break
        except KeyError:
            pass
        if stop() or time.time() > deadline:
            print(f"topic {args.topic} not available after "
                  f"{args.wait_topic_seconds}s")
            return 1
        time.sleep(0.1)

    def emit(stats: dict) -> None:
        if args.stats:
            print(json.dumps(stats), flush=True)

    from ..core.normalize import CAR_NORMALIZER, FULL_NORMALIZER
    from ..train.artifacts import ArtifactStore

    normalizer = (FULL_NORMALIZER if args.normalize == "full"
                  else CAR_NORMALIZER)
    store = ArtifactStore(args.artifact_root)
    registry = None
    checkpointer = None
    if args.registry:
        from ..config import load_config
        from ..mlops import ModelRegistry

        registry = ModelRegistry(args.registry)
        if args.cmd == "train":
            from ..mlops.checkpoint import AsyncCheckpointer

            registry.recover()  # sweep torn publishes from a prior kill
            # env-resolved mlops policy (IOTML_MLOPS_*): queue depth,
            # promote-on-publish vs gate-owned, optimizer archival,
            # retention — the CLI flag only owns the cadence
            mcfg = load_config([])[0].mlops
            checkpointer = AsyncCheckpointer(
                registry, queue_depth=mcfg.queue_depth,
                save_opt_state=mcfg.save_opt_state,
                auto_promote=mcfg.auto_promote,
                keep_versions=mcfg.keep_versions,
                min_interval_s=args.checkpoint_interval_s)
    if args.cmd == "train":
        from ..train.live import ContinuousTrainer

        mesh = None
        if mesh_devices >= 2:
            # the multi-chip path (IOTML_MESH_DATA): one data-axis mesh
            # over the first N local devices, partition-parallel feeds,
            # sharded jitted step — ARCHITECTURE §24
            import jax

            from ..parallel.mesh import make_mesh

            if mesh_devices > len(jax.devices()):
                ap.error(f"IOTML_MESH_DATA={mesh_devices} but only "
                         f"{len(jax.devices())} local devices")
            mesh = make_mesh((mesh_devices,), ("data",),
                             devices=jax.devices()[:mesh_devices])
        svc = ContinuousTrainer(broker, args.topic, store,
                                model_name=args.model_name, group=args.group,
                                batch_size=args.batch_size,
                                take_batches=args.take_batches,
                                epochs_per_round=args.epochs_per_round,
                                normalizer=normalizer,
                                backfill_since_ms=args.backfill_since_ms,
                                registry=registry,
                                checkpointer=checkpointer,
                                mesh=mesh, device_normalize=dev_norm)
        print(f"live train: {args.topic} rounds of "
              f"{args.take_batches}x{args.batch_size} -> "
              f"{args.artifact_root}/{args.model_name}"
              + (f" + registry {args.registry}" if registry else "")
              + (f" [mesh data={mesh_devices}"
                 f"{', device-normalize' if dev_norm else ''}]"
                 if mesh is not None else ""),
              flush=True)
        rounds = svc.run(stop=stop, on_round=emit)
        svc.close()  # flush pending checkpoints, stop the writer
        print(f"live train done: {rounds} rounds, "
              f"{svc.records_trained} records, last loss {svc.last_loss}",
              flush=True)
    else:
        from ..serve.live import LiveScorer

        car_th = args.car_threshold if args.car_threshold == "auto" \
            else float(args.car_threshold)
        svc = LiveScorer(broker, args.topic, args.result_topic, store,
                         model_name=args.model_name, group=args.group,
                         threshold=args.threshold,
                         car_threshold=car_th,
                         car_feature_heads=args.car_feature_heads,
                         batch_size=args.batch_size,
                         normalizer=normalizer, registry=registry)
        artifact = svc.wait_for_model(args.wait_model_seconds)
        print(f"live score: model {artifact} loaded; "
              f"{args.topic} -> {args.result_topic}", flush=True)
        n = svc.run(stop=stop, on_drain=emit)
        q = svc.scorer.quality
        print(f"live score done: {n} rows, {svc.model_updates} model "
              f"updates, quality {q}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
