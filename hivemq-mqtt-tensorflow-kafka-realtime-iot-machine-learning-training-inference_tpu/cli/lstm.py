"""LSTM streaming app — the reference's supervised next-step predictor CLI.

Positional contract mirrors `LSTM-TensorFlow-IO-Kafka/cardata-v2.py`
(same shape as the autoencoder v3 CLI: servers topic offset result_topic
mode model-file artifact-root), with `emulator[:n]` standing in for a
cluster, like `cli.cardata`.

Reference semantics kept (LSTM cardata-v1.py:165-200, v2 adds mode+GCS):
windows of `look_back` consecutive records with the next record as target
(window(look_back, shift=1) + skip(look_back)), MSE loss, 5 epochs; predict
mode loads the stored model and writes next-step predictions to the result
topic in stream order.  The TPU translation re-batches the reference's
pathological batch=1 into [B, T, F] windows (SURVEY §7 hard part (f)) —
same objective, same architecture, accelerator-sane shapes.
"""

from __future__ import annotations

from ._app import run_streaming_app

NB_EPOCH = 5
BATCH_SIZE = 64       # reference trains batch=1; re-batched for the MXU
LOOK_BACK = 1
TRAIN_TAKE = 1000     # reference: 1000 train steps (batch 1) = 1000 windows
PREDICT_TAKE = 200    # reference: 200 predict steps

USAGE = ("usage: python -m iotml.cli.lstm <servers> <topic> <offset> "
         "<result_topic> <mode:train|predict> <model-file> <artifact-root>\n"
         "  servers: emulator[:n_records] | host:port[,host:port...]")


def _make_model():
    from ..models.lstm import LSTMSeq2Seq

    return LSTMSeq2Seq(features=18, look_back=LOOK_BACK)


def main(argv=None) -> int:
    n_batches = max(1, TRAIN_TAKE // BATCH_SIZE)
    return run_streaming_app(
        argv, prog="lstm", usage=USAGE, make_model=_make_model,
        group="cardata-lstm", epochs=NB_EPOCH, batch_size=BATCH_SIZE,
        take_batches=n_batches, predict_skip=n_batches,
        predict_take=max(1, PREDICT_TAKE // BATCH_SIZE),
        supervised=True, window=LOOK_BACK)


if __name__ == "__main__":
    raise SystemExit(main())
