"""MNIST ingestion smoke test — broker path vs no-broker control.

The reference pair: produce MNIST bytes to topics `xx`/`yy`, consume via
KafkaDataset, train Flatten→Dense(128)→Dense(10)
(`confluent-tensorflow-io-kafka.py`), with an in-memory control model
(`confluent-tensorflow-io-kafka-simplified.py`) to tell ingestion bugs from
model bugs.  Same experiment here: both paths train jit-compiled on
identical data; the smoke test passes when the streamed path's loss curve
falls and the two paths' record counts agree.

    python -m iotml.cli.mnist_smoke [--n 2000 --epochs 2 --batch-size 32]
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax


def classifier_fit(model, images, labels, batch_size: int, epochs: int,
                   learning_rate: float = 1e-3, seed: int = 0) -> dict:
    """Scanned cross-entropy fit (one XLA program for all epochs×batches)."""
    n = (images.shape[0] // batch_size) * batch_size
    xs = images[:n].reshape((-1, batch_size) + images.shape[1:]) \
        .astype(np.float32)
    ys = labels[:n].reshape(-1, batch_size).astype(np.int32)

    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1,) + images.shape[1:], jnp.float32))["params"]
    tx = optax.adam(learning_rate)
    opt_state = tx.init(params)

    def loss_fn(p, x, y):
        logits = model.apply({"params": p}, x)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        acc = (logits.argmax(-1) == y).mean()
        return loss, acc

    def batch_step(carry, inp):
        p, s = carry
        x, y = inp
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, x, y)
        updates, s = tx.update(grads, s, p)
        return (optax.apply_updates(p, updates), s), (loss, acc)

    @jax.jit
    def fit(p, s, xs, ys):
        def epoch(carry, _):
            carry, (losses, accs) = jax.lax.scan(batch_step, carry, (xs, ys))
            return carry, (losses.mean(), accs.mean())
        return jax.lax.scan(epoch, (p, s), None, length=epochs)

    (params, _), (losses, accs) = fit(params, opt_state, xs, ys)
    return {"params": params,
            "loss": np.asarray(losses).tolist(),
            "accuracy": np.asarray(accs).tolist(),
            "records": n}


def run(argv=None) -> dict:
    from ..data.mnist_stream import MnistBatches, produce_mnist, synth_mnist
    from ..models.mnist import MNISTBaseline, MNISTClassifier
    from ..stream.broker import Broker

    p = argparse.ArgumentParser(prog="iotml.cli.mnist_smoke",
                                description=__doc__)
    p.add_argument("--n", type=int, default=2000)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args(argv)

    images, labels = synth_mnist(args.n)

    # --- streamed path: produce → topics xx/yy → zip-consume → train
    broker = Broker()
    produced = produce_mnist(broker, images, labels)
    batches = list(MnistBatches(broker, batch_size=args.batch_size))
    streamed_records = sum(b.n_valid for b in batches)
    sx = np.concatenate([b.x[: b.n_valid] for b in batches])
    sy = np.concatenate([b.y[: b.n_valid] for b in batches])
    streamed = classifier_fit(MNISTClassifier(), sx, sy,
                              args.batch_size, args.epochs)

    # --- control path: identical data straight from memory, control model
    control = classifier_fit(MNISTBaseline(), images.astype(np.float32),
                             labels, args.batch_size, args.epochs)

    out = {
        "produced": produced,
        "streamed_records": streamed_records,
        "ingestion_intact": bool(streamed_records == produced
                                 and np.array_equal(sx, images.astype(np.float32))
                                 and np.array_equal(sy, labels)),
        "streamed": {"loss": streamed["loss"], "accuracy": streamed["accuracy"]},
        "control": {"loss": control["loss"], "accuracy": control["accuracy"]},
    }
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    run()
