"""Long-lived continuous scorer — the serve-side entry point.

The reference's inference Deployment scores a fixed slice, exits, and lets
Kubernetes restart the pod forever — called out by its own docs as "not an
ideal architecture … Python batch style" (reference
python-scripts/README.md:24).  This CLI is the fix the reference wishes
for, and what `deploy/model-predictions.yaml` actually runs: restore the
model once, then poll the stream indefinitely, scoring what arrives and
writing ordered predictions back, with consumer-group offset commits so a
crash (or pod reschedule) resumes exactly where it stopped.

    python -m iotml.cli.serve <servers> <topic> <offset|committed|group>
        <result_topic> <model-file> <artifact-root>

`offset` may be `committed` to resume every partition from the consumer
group's last committed position (fresh start at 0 if none), or `group` for
elastic membership: multiple replicas of this command split the topic's
partitions through the group coordinator (over the Kafka wire protocol when
the broker speaks it) and rebalance on scale-out or crash.  `--serve.*`
flags / env tune polling and the anomaly threshold (see `iotml.config`).
"""

from __future__ import annotations

import sys
import tempfile

USAGE = ("usage: python -m iotml.cli.serve <servers> <topic> "
         "<offset|committed|group> <result_topic> <model-file> "
         "<artifact-root>\n"
         "  servers: emulator[:n_records] | host:port[,host:port...]\n"
         "  offset:  absolute | committed (resume cursor) | group (elastic "
         "replica membership)")

GROUP = "iotml-serve"


def main(argv=None, max_rounds=None) -> int:
    """max_rounds bounds the forever-loop for tests; None = run forever."""
    from ..config import load_config

    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        cfg, argv = load_config(argv)
    except ValueError as e:
        print(f"config error: {e}")
        return 1
    print("Options: ", argv)
    if len(argv) != 6:
        print(USAGE)
        return 1
    servers, topic, offset, result_topic, model_file, artifact_root = argv
    offset = offset.strip().lower()

    from ._app import _broker_for
    from ..data.dataset import SensorBatches
    from ..serve.scorer import StreamScorer
    from ..stream.consumer import StreamConsumer
    from ..stream.producer import OutputSequence
    from ..train.artifacts import ArtifactStore

    broker = _broker_for(servers, topic, cfg)
    store = ArtifactStore(artifact_root)

    print("Downloading model", model_file)
    local = tempfile.mkdtemp(prefix="iotml_serve_") + "/ckpt"
    store.download_tree(model_file, local)
    import orbax.checkpoint as ocp

    payload = ocp.PyTreeCheckpointer().restore(local)

    def all_parts():
        try:
            return list(range(broker.topic(topic).partitions))
        except KeyError:
            return [0]

    if offset == "group":
        # elastic membership: replicas of this scorer split the topic's
        # partitions via the group coordinator and heal on scale/crash —
        # the reference's scalable predict Deployment (SURVEY §2.7), with
        # rebalancing instead of fixed shards.  Remote coordination over
        # the wire protocol when the broker speaks it; in-process otherwise.
        from ..stream.group import GroupConsumer, GroupCoordinator

        if hasattr(broker, "join_group"):
            from ..stream.kafka_wire import RemoteGroupCoordinator

            coord = RemoteGroupCoordinator(broker, GROUP)
        else:
            coord = GroupCoordinator(broker, GROUP)
        consumer = GroupConsumer(coord, [topic])
    elif offset == "committed":
        consumer = StreamConsumer.from_committed(
            broker, topic, all_parts(), group=GROUP, eof=False)
    else:
        consumer = StreamConsumer(
            broker, [f"{topic}:{p}:{int(offset)}" for p in all_parts()],
            group=GROUP, eof=False)

    from ..models.autoencoder import CAR_AUTOENCODER

    threshold = getattr(cfg.serve, "threshold", 0.0) or None
    batches = SensorBatches(consumer, batch_size=cfg.train.batch_size)
    out = OutputSequence(broker, result_topic, partition=0)
    scorer = StreamScorer(CAR_AUTOENCODER, payload["params"], batches, out,
                          threshold=threshold)
    print(f"serving: polling {topic} every {cfg.serve.poll_interval_s}s "
          f"→ {result_topic}")
    scorer.run_forever(poll_interval_s=cfg.serve.poll_interval_s,
                       max_rounds=max_rounds)
    print(f"serve loop exited after scoring {scorer.scored} records")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
