"""One-command platform bring-up — the install-scripts layer, natively.

The reference provisions its stack with Terraform shelling into
`01_installConfluentPlatform.sh` (Prometheus operator, ZK/Kafka/SR/Connect/
KSQL via Helm, topic creation, KSQL DDL) plus `hivemq/setup.sh` (MQTT
broker + Kafka extension) — hundreds of lines of orchestration before the
first record can flow (SURVEY §2.6, §3.5).  Here the same platform comes up
in one process:

    python -m iotml.cli.up [--sasl user:pass] [--fleet N] [--quiet]

brings up and prints endpoints for
  - the stream broker, served over the real Kafka wire protocol (TCP,
    optional SASL/PLAIN like the reference's `gcp.yaml:29-32`), with the
    reference's topics pre-created (`sensor-data`, `model-predictions`,
    10 partitions — `01_installConfluentPlatform.sh:180-183`)
  - an MQTT broker (TCP) bridged into `sensor-data` with the reference's
    topic mapping `vehicles/sensor/data/#` (`kafka-config.yaml:20-29`)
  - the Schema Registry REST API (with both car schemas pre-registered)
  - the KSQL-equivalent REST API, reference DDL pipeline pre-installed
  - the Kafka-Connect REST API
  - a Prometheus /metrics exporter
  - a control-center UI (live topics/queries/sessions/metrics — the
    Confluent C3 / HiveMQ Control Center stand-in)

With `--fleet N`, N simulated cars publish continuously over real MQTT —
the whole reference demo, minus the Kubernetes cluster.  Ctrl-C stops
everything.  This is also importable: `Platform().start()` for tests.
"""

from __future__ import annotations

import argparse
import threading
import time
from typing import Optional


class Platform:
    """All reference services over one in-process broker."""

    def __init__(self, sasl: Optional[tuple] = None, partitions: int = 10,
                 kafka_port: int = 0, mqtt_port: int = 0,
                 registry_port: int = 0, ksql_port: int = 0,
                 connect_port: int = 0, host: str = "127.0.0.1",
                 retention_messages: Optional[int] = None, cc_port: int = 0,
                 store_dir: Optional[str] = None, store_policy=None,
                 tier=None,
                 trusted_passthrough: Optional[bool] = None,
                 registry_dir: Optional[str] = None,
                 registry_watch_poll_s: float = 0.25):
        from ..connect import ConnectServer, ConnectWorker
        from ..core.schema import CAR_SCHEMA, KSQL_CAR_SCHEMA
        from ..mqtt.bridge import KafkaBridge
        from ..mqtt.broker import MqttBroker
        from ..mqtt.eventserver import MqttEventServer
        from ..obs import metrics as obs_metrics
        from ..stream import Broker, SchemaRegistry, SchemaRegistryServer
        from ..stream.kafka_wire import KafkaWireServer
        from ..stream.registry import subject_for_topic
        from ..streamproc import KsqlServer, SqlEngine
        from ..streamproc.sql import install_reference_pipeline

        # durable mode (iotml.store): every partition is a crash-
        # recoverable segmented log on disk, consumer offsets persist,
        # and a restarted platform re-serves everything it acked — the
        # "no data lake" training substrate surviving the process
        self.store_dir = store_dir
        self.broker = Broker(store_dir=store_dir, store_policy=store_policy,
                             tier=tier)
        # durable brokers get the background dirty-ratio compactor: a
        # platform with compacted topics (the CAR_TWIN changelog) must
        # actually reclaim them, not only when a drill calls
        # run_compaction by hand.  No-op cadence on brokers with no
        # compact topics; None on the in-memory backend.  Built here,
        # STARTED in start() like every other component thread.
        self.compactor = None
        if self.broker.store is not None:
            from ..store import StoreCompactor
            self.compactor = StoreCompactor(
                self.broker,
                interval_s=self.broker.store.policy.compact_interval_s)
        # tiered stores additionally get the background uploader that
        # offloads sealed segments to the object store and enforces the
        # hot-tier byte budget.  Same lifecycle shape as the compactor.
        self.uploader = None
        if self.broker.store is not None and tier:
            from ..store import TierUploader
            self.uploader = TierUploader(self.broker,
                                         interval_s=tier.interval_s)
        # the reference's two topics, its partition count.  retention
        # bounds the in-memory log for long-running platforms (the
        # reference sets retention.ms=100000 — aggressive 100s retention,
        # 01_installConfluentPlatform.sh:180-183); None keeps everything,
        # which week-long soak tests will notice.
        self.broker.create_topic("sensor-data", partitions=partitions,
                                 retention_messages=retention_messages)
        self.broker.create_topic("model-predictions", partitions=partitions,
                                 retention_messages=retention_messages)

        self.host = host
        self.kafka = KafkaWireServer(self.broker, host=host, port=kafka_port,
                                     credentials=sasl)
        self.registry = SchemaRegistry()
        self.registry.register(subject_for_topic("sensor-data"),
                               CAR_SCHEMA.avro_json())
        self.registry.register(subject_for_topic("SENSOR_DATA_S_AVRO"),
                               KSQL_CAR_SCHEMA.avro_json())
        self.registry_server = SchemaRegistryServer(self.registry, host=host,
                                                    port=registry_port)

        # trusted_passthrough: the platform's REKEY leg reads the AVRO leg
        # this same engine encodes in-process, so re-validating every
        # pass-through payload would only re-check the engine's own
        # encoder output (external producers still validate — the flag
        # narrows itself to engine-produced sources).  The soundness
        # premise — only the engine writes the AVRO leg — is ENFORCED,
        # not inferred: the broker marks SENSOR_DATA_S_AVRO* engine-owned
        # and rejects produces without the engine's grant; a wire/native
        # client with SASL creds gets TOPIC_AUTHORIZATION_FAILED instead
        # of silently forking the validated stream (ADVICE.md round-5).
        #
        # Exposure policy (the rest of that finding): trust DEFAULTS OFF
        # when the wire server binds a non-loopback address — an exposed
        # platform's threat model includes the broker-side grant being
        # misconfigured, so pass-through batches are fully re-validated
        # there unless the operator opts back in.  On loopback the
        # engine trusts its own encoder but still SAMPLE-VALIDATES one
        # batch in 32 (catches encoder regressions, ~3% of the cost).
        exposed = host not in ("127.0.0.1", "localhost", "::1")
        if trusted_passthrough is None:
            trusted_passthrough = not exposed
        owner = self.broker.restrict_topic("SENSOR_DATA_S_AVRO")
        self.sql = SqlEngine(self.broker, registry=self.registry,
                             trusted_passthrough=trusted_passthrough,
                             owner_token=owner,
                             passthrough_sample=32)
        install_reference_pipeline(self.sql)
        self.ksql = KsqlServer(self.sql, host=host, port=ksql_port)

        self.connect_worker = ConnectWorker(self.broker)
        self.connect = ConnectServer(self.connect_worker, host=host,
                                     port=connect_port)
        # digital twin for car health (the reference's MongoDB sink on the
        # car stream, mongodb-connector-configmap.yaml:6-23): the
        # per-car failure detector publishes keyed alert records onto
        # `car-health` (serve/carhealth.py) and this sink upserts them by
        # car id — the operator looks up a car and sees its latest state
        # (control center surfaces the active alerts; ConnectServer's
        # driver thread pumps the sink continuously once started)
        from ..connect import DocumentStoreSink

        self.broker.create_topic("car-health",
                                 retention_messages=retention_messages)
        self.car_twin = DocumentStoreSink(id_field="car")
        self.connect.register_sink(
            "car-health-twin", self.car_twin, ["car-health"],
            kind="DocumentStoreSink",
            config={"connector.class": "DocumentStoreSink",
                    "topics": "car-health", "document.id.field": "car"})

        self.mqtt_broker = MqttBroker()
        self.bridge = KafkaBridge(self.mqtt_broker, self.broker,
                                  partitions=partitions)
        # the epoll front: fleet-scale connection counts + HiveMQ-style
        # overload protection (watermark backpressure, slow-consumer
        # eviction) — same MqttProtocol semantics as the threaded server
        self.mqtt = MqttEventServer(self.mqtt_broker, host=host,
                                    port=mqtt_port)

        # model-lifecycle wing (iotml.mlops): mount the versioned
        # registry, sweep torn publishes from a prior kill, and keep a
        # watcher on the serving channel — scorers attach to it, and
        # /healthz + the version gauge carry the platform's model
        # identity.  A trainer process hands its AsyncCheckpointer to
        # attach_checkpointer() so --supervise owns the writer loop.
        self.registry_dir = registry_dir
        self.model_registry = None
        self.registry_watcher = None
        self.checkpoint_writer = None
        if registry_dir:
            from ..mlops import ModelRegistry
            from ..mlops.rollout import RegistryWatcher

            self.model_registry = ModelRegistry(registry_dir,
                                                component="platform")
            self.model_registry.recover()
            self.registry_watcher = RegistryWatcher(
                self.model_registry, component="platform",
                poll_interval_s=registry_watch_poll_s)

        from ..obs.control_center import ControlCenter

        self.control_center = ControlCenter(self, host=host, port=cc_port)
        self._obs = obs_metrics
        self.metrics_server = None
        self._fleet_stop = threading.Event()
        self._fleet_thread: Optional[threading.Thread] = None
        self.started = False

    def attach_checkpointer(self, checkpointer):
        """Register a trainer's AsyncCheckpointer so ``supervised()``
        runs its writer as a supervised unit (crash -> restart under
        backoff, pending snapshots surviving in the queue)."""
        self.checkpoint_writer = checkpointer
        return checkpointer

    def start(self, metrics_port: Optional[int] = None) -> "Platform":
        self.kafka.start()
        self.registry_server.start()
        self.ksql.start()
        self.connect.start()
        self.mqtt.start()
        if self.registry_watcher is not None:
            self.registry_watcher.start()
        if self.compactor is not None:
            self.compactor.start()
        if self.uploader is not None:
            self.uploader.start()
        if metrics_port is not None:
            self.metrics_server = self._obs.start_http_server(metrics_port)
        self.control_center.start()
        self.started = True
        return self

    def endpoints(self) -> dict:
        out = {} if self.store_dir is None else {"store": self.store_dir}
        if self.uploader is not None:
            out["tier"] = self.broker.store.tier.uri
        if self.registry_dir:
            out["registry"] = self.registry_dir
        out.update({
            "kafka": f"{self.host}:{self.kafka.port}",
            "mqtt": f"{self.host}:{self.mqtt.port}",
            "schema-registry": self.registry_server.url,
            "ksql": self.ksql.url,
            "connect": self.connect.url,
            "control-center": self.control_center.url,
        })
        if self.metrics_server is not None:
            out["metrics"] = "http://127.0.0.1:" + \
                str(self.metrics_server.server_address[1]) + "/metrics"
        return out

    # ------------------------------------------------------------- fleet
    def start_fleet(self, num_cars: int, rate_hz: float = 1.0,
                    failure_rate: float = 0.01) -> None:
        """Continuous simulated fleet publishing over real MQTT (the device
        simulator's role, `scenario.xml` semantics at 1 msg/`rate_hz`)."""
        from ..core.schema import KSQL_CAR_SCHEMA
        from ..gen.simulator import FleetGenerator, FleetScenario
        from ..mqtt.wire import MqttClient

        scenario = FleetScenario(num_cars=num_cars, failure_rate=failure_rate)
        gen = FleetGenerator(scenario)

        def run():
            import json as _json

            # socket budget: at most 64 TCP connections; cars beyond that
            # multiplex round-robin over the open connections (every car
            # still publishes on its own MQTT topic every tick)
            n_conns = min(num_cars, 64)
            # connect to the address the platform actually listens on; a
            # wildcard bind is reachable via loopback
            connect_host = "127.0.0.1" if self.host in ("0.0.0.0", "::") else self.host
            clients = [
                MqttClient(connect_host, self.mqtt.port, scenario.car_id(i))
                for i in range(n_conns)
            ]
            try:
                while not self._fleet_stop.wait(1.0 / rate_hz):
                    cols = gen.step_columns()
                    for i in range(num_cars):
                        rec = gen.row_record(cols, i, KSQL_CAR_SCHEMA)
                        clients[i % n_conns].publish(
                            f"vehicles/sensor/data/{scenario.car_id(i)}",
                            _json.dumps(rec).encode(), qos=0)
            finally:
                for c in clients:
                    try:
                        c.disconnect()
                    except OSError:
                        pass

        from ..supervise.registry import register_thread

        self._fleet_thread = register_thread(threading.Thread(
            target=run, daemon=True, name="iotml-fleet"))
        self._fleet_thread.start()

    def stop_fleet(self) -> None:
        """Stop the simulated fleet and wait for its last publishes to
        land (join the thread), so callers can pump once afterwards and
        see a quiescent stream."""
        self._fleet_stop.set()
        if self._fleet_thread is not None:
            self._fleet_thread.join(timeout=10)
            self._fleet_thread = None

    def pump(self) -> int:
        """Advance continuous queries + connectors once (deterministic)."""
        n = self.ksql.pump_now()
        self.connect.pump_now()
        return n

    # ------------------------------------------------------- supervision
    def supervised(self, poll_interval_s: Optional[float] = None):
        """A Supervisor owning this platform's component lifecycles.

        ``start()`` alone launches every component fire-and-forget (the
        pre-supervision behavior, kept for tests); this wraps each
        component's serving thread(s) in a probed unit so a crashed
        accept loop / pump loop / event loop is detected and its thread
        respawned under backoff — the kubelet role the reference
        delegates to Kubernetes Deployments (SURVEY §2.6/§2.7).  The
        MQTT→Kafka bridge has no thread of its own (it runs inside the
        MQTT delivery path) and needs no unit.  Returns the Supervisor
        (caller starts/stops it); unit states surface on ``/healthz``."""
        import os as _os

        from ..supervise.registry import register_thread
        from ..supervise.supervisor import Supervisor

        if poll_interval_s is None:
            # platform default is laxer than the Supervisor's (these are
            # thread-aliveness probes, not failover detection), but the
            # IOTML_SUPERVISE_POLL_S knob must still win when set
            poll_interval_s = float(_os.environ.get(
                "IOTML_SUPERVISE_POLL_S", "0.25"))
        sup = Supervisor(poll_interval_s=poll_interval_s,
                         name="platform-supervisor")

        def thread_alive(get_thread):
            def probe():
                t = get_thread()
                return t is not None and t.is_alive()
            return probe

        def respawn(get_thread, spawn):
            def restart():
                t = get_thread()
                if t is None or not t.is_alive():
                    spawn()
            return restart

        sup.add_probed(
            "kafka-wire", thread_alive(lambda: self.kafka._thread),
            restart=respawn(lambda: self.kafka._thread,
                            self.kafka.start))
        sup.add_probed(
            "mqtt-front", thread_alive(lambda: self.mqtt._thread),
            restart=respawn(lambda: self.mqtt._thread, self.mqtt.start))

        def spawn_ksql_pump():
            # respawn ONLY the pump thread: KsqlServer.start() would
            # also duplicate the live REST serving thread
            self.ksql._pump_thread = register_thread(threading.Thread(
                target=self.ksql._pump_loop, daemon=True,
                name="iotml-ksql-pump"))
            self.ksql._pump_thread.start()

        sup.add_probed(
            "ksql-tasks", thread_alive(lambda: self.ksql._pump_thread),
            restart=respawn(lambda: self.ksql._pump_thread,
                            spawn_ksql_pump))

        def spawn_connect_driver():
            self.connect._driver = register_thread(threading.Thread(
                target=self.connect._drive, daemon=True,
                name="iotml-connect-driver"))
            self.connect._driver.start()

        sup.add_probed(
            "connect-driver", thread_alive(lambda: self.connect._driver),
            restart=respawn(lambda: self.connect._driver,
                            spawn_connect_driver))
        for name, rest in (("schema-registry", self.registry_server),
                           ("control-center", self.control_center)):
            sup.add_probed(
                name, thread_alive(lambda r=rest: r._thread),
                restart=respawn(lambda r=rest: r._thread,
                                lambda r=rest: r.start()))
        if self._fleet_thread is not None:
            sup.add_probed(
                "fleet", thread_alive(lambda: self._fleet_thread))
        # the model-lifecycle units (ISSUE 7): the registry watcher's
        # poll thread is probed+respawned like every serving thread, and
        # an attached checkpoint writer runs as a supervised LOOP unit —
        # a crashed writer restarts under backoff with its pending
        # snapshots intact in the bounded queue
        if self.registry_watcher is not None:
            sup.add_probed(
                "registry-watcher",
                thread_alive(lambda: self.registry_watcher._thread),
                restart=respawn(lambda: self.registry_watcher._thread,
                                self.registry_watcher.start))
        if self.checkpoint_writer is not None:
            sup.add_loop("ckpt-writer", self.checkpoint_writer.unit_loop(),
                         heartbeat_timeout_s=30.0)
        return sup

    def stop(self) -> None:
        self._fleet_stop.set()
        if self._fleet_thread is not None:
            self._fleet_thread.join(timeout=3)
        if self.registry_watcher is not None:
            self.registry_watcher.stop()
        if self.checkpoint_writer is not None:
            self.checkpoint_writer.stop(flush=True)
        for s in (self.connect, self.ksql, self.registry_server,
                  self.control_center):
            s.stop()
        self.kafka.shutdown()
        self.kafka.server_close()
        self.mqtt.stop()
        if self.metrics_server is not None:
            self.metrics_server.shutdown()
            self.metrics_server.server_close()
            self.metrics_server = None
        if self.uploader is not None:
            self.uploader.stop()
        if self.compactor is not None:
            self.compactor.stop()
        self.broker.close()  # durable: fsync + release fds (no-op else)
        self.started = False


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.cli.up",
        description="Bring up the full streaming-ML platform in one process")
    ap.add_argument("--sasl", metavar="USER:PASS", default=None,
                    help="require SASL/PLAIN on the Kafka wire port")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="start N simulated cars publishing over MQTT")
    ap.add_argument("--rate", type=float, default=1.0,
                    help="fleet publish rate per car (Hz)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address for every server (0.0.0.0 in a pod)")
    ap.add_argument("--kafka-port", type=int, default=0)
    ap.add_argument("--mqtt-port", type=int, default=0)
    ap.add_argument("--registry-port", type=int, default=0)
    ap.add_argument("--ksql-port", type=int, default=0)
    ap.add_argument("--connect-port", type=int, default=0)
    ap.add_argument("--cc-port", type=int, default=0,
                    help="control-center UI port (topics/queries/metrics)")
    ap.add_argument("--metrics-port", type=int, default=9100)
    ap.add_argument("--retention", type=int, default=0, metavar="N",
                    help="keep at most N messages per partition "
                         "(0 = unbounded; the reference retains ~100s). "
                         "Validated by the broker (negative rejected).")
    ap.add_argument("--durable", action="store_true",
                    help="mount the broker on a durable segmented log "
                         "(iotml.store): crash recovery, persisted "
                         "consumer offsets, disk retention.  Dir from "
                         "--store-dir / IOTML_STORE_DIR / "
                         "/tmp/iotml-store; fsync & retention knobs ride "
                         "the store.* config section.")
    ap.add_argument("--store-dir", default=None, metavar="DIR",
                    help="store directory for --durable (also enables "
                         "durable mode when given)")
    ap.add_argument("--tier-uri", default=None, metavar="URI",
                    help="object-store URI (gs://... or a local path) for "
                         "tiered storage: sealed segments upload to the "
                         "remote tier and the local dir becomes a hot "
                         "cache.  Requires durable mode.  Also via "
                         "IOTML_TIER_URI; budget/lag knobs ride the "
                         "tier.* config section.")
    ap.add_argument("--registry", default=None, metavar="DIR",
                    help="mount a versioned model registry (iotml.mlops): "
                         "torn publishes swept at boot, the serving "
                         "channel watched (supervised under --supervise), "
                         "model identity on /healthz.  Also via "
                         "IOTML_MLOPS_REGISTRY_DIR.")
    ap.add_argument("--supervise", action="store_true",
                    help="run component lifecycles under the "
                         "iotml.supervise supervisor (crashed serving "
                         "threads restart under backoff; unit states on "
                         "/healthz).  Also enabled by IOTML_SUPERVISE=1.")
    ap.add_argument("--trust-passthrough", dest="trust_passthrough",
                    action="store_true", default=None,
                    help="opt back into trusted pass-through on a "
                         "non-loopback host (default: exposed platforms "
                         "fully re-validate pass-through batches; "
                         "loopback trusts with 1-in-32 sampling)")
    ap.add_argument("--prefetch-depth", type=int, default=None,
                    metavar="N",
                    help="host→device prefetch queue depth for every "
                         "in-process consumer pipeline (sets "
                         "IOTML_PREFETCH_DEPTH; default 2)")
    ap.add_argument("--decode-ring-buffers", type=int, default=None,
                    metavar="N",
                    help="reusable columnar decode buffers per pipeline "
                         "(sets IOTML_DECODE_RING_BUFFERS; default 4, "
                         "min 2)")
    ap.add_argument("--raw-batch-bytes", type=int, default=None,
                    metavar="BYTES",
                    help="max bytes per raw frame fetch on the "
                         "zero-copy consume path (sets "
                         "IOTML_RAW_BATCH_BYTES; default 1 MiB)")
    ap.add_argument("--raw-produce", default=None,
                    choices=("auto", "on", "off"),
                    help="zero-copy produce plane (sets "
                         "IOTML_RAW_PRODUCE): auto = RAW_PRODUCE where "
                         "supported with classic fallback, on = raw "
                         "required (CI parity), off = classic "
                         "everywhere (debug)")
    ap.add_argument("--produce-batch-bytes", type=int, default=None,
                    metavar="BYTES",
                    help="max frame bytes per RAW_PRODUCE request "
                         "(sets IOTML_PRODUCE_BATCH_BYTES; default "
                         "1 MiB)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    from ..data.pipeline import set_knobs

    try:
        set_knobs(prefetch_depth=args.prefetch_depth,
                  decode_ring_buffers=args.decode_ring_buffers,
                  raw_batch_bytes=args.raw_batch_bytes,
                  produce_batch_bytes=args.produce_batch_bytes,
                  raw_produce=args.raw_produce)
    except ValueError as e:
        ap.error(str(e))

    sasl = tuple(args.sasl.split(":", 1)) if args.sasl else None
    # the store.* config section (file < IOTML_STORE_* env) supplies the
    # durable dir and fsync/segment/retention policy; the CLI flags win
    from ..config import load_config
    from ..store import StorePolicy

    cfg, _ = load_config([])
    store_dir = args.store_dir or (
        (cfg.store.dir or "/tmp/iotml-store") if args.durable else
        (cfg.store.dir or None))
    tier = None
    if store_dir:
        from ..store import TierPolicy

        tier = TierPolicy.from_config(cfg.tier)
        if args.tier_uri:
            tier.uri = args.tier_uri
        if not tier:
            tier = None
    elif args.tier_uri:
        ap.error("--tier-uri requires durable mode (--durable/--store-dir)")
    try:
        plat = Platform(sasl=sasl, host=args.host,
                        kafka_port=args.kafka_port,
                        mqtt_port=args.mqtt_port,
                        # 0 (the default) = UNSET, so durable topics
                        # inherit the store.* retention policy; negatives
                        # still reach the broker's validation below
                        retention_messages=args.retention
                        if args.retention else None,
                        cc_port=args.cc_port,
                        registry_port=args.registry_port,
                        ksql_port=args.ksql_port,
                        connect_port=args.connect_port,
                        store_dir=store_dir,
                        store_policy=(StorePolicy.from_config(cfg.store)
                                      if store_dir else None),
                        tier=tier,
                        trusted_passthrough=args.trust_passthrough,
                        registry_dir=args.registry
                        or (cfg.mlops.registry_dir or None),
                        registry_watch_poll_s=cfg.mlops.watch_poll_s)
    except ValueError as e:  # e.g. negative retention: clean usage error
        ap.error(str(e))
    plat.start(metrics_port=args.metrics_port)
    if args.fleet:
        plat.start_fleet(args.fleet, rate_hz=args.rate)
    import os as _os

    supervise = args.supervise or _os.environ.get(
        "IOTML_SUPERVISE", "").strip().lower() in ("1", "true", "yes", "on")
    sup = plat.supervised().start() if supervise else None
    if not args.quiet:
        print("iotml platform up:")
        for k, v in plat.endpoints().items():
            print(f"  {k:16s} {v}")
        if args.fleet:
            print(f"  fleet            {args.fleet} cars @ {args.rate} Hz → "
                  f"mqtt topic vehicles/sensor/data/<car>")
        if sup is not None:
            print(f"  supervisor       {len(sup.units())} units "
                  f"(self-healing; states on /healthz)")
        print("Ctrl-C to stop.")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        if sup is not None:
            sup.stop()
        plat.stop()
        if not args.quiet:
            print("stopped.")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
