"""iotml.cluster — partitioned multi-broker data plane.

The single-leader broker saturated at ~13.3k rec/s end to end
(BENCH_r05) while one TPU chip trains at 60k rec/s: the data plane, not
the compute, became the ceiling.  This package shards topic partitions
across N live brokers — the reference's 10-partitions / 3-brokers shape
(PAPER.md L3) — and makes every client partition-aware:

- ``PartitionMap``: (topic, partition) → (broker, epoch); per-shard
  ``supervise.Topology`` cells, so failover moves ONE shard's entry.
- ``ShardBroker``: a ``Broker`` materializing only the partitions its
  shard owns (store dirs included); unowned touches answer
  NOT_LEADER_FOR_PARTITION.
- ``ClusterController``: boots the brokers, provisions topics
  cluster-wide, runs per-shard followers, promotes on death
  (``supervised()`` wires this into iotml.supervise).
- ``ClusterClient``: the Broker duck-type, routed — produce/fetch to
  the owning broker with cached metadata refreshed on NOT_LEADER;
  group/offset APIs pinned to the coordinator broker.
- ``ScorerFleet`` / ``PumpFleet``: partition-parallel scorer members
  and KSQL pumps as consumer groups over the wire group protocol.

Boundary rule (lint R10): outside this package, production code must
not address broker instances directly (``controller.shards`` /
``ShardBroker(...)``) — route through ``ClusterClient`` and the
``PartitionMap`` so the ownership and fencing invariants hold.
"""

from .client import ClusterClient
from .controller import ClusterController, ShardView
from .fleet import PumpFleet, ScorerFleet
from .partition_map import PartitionMap
from .shard import ShardBroker

__all__ = ["ClusterClient", "ClusterController", "PartitionMap",
           "PumpFleet", "ScorerFleet", "ShardBroker", "ShardView",
           "main"]


def main(argv=None) -> int:
    """CLI entry (`python -m iotml.cluster`); see cluster.__main__."""
    from .__main__ import main as _main

    return _main(argv)
