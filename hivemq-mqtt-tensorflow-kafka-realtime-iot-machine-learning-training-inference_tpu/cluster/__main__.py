"""CLI: bring up a partitioned broker cluster / run the rebalance drill.

    python -m iotml.cluster up --brokers 3 --partitions 10
    python -m iotml.cluster drill [--seed 7] [--records 2000]

``up`` boots N wire-served shard brokers (the reference's 3-broker /
10-partition shape), pre-creates the reference topics, prints one
bootstrap line any client in the framework can consume
(``ClusterClient(bootstrap=...)``), and serves until Ctrl-C.

``drill`` runs the rebalance-under-chaos scenario (kill a group member
AND a shard leader mid-epoch; assert zero lost / zero double-scored
records) and exits nonzero on any invariant failure — the CI gate.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def cmd_up(args) -> int:
    from . import ClusterController

    ctl = ClusterController(
        brokers=args.brokers, host=args.host,
        store_root=args.store_root,
        replicated=args.replicated,
        replication_factor=args.replication_factor,
        min_isr=args.min_isr,
        base_port=args.base_port,
        advertise_host=args.advertise_host,
        mirror_groups=tuple(args.mirror_groups.split(","))
        if args.mirror_groups else ())
    ctl.start()
    for topic in args.topics.split(","):
        if topic:
            ctl.create_topic(topic, partitions=args.partitions)
    if args.metrics_port:
        from ..obs.metrics import start_http_server

        start_http_server(args.metrics_port)
    sup = None
    if args.replicated or args.replication_factor:
        sup = ctl.supervised().start()
    if not args.quiet:
        print("iotml cluster up:")
        for k, v in ctl.endpoints().items():
            print(f"  {k:14s} {v}")
        print(f"  topics         {args.topics} "
              f"({args.partitions} partitions, "
              f"{args.brokers}-way sharded)")
        print(f"  bootstrap      {ctl.bootstrap()}")
        if sup is not None:
            print(f"  supervisor     per-shard failover armed "
                  f"({ctl.n} probed shards)")
        print("Ctrl-C to stop.")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        if sup is not None:
            sup.stop()
        ctl.stop()
        if not args.quiet:
            print("stopped.")
    return 0


def cmd_admin(args) -> int:
    """add-broker / drain-broker / status against a LIVE cluster: the
    CLUSTER_ADMIN wire extension reaches the controller inside the `up`
    process, which runs the online reassignment (new replica bootstraps
    over zero-copy RAW_FETCH, joins the ISR, leadership moves through
    the Topology cell, the old replica retires) and reports back."""
    from ..stream.kafka_wire import KafkaWireBroker

    client = KafkaWireBroker(args.bootstrap,
                             client_id="iotml-cluster-admin")
    try:
        payload = {}
        if args.cmd in ("add-broker", "drain-broker"):
            payload["shard"] = args.shard
        if getattr(args, "store_dir", None):
            payload["store_dir"] = args.store_dir
        doc = client.cluster_admin(args.cmd, payload)
    finally:
        client.close()
    print(json.dumps(doc, indent=2, default=str))
    return 0 if doc.get("state") in (None, "moved", "retired") else 1


def cmd_drill(args) -> int:
    # lint-ok: R7 CLI entry point delegating to the chaos harness — this
    # is drill orchestration (the runner's own caller), not a hot path
    from ..chaos.runner import ChaosRunner

    report = ChaosRunner("rebalance-under-chaos", seed=args.seed,
                         records=args.records).run()
    print(json.dumps(report.to_dict(), indent=2, default=str))
    for inv in report.invariants:
        print(inv.verdict(), file=sys.stderr)
    return 0 if report.ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m iotml.cluster",
        description="partitioned multi-broker data plane")
    sub = ap.add_subparsers(dest="cmd", required=True)

    up = sub.add_parser("up", help="boot an N-broker cluster and serve")
    up.add_argument("--brokers", type=int, default=3)
    up.add_argument("--partitions", type=int, default=10)
    up.add_argument("--topics", default="sensor-data,model-predictions")
    up.add_argument("--host", default="127.0.0.1")
    up.add_argument("--base-port", type=int, default=None,
                    help="fixed ports: shard i listens on base+i, its "
                         "follower on base+N+i (default: ephemeral)")
    up.add_argument("--advertise-host", default=None,
                    help="hostname clients dial when it differs from "
                         "the bind --host (k8s Service name / LB)")
    up.add_argument("--store-root", default=None,
                    help="durable mode: each shard mounts "
                         "<root>/broker-<i> (cold restart resumes)")
    up.add_argument("--replicated", action="store_true",
                    help="one follower per shard + supervised "
                         "per-shard failover")
    up.add_argument("--replication-factor", type=int, default=None,
                    help="quorum mode (iotml.replication): RF-1 "
                         "ISR-tracked followers per shard, acks=all at "
                         "the quorum HWM, ISR-restricted failover, and "
                         "the add-broker/drain-broker admin verbs")
    up.add_argument("--min-isr", type=int, default=2,
                    help="min.insync.replicas for acks=all (quorum "
                         "mode)")
    up.add_argument("--mirror-groups", default="iotml",
                    help="comma list of groups whose offsets followers "
                         "mirror")
    up.add_argument("--prefetch-depth", type=int, default=None,
                    help="host→device prefetch depth for fleet "
                         "pipelines (sets IOTML_PREFETCH_DEPTH)")
    up.add_argument("--decode-ring-buffers", type=int, default=None,
                    help="columnar decode buffers per pipeline (sets "
                         "IOTML_DECODE_RING_BUFFERS)")
    up.add_argument("--raw-batch-bytes", type=int, default=None,
                    help="max bytes per raw frame fetch (sets "
                         "IOTML_RAW_BATCH_BYTES)")
    up.add_argument("--raw-produce", default=None,
                    choices=("auto", "on", "off"),
                    help="zero-copy produce plane for pump fleets and "
                         "shard appends (sets IOTML_RAW_PRODUCE)")
    up.add_argument("--produce-batch-bytes", type=int, default=None,
                    help="max frame bytes per RAW_PRODUCE request "
                         "(sets IOTML_PRODUCE_BATCH_BYTES)")
    up.add_argument("--mesh-data", type=int, default=None,
                    help="multi-chip streaming training for trainers "
                         "launched from this process env (sets "
                         "IOTML_MESH_DATA: data-axis devices; 0 = "
                         "single-chip)")
    up.add_argument("--device-normalize", default=None,
                    choices=("0", "1"),
                    help="fold normalization into the sharded train "
                         "step — host pipelines ship raw columns (sets "
                         "IOTML_DEVICE_NORMALIZE; needs --mesh-data "
                         ">= 2)")
    up.add_argument("--metrics-port", type=int, default=0,
                    help="serve /metrics + /healthz (0 = off); with "
                         "IOTML_OBS_ENDPOINTS set the endpoint auto-"
                         "joins the federation manifest")
    up.add_argument("--quiet", action="store_true")
    up.set_defaults(fn=cmd_up)

    drill = sub.add_parser(
        "drill", help="rebalance-under-chaos (exit = invariant verdict)")
    drill.add_argument("--seed", type=int, default=7)
    drill.add_argument("--records", type=int, default=2000)
    drill.set_defaults(fn=cmd_drill)

    for verb, help_ in (("add-broker",
                         "online reassignment: a NEW broker node takes "
                         "over --shard (bootstrap over RAW_FETCH, ISR "
                         "join, leadership move, old replica retires)"),
                        ("drain-broker",
                         "move --shard's leadership onto an existing "
                         "ISR follower and retire the drained leader"),
                        ("status",
                         "cluster + reassignment status (quorum mode)")):
        p = sub.add_parser(verb, help=help_)
        p.add_argument("--bootstrap", required=True,
                       help="any live broker address (host:port[,...])")
        if verb != "status":
            p.add_argument("--shard", type=int, required=True)
        if verb == "add-broker":
            p.add_argument("--store-dir", default=None,
                           help="the new node's store dir (durable "
                                "clusters; default: auto under the "
                                "cluster store root)")
        p.set_defaults(fn=cmd_admin, cmd=verb)

    args = ap.parse_args(argv)
    knob_names = ("prefetch_depth", "decode_ring_buffers",
                  "raw_batch_bytes", "raw_produce",
                  "produce_batch_bytes", "mesh_data")
    dev_norm = getattr(args, "device_normalize", None)
    if dev_norm is not None or \
            any(getattr(args, k, None) is not None for k in knob_names):
        from ..data.pipeline import set_knobs

        try:
            set_knobs(device_normalize=None if dev_norm is None
                      else dev_norm == "1",
                      **{k: getattr(args, k, None) for k in knob_names})
        except ValueError as e:
            ap.error(str(e))
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
