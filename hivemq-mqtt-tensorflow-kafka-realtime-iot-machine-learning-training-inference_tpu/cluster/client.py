"""ClusterClient — a partition-aware client over N shard brokers.

The ``Broker`` duck-type (produce / fetch / offsets / commit / group
APIs), routed: every produce and fetch goes to the broker that OWNS the
(topic, partition), resolved from cached metadata and refreshed when a
broker answers ``NOT_LEADER_FOR_PARTITION`` (Kafka error 6) — the exact
contract real Kafka clients implement.  Group and offset APIs are pinned
to the cluster's coordinator broker, re-discovered via FIND_COORDINATOR
after ``NOT_COORDINATOR`` or a coordinator death.

Two metadata sources, one routing path:

- ``partition_map=`` (in-process): the controller's live ``PartitionMap``.
  Per-shard connections are built with ``topology=map.cell(shard)``, so
  they re-resolve the shard's address AND stamp its fencing epoch into
  every request — a failed-over shard fences its stale leader through
  the PR 4 ``@e<N>`` machinery unchanged.
- ``bootstrap=`` (wire): per-partition leaders come from Metadata
  responses and are cached; a NOT_LEADER bounce or a dead connection
  triggers a refresh from any reachable broker.

Retry discipline (same at-least-once stance as ``KafkaWireBroker``):
NOT_LEADER means *nothing was appended there* — safe to re-route and
retry, any operation.  A plain ConnectionError on produce/commit is NOT
auto-retried (the dead broker may have applied it); the client refreshes
its view and re-raises, the caller owns redelivery.  Reads retry freely.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics
from ..stream.broker import Message, TopicSpec
from ..stream.kafka_wire import (CoordinatorMovedError, KafkaWireBroker,
                                 NotLeaderForPartitionError)

#: routing attempts per operation: first try + one re-route after each
#: of up to two refreshes (a refresh mid-failover may itself be stale)
_ATTEMPTS = 3


class ClusterClient:
    def __init__(self, bootstrap: Optional[str] = None,
                 partition_map=None, client_id: str = "iotml-cluster",
                 sasl_username: Optional[str] = None,
                 sasl_password: Optional[str] = None,
                 timeout_s: float = 30.0):
        if (bootstrap is None) == (partition_map is None):
            raise ValueError(
                "exactly one of bootstrap= (wire discovery) or "
                "partition_map= (in-process map) is required")
        self.client_id = client_id
        self._pmap = partition_map
        self._sasl = (sasl_username, sasl_password)
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._conns: Dict[int, KafkaWireBroker] = {}
        self._counts: Dict[str, int] = {}
        self._rr: Dict[str, int] = {}
        # wire-mode cache (pmap mode reads the live map instead)
        self._addr: Dict[int, str] = {}
        self._leaders: Dict[Tuple[str, int], int] = {}
        self._coord: Optional[Tuple[int, str]] = None  # (node, address)
        if self._pmap is None:
            from ..utils.net import parse_bootstrap

            self._seeds = [f"{h}:{p}"
                           for h, p in parse_bootstrap(bootstrap)]
            self._refresh_metadata()

    # -------------------------------------------------------- connections
    def _new_conn(self, addr: str, tag: str, topology=None
                  ) -> KafkaWireBroker:
        user, pw = self._sasl
        return KafkaWireBroker(addr, client_id=f"{self.client_id}-{tag}",
                               sasl_username=user, sasl_password=pw,
                               timeout_s=self._timeout_s,
                               topology=topology)

    def _conn(self, shard: int) -> KafkaWireBroker:
        with self._lock:
            c = self._conns.get(shard)
            if c is None:
                if self._pmap is not None:
                    cell = self._pmap.cell(shard)
                    c = self._new_conn(cell.leader, f"s{shard}",
                                       topology=cell)
                else:
                    c = self._new_conn(self._addr[shard], f"s{shard}")
                self._conns[shard] = c
            return c

    def _drop_conn(self, shard: int) -> None:
        with self._lock:
            c = self._conns.pop(shard, None)
        if c is not None:
            try:
                c.close()
            except OSError:
                pass

    def _shard_ids(self) -> List[int]:
        if self._pmap is not None:
            return list(range(self._pmap.n_shards))
        return sorted(self._addr)

    # ----------------------------------------------------------- metadata
    def _refresh_metadata(self) -> None:
        """Wire mode: re-learn (brokers, per-partition leaders) from any
        reachable broker; connections whose address moved are dropped.
        In pmap mode the map is live — refreshing means only forcing the
        affected connection to re-resolve through its topology."""
        if self._pmap is not None:
            return
        candidates = list(self._addr.values()) + [
            s for s in self._seeds if s not in self._addr.values()]
        last: Optional[Exception] = None
        for addr in candidates:
            probe = None
            try:
                probe = self._new_conn(addr, "meta")
                meta = probe.cluster_metadata()
            except (OSError, RuntimeError) as e:
                last = e
                continue
            finally:
                if probe is not None:
                    try:
                        probe.close()
                    except OSError:
                        pass
            new_addr = {node: f"{host}:{port}"
                        for node, host, port, _rack in meta["brokers"]}
            with self._lock:
                moved = [n for n, a in new_addr.items()
                         if self._addr.get(n) not in (None, a)]
                self._addr = new_addr
                self._leaders = dict(meta["leaders"])
                self._counts.update(meta["topics"])
            for n in moved:
                self._drop_conn(n)
            obs_metrics.cluster_metadata_refreshes.inc()
            return
        raise last or OSError("no reachable broker for metadata")

    def _shard_of(self, topic: str, partition: int) -> int:
        if self._pmap is not None:
            return self._pmap.shard_for(topic, partition)
        node = self._leaders.get((topic, partition))
        if node is None:
            self._refresh_metadata()
            node = self._leaders.get((topic, partition))
            if node is None:
                raise KeyError((topic, partition))
        return node

    def _handle_move(self, shard: int) -> None:
        """A bounce or dead connection: learn the new world."""
        if self._pmap is not None:
            # live map: the address/epoch already moved — force this
            # shard's connection to re-resolve through its topology
            self._drop_conn(shard)
        else:
            self._drop_conn(shard)
            try:
                self._refresh_metadata()
            except OSError:
                pass  # nothing reachable NOW; the retry loop decides

    # ------------------------------------------------------------ routing
    def _routed(self, topic: str, partition: int, op, *,
                retry_connection: bool):
        """Run op(conn) against the owning shard.  NOT_LEADER always
        re-routes (nothing was applied); ConnectionError re-routes only
        when `retry_connection` (reads) — writes re-raise after
        refreshing, preserving the caller-owns-redelivery contract."""
        last: Optional[Exception] = None
        for _ in range(_ATTEMPTS):
            shard = self._shard_of(topic, partition)
            try:
                return op(self._conn(shard))
            except NotLeaderForPartitionError as e:
                obs_metrics.cluster_not_leader_bounces.inc()
                self._handle_move(shard)
                last = e
            except ConnectionError as e:
                self._handle_move(shard)
                if not retry_connection:
                    raise
                last = e
        raise last  # type: ignore[misc]

    # ------------------------------------------------------------ produce
    def _count(self, topic: str) -> int:
        if self._pmap is not None:
            n = self._pmap.topics().get(topic)
            if n:
                return n
        n = self._counts.get(topic)
        if n:
            return n
        n = self._any_conn_call(
            lambda c: c.cluster_metadata([topic])["topics"].get(topic))
        if not n:
            raise KeyError(topic)
        self._counts[topic] = n
        return n

    def _partition_for(self, topic: str, key: Optional[bytes]) -> int:
        n = self._count(topic)
        if key is None:
            self._rr[topic] = (self._rr.get(topic, -1) + 1) % n
            return self._rr[topic]
        # same keyed partitioner as every other client in the family:
        # per-key ordering is a cross-client invariant
        return zlib.crc32(key) % n

    def produce(self, topic: str, value: bytes,
                key: Optional[bytes] = None,
                partition: Optional[int] = None, timestamp_ms: int = 0,
                headers: Optional[tuple] = None) -> int:
        return self.produce_many(topic, [(key, value, timestamp_ms)],
                                 partition=partition)

    def produce_batch(self, topic: str, values, key=None,
                      partition=None) -> int:
        return self.produce_many(topic, [(key, v, 0) for v in values],
                                 partition=partition)

    def produce_many(self, topic: str, entries, partition=None,
                     acks: Optional[int] = None,
                     timeout_ms: int = 10_000) -> int:
        """Route each record to its partition's owning shard.  ONE wire
        request per partition — never a multi-partition request, so a
        NOT_LEADER bounce is all-or-nothing for its entries and the
        re-route after a refresh cannot double-append the rest.
        ``acks``/``timeout_ms`` forward to the wire client (quorum
        semantics on replicated shards — see KafkaWireBroker)."""
        by_part: Dict[int, list] = {}
        for entry in entries:
            key = entry[0]
            p = self._partition_for(topic, key) if partition is None \
                else partition
            by_part.setdefault(p, []).append(entry)
        last = -1
        for p, ents in sorted(by_part.items()):
            off = self._routed(
                topic, p,
                lambda c, _p=p, _e=ents: c.produce_many(
                    topic, _e, partition=_p, acks=acks,
                    timeout_ms=timeout_ms),
                retry_connection=False)
            last = max(last, off)
        return last

    def produce_raw(self, topic: str, partition: int,
                    frames: bytes, acks: Optional[int] = None,
                    timeout_ms: int = 10_000) -> int:
        """Route a pre-framed RAW_PRODUCE batch to the partition's
        owning shard (one request, all-or-nothing — a NOT_LEADER bounce
        re-routes with nothing appended).  NotImplementedError from an
        extension-less shard propagates so producers pin back to
        classic produce; ConnectionError keeps caller-owns-redelivery."""
        def op(c):
            pr = getattr(c, "produce_raw", None)
            if pr is None:
                raise NotImplementedError(
                    "owning broker lacks raw-batch produce")
            return pr(topic, partition, frames, acks=acks,
                      timeout_ms=timeout_ms)

        return self._routed(topic, partition, op, retry_connection=False)

    # -------------------------------------------------------------- fetch
    def fetch(self, topic: str, partition: int, offset: int,
              max_messages: int = 1024) -> List[Message]:
        return self._routed(
            topic, partition,
            lambda c: c.fetch(topic, partition, offset, max_messages),
            retry_connection=True)

    def fetch_raw(self, topic: str, partition: int, offset: int,
                  max_bytes: int = 1 << 20):
        """Raw-batch fetch routed to the owning shard (see
        Broker.fetch_raw / KafkaWireBroker.fetch_raw).  Raises
        NotImplementedError when the owning connection has no raw-batch
        support, so consumers fall back to the legacy paths."""
        def op(c):
            fr = getattr(c, "fetch_raw", None)
            if fr is None:
                raise NotImplementedError(
                    "owning broker lacks raw-batch fetch")
            return fr(topic, partition, offset, max_bytes=max_bytes)

        return self._routed(topic, partition, op, retry_connection=True)

    def last_hwm(self, topic: str, partition: int):
        """The owning shard connection's cached high-water mark (fetch
        responses carry it), None when uncached — consumer-lag telemetry
        must never trigger a routing round trip, so this reads only the
        LIVE connection caches (see StreamConsumer.record_lag)."""
        with self._lock:
            conns = list(self._conns.values())
        best = None
        for c in conns:
            hwm = getattr(c, "last_hwm", lambda *a: None)(topic,
                                                          partition)
            # MAX over the caches: after a failover an old leader's
            # connection keeps a frozen pre-failover hwm, and returning
            # it first would report zero lag for a partition actually
            # falling behind.  The hwm only ever grows, so max is the
            # freshest answer any live connection has.
            if hwm is not None and (best is None or hwm > best):
                best = hwm
        return best

    def end_offset(self, topic: str, partition: int = 0) -> int:
        return self._routed(topic, partition,
                            lambda c: c.end_offset(topic, partition),
                            retry_connection=True)

    def begin_offset(self, topic: str, partition: int = 0) -> int:
        return self._routed(topic, partition,
                            lambda c: c.begin_offset(topic, partition),
                            retry_connection=True)

    def offset_for_timestamp(self, topic: str, partition: int,
                             timestamp_ms: int) -> int:
        return self._routed(
            topic, partition,
            lambda c: c.offset_for_timestamp(topic, partition,
                                             timestamp_ms),
            retry_connection=True)

    # ------------------------------------------------------------- topics
    def _any_conn_call(self, op):
        last: Optional[Exception] = None
        for shard in self._shard_ids():
            try:
                return op(self._conn(shard))
            except (OSError, RuntimeError) as e:
                last = e
                self._drop_conn(shard)
        raise last or OSError("no reachable broker")

    def topics(self) -> List[str]:
        return self._any_conn_call(lambda c: c.topics())

    def topic(self, name: str) -> TopicSpec:
        return TopicSpec(name, self._count(name))

    def create_topic(self, name: str, partitions: int = 1,
                     **retention) -> TopicSpec:
        """Provision cluster-wide: every broker learns the full spec and
        mounts only the partitions it owns."""
        for shard in self._shard_ids():
            self._conn(shard).create_topic(name, partitions=partitions,
                                           **retention)
        if self._pmap is not None:
            self._pmap.register_topic(name, partitions)
        self._counts[name] = partitions
        return TopicSpec(name, partitions)

    # ------------------------------------------------------- coordination
    def _coord_conn(self) -> KafkaWireBroker:
        if self._pmap is not None:
            return self._conn(self._pmap.coordinator()[0])
        with self._lock:
            coord = self._coord
        if coord is None:
            node, host, port = self._any_conn_call(
                lambda c: c.find_coordinator("iotml"))
            coord = (node, f"{host}:{port}")
            with self._lock:
                self._coord = coord
                self._addr.setdefault(node, coord[1])
        return self._conn(coord[0])

    def _coord_moved(self) -> None:
        obs_metrics.cluster_coordinator_moves.inc()
        if self._pmap is not None:
            self._drop_conn(self._pmap.coordinator()[0])
            return
        with self._lock:
            coord, self._coord = self._coord, None
        if coord is not None:
            self._drop_conn(coord[0])
        try:
            self._refresh_metadata()
        except OSError:
            pass

    def _coordinated(self, op, *, retry_connection: bool):
        """Run op against the coordinator; NOT_COORDINATOR always
        re-discovers and retries (nothing was applied).  ConnectionError
        retries only reads — a commit/join interrupted mid-flight
        surfaces to the caller, whose loops already own redelivery."""
        last: Optional[Exception] = None
        for _ in range(_ATTEMPTS):
            try:
                return op(self._coord_conn())
            except CoordinatorMovedError as e:
                self._coord_moved()
                last = e
            except ConnectionError as e:
                self._coord_moved()
                if not retry_connection:
                    raise
                last = e
        raise last  # type: ignore[misc]

    # ------------------------------------------------- consumer-group API
    def commit(self, group: str, topic: str, partition: int,
               next_offset: int) -> None:
        self._coordinated(
            lambda c: c.commit(group, topic, partition, next_offset),
            retry_connection=False)

    def commit_many(self, group: str, topic: str, entries) -> None:
        self._coordinated(
            lambda c: c.commit_many(group, topic, entries),
            retry_connection=False)

    def committed(self, group: str, topic: str,
                  partition: int) -> Optional[int]:
        return self._coordinated(
            lambda c: c.committed(group, topic, partition),
            retry_connection=True)

    def committed_many(self, group: str, pairs
                       ) -> Dict[Tuple[str, int], int]:
        return self._coordinated(
            lambda c: c.committed_many(group, pairs),
            retry_connection=True)

    def commit_fenced(self, group: str, generation: int, member_id: str,
                      positions) -> bool:
        return self._coordinated(
            lambda c: c.commit_fenced(group, generation, member_id,
                                      positions),
            retry_connection=False)

    def find_coordinator(self, group: str) -> Tuple[int, str, int]:
        return self._any_conn_call(lambda c: c.find_coordinator(group))

    # group membership (RemoteGroupCoordinator drives these)
    def join_group(self, group: str, topics, member_id: str = "",
                   session_timeout_ms: int = 10_000):
        # retried across coordinator moves: a lost join at worst leaks a
        # zombie member until session timeout (the join loop's contract)
        return self._coordinated(
            lambda c: c.join_group(group, topics, member_id,
                                   session_timeout_ms=session_timeout_ms),
            retry_connection=True)

    def sync_group(self, group: str, generation: int, member_id: str,
                   assignments: Optional[dict] = None):
        return self._coordinated(
            lambda c: c.sync_group(group, generation, member_id,
                                   assignments),
            retry_connection=True)

    def heartbeat_group(self, group: str, generation: int,
                        member_id: str) -> bool:
        return self._coordinated(
            lambda c: c.heartbeat_group(group, generation, member_id),
            retry_connection=True)

    def leave_group(self, group: str, member_id: str) -> None:
        self._coordinated(
            lambda c: c.leave_group(group, member_id),
            retry_connection=True)

    # ---------------------------------------------------------- lifecycle
    def close(self) -> None:
        with self._lock:
            conns, self._conns = dict(self._conns), {}
        for c in conns.values():
            try:
                c.close()
            except OSError:
                pass
