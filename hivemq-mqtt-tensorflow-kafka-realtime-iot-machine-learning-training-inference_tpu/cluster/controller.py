"""ClusterController — boot, watch, and fail over a partitioned cluster.

One controller owns N shard brokers (``ShardBroker`` + ``KafkaWireServer``
each), the shared ``PartitionMap``, and optionally one ``FollowerReplica``
per shard.  It is the ZooKeeper-controller role of the reference's
3-broker deployment (PAPER.md L3), scoped the way this rebuild scopes
infrastructure: in-process objects speaking the real wire protocol, so
the same code drives tests, chaos drills, the CLI and the bench.

Topology on disk (``store_root=``)::

    <store_root>/broker-0/          shard 0's store (its partitions only)
    <store_root>/broker-1/
    ...
    <store_root>/broker-0-replica/  shard 0's follower (replicated=True)

Failover is PER SHARD: a dead shard leader's follower is promoted at a
bumped epoch and only that shard's map entry moves — clients of every
other shard never notice.  Group coordination is pinned to one shard's
live leader; if THAT shard fails over, the promoted follower serves the
mirrored committed offsets and groups re-form against it (membership is
in-memory by design — exactly a Kafka coordinator change).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics
from ..stream.kafka_wire import KafkaWireServer
from ..stream.replica import FollowerReplica
from .partition_map import PartitionMap
from .shard import ShardBroker


def _split(addr: str) -> Tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


class ShardView:
    """One node's view of the cluster — what its wire server consults to
    answer Metadata (per-partition leaders), FIND_COORDINATOR (the
    pinned node) and to advertise the broker list."""

    def __init__(self, pmap: PartitionMap, node_id: int):
        self.pmap = pmap
        self.node_id = node_id

    def brokers(self) -> List[Tuple[int, str, int]]:
        return [(i, *_split(addr))
                for i, addr in enumerate(self.pmap.addresses())]

    def leader_node(self, topic: str, partition: int) -> int:
        return self.pmap.shard_for(topic, partition)

    def coordinator(self) -> Tuple[int, str, int]:
        shard, addr = self.pmap.coordinator()
        return (shard, *_split(addr))


class ClusterController:
    """Boot N shard brokers behind one PartitionMap.

    Args:
      brokers: shard count (the reference ran 3).
      store_root: durable mode — each shard mounts
        ``<store_root>/broker-<i>`` (cold restart resumes every shard
        from its own dirs).
      replicated: one FollowerReplica per shard, enabling
        ``fail_shard`` / supervised per-shard failover.
      replica_sync: "thread" starts each follower's background sync
        loop; "manual" leaves stepping to the caller
        (``sync_replicas_once`` — deterministic runners).
      mirror_groups: consumer groups whose committed offsets the
        followers mirror (survive a shard/coordinator failover).
      coordinator_shard: which shard's live leader holds group state.
      base_port: fixed listen ports — shard *i* binds ``base_port + i``
        and its follower ``base_port + n + i`` (deployments expose a
        known port range); default lets the OS pick ephemeral ports.
      advertise_host: the hostname clients should dial (a k8s Service
        name, a LB address) when it differs from the bind ``host`` —
        Metadata, the PartitionMap, and failover publishes all carry
        it.  A wildcard bind (0.0.0.0/::) is never advertised: local
        clients get 127.0.0.1 when no advertise_host is given.
    """

    def __init__(self, brokers: int = 3, host: str = "127.0.0.1",
                 store_root: Optional[str] = None, store_policy=None,
                 replicated: bool = False, replica_sync: str = "thread",
                 mirror_groups: Tuple[str, ...] = (),
                 coordinator_shard: int = 0,
                 base_port: Optional[int] = None,
                 advertise_host: Optional[str] = None,
                 replication_factor: Optional[int] = None,
                 min_isr: int = 2, max_lag_s: float = 0.5):
        if brokers < 1:
            raise ValueError("brokers must be >= 1")
        if replica_sync not in ("thread", "manual"):
            raise ValueError("replica_sync is 'thread' or 'manual'")
        if replication_factor is not None:
            if replication_factor < 2:
                raise ValueError("replication_factor must be >= 2 "
                                 "(1 is the unreplicated default)")
            # quorum mode implies per-shard followers; the legacy
            # single-follower flag becomes redundant
            replicated = False
        self.n = int(brokers)
        self.host = host
        self._store_root = store_root
        self._replica_sync = replica_sync
        self._mirror_groups = tuple(mirror_groups)
        # the address brokers REACH EACH OTHER at (follower sync) vs the
        # one clients are TOLD to dial (Metadata / PartitionMap)
        connect_host = "127.0.0.1" if host in ("0.0.0.0", "::") else host
        self._adv_host = advertise_host or connect_host
        self.brokers: List[ShardBroker] = []
        self.servers: List[KafkaWireServer] = []
        self._killed = [False] * self.n
        for i in range(self.n):
            owns = self._owns_fn(i)
            store_dir = os.path.join(store_root, f"broker-{i}") \
                if store_root else None
            b = ShardBroker(owns, shard_id=i, store_dir=store_dir,
                            store_policy=store_policy)
            self.brokers.append(b)
            self.servers.append(KafkaWireServer(
                b, host=host,
                port=(base_port + i) if base_port else 0))
        addresses = [f"{self._adv_host}:{s.port}" for s in self.servers]
        local_addresses = [f"{connect_host}:{s.port}"
                           for s in self.servers]
        self.pmap = PartitionMap(addresses,
                                 coordinator_shard=coordinator_shard)
        for i, srv in enumerate(self.servers):
            srv.cluster = ShardView(self.pmap, i)
        # durable cold restart: the manifests already re-created each
        # shard's topics during mount — surface them in the map so
        # clients and assignors see the full width immediately
        for b in self.brokers:
            for t in b.topics():
                self.pmap.register_topic(t, b.topic(t).partitions)
        #: per shard: the broker currently SERVING it (the leader until
        #: a failover, then the promoted follower's local broker)
        self.serving: List[ShardBroker] = list(self.brokers)
        self.replicas: List[Optional[FollowerReplica]] = [None] * self.n
        #: quorum mode (ISSUE 14): one ReplicaSet per shard — RF-1
        #: ISR-tracked followers, acks=all at the quorum HWM, consumer
        #: reads bounded by it, ISR-restricted failover, and the
        #: elastic add-broker/drain-broker verbs.
        self.replica_sets: List = [None] * self.n
        self.replication_factor = replication_factor
        self._store_policy = store_policy
        self.reassignments: List = []  # completed/failed move reports
        if replication_factor is not None:
            from ..replication import ReplicaSet
            from ..store.hwm import hwm_file_for

            for i in range(self.n):
                owns = self._owns_fn(i)
                groups = self._mirror_groups \
                    if i == coordinator_shard else ()
                leader_dir = os.path.join(store_root, f"broker-{i}") \
                    if store_root else None

                def factory(i=i, counter=[0]):
                    owns_i = self._owns_fn(i)
                    k = counter[0]
                    counter[0] += 1
                    rep_dir = os.path.join(
                        store_root, f"broker-{i}-replica-{k}") \
                        if store_root else None
                    return ShardBroker(owns_i, shard_id=i,
                                       store_dir=rep_dir,
                                       store_policy=store_policy)

                def port_for(j, i=i):
                    return (base_port + self.n * (1 + j) + i) \
                        if base_port else 0

                rset = ReplicaSet(
                    leader_broker=self.brokers[i],
                    leader_server=self.servers[i],
                    n_followers=replication_factor - 1,
                    min_isr=min_isr, max_lag_s=max_lag_s, host=host,
                    groups=groups, partition_filter=owns,
                    topology=self.pmap.cell(i),
                    follower_local_factory=factory,
                    follower_port_fn=port_for,
                    hwm_file=hwm_file_for(leader_dir),
                    leader_addr=local_addresses[i])
                for rep in rset.followers.values():
                    # a promoted follower must keep answering cluster-
                    # shaped metadata, exactly like the legacy path
                    rep.server.cluster = ShardView(self.pmap, i)
                self.replica_sets[i] = rset
        for srv in self.servers:
            srv.admin = self  # CLUSTER_ADMIN verbs route here
        if replicated:
            for i in range(self.n):
                owns = self._owns_fn(i)
                rep_dir = os.path.join(store_root, f"broker-{i}-replica") \
                    if store_root else None
                local = ShardBroker(owns, shard_id=i, store_dir=rep_dir,
                                    store_policy=store_policy)
                # only the COORDINATOR shard's follower mirrors group
                # offsets: the cluster pins all offset state to the
                # coordinator broker (other brokers answer
                # NOT_COORDINATOR), and a promoted coordinator-follower
                # inherits the whole table with the role
                groups = self._mirror_groups \
                    if i == coordinator_shard else ()
                rep = FollowerReplica(
                    local_addresses[i], groups=groups, host=host,
                    port=(base_port + self.n + i) if base_port else 0,
                    partition_filter=owns, local=local)
                # a promoted follower must keep answering cluster-shaped
                # metadata (per-partition leaders, pinned coordinator)
                rep.server.cluster = ShardView(self.pmap, i)
                self.replicas[i] = rep
        self._compactors: list = []
        self.started = False

    def _owns_fn(self, shard: int):
        n = self.n
        return lambda t, p, _i=shard: p % n == _i

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ClusterController":
        for srv in self.servers:
            srv.start()
        for rep in self.replicas:
            if rep is None:
                continue
            if self._replica_sync == "thread":
                rep.start()          # sync loop + serving follower
            else:
                rep.server.start()   # serve only; caller steps sync
        for rset in self.replica_sets:
            if rset is not None:
                rset.start(sync=self._replica_sync)
        # durable shards reclaim their compacted topics in the
        # background, each shard compacting only the partitions it leads
        # (run_compaction skips unowned placeholders)
        if self._store_root:
            from ..store import StoreCompactor
            for b in self.brokers:
                if b.store is not None:
                    self._compactors.append(StoreCompactor(
                        b, interval_s=b.store.policy.compact_interval_s,
                    ).start())
        # per-shard scrape labels: every shard's current epoch is a
        # labeled series from boot, so the federated scrape (and the
        # TSDB behind it) can tell shards apart before any failover
        for i in range(self.n):
            obs_metrics.cluster_shard_epoch.set(
                self.pmap.epoch(i), shard=str(i))
        self.started = True
        return self

    def stop(self) -> None:
        for c in self._compactors:
            c.stop()
        self._compactors = []
        for rep in self.replicas:
            if rep is not None:
                try:
                    rep.stop()
                except (OSError, RuntimeError):
                    pass
        for rset in self.replica_sets:
            if rset is not None:
                try:
                    rset.stop()
                except (OSError, RuntimeError):
                    pass
        for i, srv in enumerate(self.servers):
            if not self._killed[i]:
                try:
                    srv.kill()
                except (OSError, RuntimeError):
                    pass
                self._killed[i] = True
        for b in self.brokers:
            try:
                b.close()
            except (OSError, RuntimeError):
                pass
        self.started = False

    def __enter__(self) -> "ClusterController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- topics
    def create_topic(self, name: str, partitions: int = 1,
                     **retention) -> None:
        """Provision a topic CLUSTER-WIDE: every shard broker learns the
        full spec (and mounts only its own partitions); the map records
        the width for clients and assignors."""
        for b in self.brokers:
            b.create_topic(name, partitions=partitions, **retention)
        for b in self.serving:
            # after a failover/reassignment the serving broker is a
            # promoted ex-follower that is in neither list above — a
            # topic it never learns answers UNKNOWN_TOPIC forever on
            # its shard (cluster servers do not auto-create)
            if b not in self.brokers:
                b.create_topic(name, partitions=partitions, **retention)
        for rep in self.replicas:
            if rep is not None:
                rep.local.create_topic(name, partitions=partitions,
                                       **retention)
        for rset in self.replica_sets:
            if rset is not None:
                for rep in rset.followers.values():
                    rep.local.create_topic(name, partitions=partitions,
                                           **retention)
        self.pmap.register_topic(name, partitions)

    # ------------------------------------------------------------ clients
    def bootstrap(self) -> str:
        return ",".join(self.pmap.addresses())

    def client(self, **kw):
        """A routing client sharing this controller's live map."""
        from .client import ClusterClient

        return ClusterClient(partition_map=self.pmap, **kw)

    def endpoints(self) -> Dict[str, str]:
        out = {f"broker-{i}": addr
               for i, addr in enumerate(self.pmap.addresses())}
        shard, addr = self.pmap.coordinator()
        out["coordinator"] = f"{addr} (shard {shard})"
        if self._store_root:
            out["store"] = self._store_root
        return out

    # ---------------------------------------------------------- failover
    def sync_replicas_once(self) -> int:
        """Step every live follower one replication round (deterministic
        runners; replica_sync='manual')."""
        copied = 0
        for i, rep in enumerate(self.replicas):
            if rep is not None and not rep.promoted:
                copied += rep.sync_once()
        for rset in self.replica_sets:
            if rset is not None:
                copied += rset.sync_once()
        return copied

    def kill_shard(self, shard: int) -> None:
        """Abruptly kill a shard's LEADER server (drills): established
        connections are severed exactly like a crashed process."""
        if not self._killed[shard]:
            self.servers[shard].kill()
            self._killed[shard] = True

    def fail_shard(self, shard: int) -> str:
        """Promote the shard's follower into its serving leader at a
        bumped epoch and publish ONLY this shard's map entry.  Returns
        the new serving address.  In quorum mode the election is
        ISR-RESTRICTED: only a follower in sync for every partition may
        serve — acked records cannot be lost by construction."""
        rset = self.replica_sets[shard]
        was_coordinator = self.pmap.coordinator()[0] == shard
        if rset is not None:
            self.kill_shard(shard)
            epoch = self.pmap.epoch(shard) + 1
            rid, _bind = rset.promote(epoch)  # ISR-restricted
            addr = f"{self._adv_host}:{rset.server.port}"
            self.pmap.publish(shard, addr, epoch)
            self.serving[shard] = rset.leader
            self.servers[shard] = rset.server
            # the promoted server inherits the full serving surface:
            # admin verbs must survive every failover, not just boot
            rset.server.admin = self
            obs_metrics.cluster_shard_failovers.inc(shard=str(shard))
            obs_metrics.cluster_shard_epoch.set(epoch, shard=str(shard))
            if was_coordinator:
                obs_metrics.cluster_coordinator_moves.inc()
            return addr
        rep = self.replicas[shard]
        if rep is None:
            raise RuntimeError(
                f"shard {shard} has no follower (replicated=False): "
                f"nothing to promote")
        self.kill_shard(shard)
        epoch = self.pmap.epoch(shard) + 1
        rep.promote(epoch)
        # publish the ADVERTISED address (promote() reports the bind
        # address, which may be a wildcard under a deployment)
        addr = f"{self._adv_host}:{rep.port}"
        self.pmap.publish(shard, addr, epoch)
        self.serving[shard] = rep.local
        obs_metrics.cluster_shard_failovers.inc(shard=str(shard))
        obs_metrics.cluster_shard_epoch.set(epoch, shard=str(shard))
        if was_coordinator:
            # the pinned shard moved WITH its follower: clients re-find
            # the coordinator at the promoted address; membership state
            # restarts empty (groups re-form), committed offsets were
            # mirrored by the follower
            obs_metrics.cluster_coordinator_moves.inc()
        return addr

    # --------------------------------------------------------- elasticity
    def _require_rset(self, shard: int):
        if not 0 <= shard < self.n:
            raise ValueError(f"no shard {shard} (0..{self.n - 1})")
        rset = self.replica_sets[shard]
        if rset is None:
            raise RuntimeError(
                "elastic reassignment needs quorum mode: boot the "
                "cluster with replication_factor >= 2")
        return rset

    def add_broker(self, shard: int, store_dir: Optional[str] = None,
                   port: int = 0, catch_up_timeout_s: float = 60.0,
                   retire_old: bool = True) -> dict:
        """Online reassignment: move `shard`'s leadership onto a NEW
        broker node with zero downtime.

        The new node starts as one more follower of the shard: it
        bootstraps the whole segment log over zero-copy RAW_FETCH
        mirroring (batches append verbatim), catches up, earns ISR
        admission, and only THEN is promoted at epoch+1 — the shard's
        Topology cell republishes, clients re-resolve on their next
        reconnect/fence, consumers keep their cursors (offsets are
        identical by the mirror contract), the remaining followers
        re-point through the same cell, and the old leader retires
        (``retire_old``).  Returns the reassignment report
        (state/catch_up_s/move_s)."""
        from ..replication.reassign import (CATCHING_UP, IN_SYNC, MOVED,
                                            RETIRED, ShardReassignment)

        rset = self._require_rset(shard)
        move = ShardReassignment(shard=shard,
                                 old_leader=self.pmap.leader(shard))
        self.reassignments.append(move)
        if store_dir is None and self._store_root:
            store_dir = os.path.join(
                self._store_root,
                f"broker-{shard}-gen{self.pmap.epoch(shard) + 1}")
        # ALWAYS a ShardBroker (store-backed or in-memory): a plain
        # Broker local would materialise unowned partitions and serve
        # them EMPTY after promotion instead of bouncing NOT_LEADER —
        # a stale client would read silence where it must read the
        # re-route signal
        local = ShardBroker(self._owns_fn(shard), shard_id=shard,
                            store_dir=store_dir,
                            store_policy=self._store_policy)
        try:
            rid = rset.add_follower(local=local,
                                    sync=self._replica_sync)
            move.target_rid = rid
            new_rep = rset.followers[rid]
            new_rep.server.cluster = ShardView(self.pmap, shard)
            move.advance(CATCHING_UP)  # the mirror is live; an
            # operator polling `status` watches lag shrink from here
            # catch-up: ISR admission is the bar (lag within the
            # staleness window for EVERY partition), not merely lag==0
            # at one instant
            deadline = time.monotonic() + catch_up_timeout_s
            while time.monotonic() < deadline:
                if self._replica_sync == "manual":
                    rset.sync_once()
                if rid in rset.state.isr_follower_ids():
                    break
                time.sleep(0.0 if self._replica_sync == "manual"
                           else 0.02)
            else:
                raise RuntimeError(
                    f"new replica {rid} did not reach the ISR within "
                    f"{catch_up_timeout_s}s")
            move.records_mirrored = sum(
                new_rep.local.end_offset(t, p)
                for t in new_rep.local.topics()
                for p in range(new_rep.local.topic(t).partitions)
                if self._owns_fn(shard)(t, p))
            move.raw_mirrored = new_rep.raw_mirrored
            move.advance(IN_SYNC)
            self._move_leadership(shard, rid, move,
                                  retire_old=retire_old)
            move.advance(RETIRED if retire_old else MOVED)
        except Exception as e:
            move.fail(f"{type(e).__name__}: {e}")
            raise
        return move.to_dict()

    def drain_broker(self, shard: int,
                     retire_old: bool = True) -> dict:
        """Drain `shard`'s current leader: leadership moves to an
        EXISTING ISR follower (no bootstrap needed — it already holds
        the log), the cell republishes at epoch+1, and the drained
        leader retires.  The capacity-removal half of elasticity."""
        from ..replication.reassign import (IN_SYNC, MOVED, RETIRED,
                                            ShardReassignment)

        rset = self._require_rset(shard)
        move = ShardReassignment(shard=shard,
                                 old_leader=self.pmap.leader(shard))
        self.reassignments.append(move)
        try:
            rid = rset.elect()  # ISR-restricted by construction
            move.target_rid = rid
            move.advance(IN_SYNC)  # already in sync: nothing to copy
            self._move_leadership(shard, rid, move,
                                  retire_old=retire_old)
            move.advance(RETIRED if retire_old else MOVED)
        except Exception as e:
            move.fail(f"{type(e).__name__}: {e}")
            raise
        return move.to_dict()

    def _move_leadership(self, shard: int, rid: int, move,
                         retire_old: bool = True) -> None:
        """The MOVED step both verbs share: promote `rid` at epoch+1,
        publish the cell, update serving state, retire the old leader
        (its server would answer FENCED anyway — its epoch is stale)."""
        from ..replication.reassign import MOVED

        rset = self.replica_sets[shard]
        old_server = self.servers[shard]
        was_coordinator = self.pmap.coordinator()[0] == shard
        epoch = self.pmap.epoch(shard) + 1
        # step down FIRST: from here the old server answers every write
        # with NOT_LEADER, so nothing can land in the retired log
        # during the drain grace — even from unstamped legacy producers
        old_server.retiring = True
        old_broker = self.brokers[shard]
        rset.promote(epoch, rid=rid)
        addr = f"{self._adv_host}:{rset.server.port}"
        self.pmap.publish(shard, addr, epoch)
        self.serving[shard] = rset.leader
        self.servers[shard] = rset.server
        # the promoted broker REPLACES the retired one everywhere the
        # controller fans out (create_topic, stop) — the old one is
        # closed below, and a closed durable broker must not keep
        # receiving manifest writes (or hold its store flock forever)
        self.brokers[shard] = rset.leader
        # admin verbs must survive the move (a cluster whose every
        # shard has moved once must still be reachable for the NEXT
        # add-broker/drain-broker/status)
        rset.server.admin = self
        move.new_leader = addr
        move.epoch = epoch
        move.advance(MOVED)
        obs_metrics.cluster_shard_failovers.inc(shard=str(shard))
        obs_metrics.cluster_shard_epoch.set(epoch, shard=str(shard))
        if was_coordinator:
            obs_metrics.cluster_coordinator_moves.inc()
        if retire_old:
            # graceful retirement: the map already points elsewhere and
            # the old epoch is fenced for writes; severing reads forces
            # the one reconnect consumers already treat as failover.
            # The kill is DEFERRED a beat: the admin verb driving this
            # move may have arrived on the very server being retired
            # (drain-broker against its own shard's leader), and an
            # immediate kill would sever the admin connection before
            # the response flushes.
            import threading

            from ..supervise.registry import register_thread

            # the retired broker's compactor (durable clusters) must
            # stop BEFORE its store closes, or it errors every interval
            # against closed segment logs forever
            old_compactors = [c for c in self._compactors
                              if c.broker is old_broker]
            self._compactors = [c for c in self._compactors
                                if c.broker is not old_broker]

            def _retire(srv=old_server, b=old_broker,
                        compactors=old_compactors):
                time.sleep(0.25)
                try:
                    srv.kill()
                except OSError:
                    pass
                for c in compactors:
                    try:
                        c.stop()
                    except (OSError, RuntimeError):
                        pass
                try:
                    # release the store: open segment fds, the dir
                    # flock, the offsets file — weekly reassignments on
                    # a long-lived process must not leak one mount each
                    b.close()
                except (OSError, RuntimeError):
                    pass

            register_thread(threading.Thread(
                target=_retire, daemon=True,
                name=f"iotml-retire-shard-{shard}")).start()
            self._killed[shard] = True

    def admin_command(self, command: str, args: dict) -> dict:
        """CLUSTER_ADMIN dispatch (the wire server's `admin` hook) —
        what `python -m iotml.cluster add-broker/drain-broker/status`
        drive from another process."""
        if command == "status":
            doc = {"brokers": self.n,
                   "addresses": self.pmap.addresses(),
                   "epochs": [self.pmap.epoch(i)
                              for i in range(self.n)],
                   "replication_factor": self.replication_factor,
                   "reassignments": [m.to_dict()
                                     for m in self.reassignments]}
            if self.replication_factor is not None:
                doc["shards"] = {
                    str(i): self.replica_sets[i].describe()
                    for i in range(self.n)
                    if self.replica_sets[i] is not None}
            return doc
        if command == "add-broker":
            return self.add_broker(
                shard=int(args.get("shard", 0)),
                store_dir=args.get("store_dir"),
                catch_up_timeout_s=float(
                    args.get("catch_up_timeout_s", 60.0)))
        if command == "drain-broker":
            return self.drain_broker(shard=int(args.get("shard", 0)))
        raise ValueError(f"unknown admin command {command!r} "
                         f"(have: status, add-broker, drain-broker)")

    # -------------------------------------------------------- supervision
    def _shard_alive(self, shard: int) -> bool:
        host, port = _split(self.pmap.leader(shard))
        try:
            with socket.create_connection((host, port), timeout=1.0):
                return True
        except OSError:
            return False

    def supervised(self, poll_interval_s: Optional[float] = None,
                   probe_failures: int = 3):
        """A Supervisor probing every shard leader over TCP; a dead
        leader fires per-shard failover (``fail_shard``) — one shard
        moves, the rest of the cluster keeps serving untouched.  The
        caller starts/stops the returned Supervisor."""
        from ..supervise.supervisor import Supervisor

        sup = Supervisor(poll_interval_s=poll_interval_s,
                         name="cluster-supervisor")
        for i in range(self.n):
            if self.replicas[i] is None and self.replica_sets[i] is None:
                sup.add_probed(f"shard-{i}",
                               (lambda i=i: self._shard_alive(i)),
                               probe_failures=probe_failures)
            else:
                # quorum mode fails over through the same hook — the
                # election inside fail_shard is ISR-restricted
                sup.add_probed(
                    f"shard-{i}", (lambda i=i: self._shard_alive(i)),
                    probe_failures=probe_failures,
                    on_death=(lambda _u, i=i: self.fail_shard(i)))
        return sup

    def await_failover(self, shard: int, timeout_s: float = 10.0) -> bool:
        """Block until the shard's map entry moves (a supervised
        failover completed) or timeout."""
        cell = self.pmap.cell(shard)
        start_gen = cell.generation
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if cell.generation != start_gen:
                return True
            time.sleep(0.02)
        return False
