"""Partition-parallel fleets: scorers and KSQL pumps as consumer groups.

The reference scales inference as a K8s Deployment of predict pods in
one consumer group over 10 partitions (SURVEY §2.7) — kill a pod and
its partitions rebalance to survivors.  These helpers are that shape
over the partitioned cluster: every member is a ``GroupConsumer`` via
the wire group protocol (coordinator pinned to one broker), fetching
from whichever shard leads each of its assigned partitions.

Members are DRIVEN, not threaded, by default: ``pump_once()`` advances
every member one round deterministically (tests, the chaos runner), and
``start()`` wraps each member in a registered daemon thread for live
use.  Both fleets expose ``kill(i)`` — stop driving member *i* without
leaving the group, exactly a crashed pod: after the session timeout the
coordinator expires it and survivors inherit its partitions at the last
committed offsets.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence

from ..stream.group import GroupConsumer
from ..stream.kafka_wire import RemoteGroupCoordinator


class _Member:
    """One fleet member: a group-elastic consumer plus a per-round
    drive function; `alive` gates driving (kill(i) clears it)."""

    __slots__ = ("name", "consumer", "drive", "alive", "rounds",
                 "payload", "client")

    def __init__(self, name: str, consumer: GroupConsumer,
                 drive: Callable[[], int], payload=None, client=None):
        self.name = name
        self.consumer = consumer
        self.drive = drive
        self.alive = True
        self.rounds = 0
        #: the member's worker object (StreamScorer / StreamTask)
        self.payload = payload
        #: the member's own broker client — stop() closes its sockets
        self.client = client


class _Fleet:
    """Shared driving machinery; subclasses build the members."""

    def __init__(self):
        self.members: List[_Member] = []
        self._threads: List[Optional[threading.Thread]] = []
        self._stop = threading.Event()

    def pump_once(self) -> int:
        """Drive every live member one round; returns records handled."""
        n = 0
        for m in self.members:
            if m.alive:
                n += m.drive()
                m.rounds += 1
        return n

    def kill(self, i: int) -> None:
        """Stop driving member i WITHOUT leaving the group — the
        crashed-pod shape: its partitions rebalance to survivors only
        after the coordinator's session timeout expires it."""
        self.members[i].alive = False

    def assignments(self) -> List[Sequence]:
        return [m.consumer.assignment for m in self.members]

    def start(self, poll_interval_s: float = 0.05) -> "_Fleet":
        from ..supervise.registry import register_thread

        self._stop.clear()
        self._threads = []
        for m in self.members:
            def run(m=m):
                while not self._stop.is_set():
                    if m.alive:
                        moved = m.drive()
                        m.rounds += 1
                        if moved:
                            continue
                    self._stop.wait(poll_interval_s)

            t = register_thread(threading.Thread(
                target=run, daemon=True,
                name=f"iotml-fleet-{m.name}"))
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            if t is not None:
                t.join(timeout=10)
        for m in self.members:
            if m.alive:
                try:
                    m.consumer.close()  # commit + clean leave
                except (ConnectionError, RuntimeError, OSError):
                    pass
            # dead members keep crashed-pod semantics (no commit, no
            # clean leave — the coordinator expires them), but their
            # sockets must still be released: every member owns a
            # client with one connection per shard + the coordinator
            if m.client is not None:
                try:
                    m.client.close()
                except (ConnectionError, RuntimeError, OSError):
                    pass


class ScorerFleet(_Fleet):
    """N partition-parallel ``StreamScorer`` members in one group.

    Each member owns its slice of the input partitions (the group's
    assignment) and writes predictions to its OWN partition of the
    output topic — OutputSequence's per-member global index stays an
    ordered stream, and downstream consumers see one partition per
    member exactly like the reference's predict pods.

    Args:
      client_factory: () -> broker duck-type (a fresh ``ClusterClient``
        per member — members are independent processes in spirit, and
        must not share a coordinator connection).
      model/params: as StreamScorer.
      in_topic/group: the scored stream and the fleet's group id.
      out_topic: predictions topic (created with >= n_members
        partitions by the caller).

    Data plane: each member's `SensorBatches` takes the zero-copy
    columnar path automatically when the owning shards are durable (or
    reached over the wire) — raw frame batches routed by the
    ClusterClient, decoded by the one FrameDecoder into ring buffers.
    The process knobs IOTML_PREFETCH_DEPTH / IOTML_DECODE_RING_BUFFERS /
    IOTML_RAW_BATCH_BYTES (data/pipeline.py; `cluster up` flags) tune
    every member's pipeline at once.
    """

    def __init__(self, client_factory, model, params, n_members: int,
                 in_topic: str, out_topic: str,
                 group: str = "scorer-fleet",
                 session_timeout_ms: int = 10_000,
                 batch_size: int = 100, registry=None,
                 registry_poll_s: float = 0.25):
        super().__init__()
        from ..data.dataset import SensorBatches
        from ..serve.scorer import StreamScorer
        from ..stream.producer import OutputSequence

        self.group = group
        #: zero-downtime rollout across the whole fleet (iotml.mlops):
        #: one shared watcher hot-swaps EVERY member between drains when
        #: the registry's serving channel moves — the PR 6 partition-
        #: parallel shape of the single-scorer hot swap, driven by
        #: pump_once (deterministic) or the watcher thread (start()).
        self.watcher = None
        if registry is not None:
            from ..mlops.rollout import RegistryWatcher

            self.watcher = RegistryWatcher(registry,
                                           poll_interval_s=registry_poll_s)
        for i in range(n_members):
            client = client_factory()
            coord = RemoteGroupCoordinator(
                client, group, session_timeout_ms=session_timeout_ms)
            consumer = GroupConsumer(coord, [in_topic])
            batches = SensorBatches(consumer, batch_size=batch_size,
                                    only_normal=False)
            out = OutputSequence(client, out_topic, partition=i)
            scorer = StreamScorer(model, params, batches, out)

            def drive(scorer=scorer, consumer=consumer):
                try:
                    return scorer.score_available()
                except ConnectionError:
                    consumer.rewind_to_committed()
                    return 0

            self.members.append(
                _Member(f"scorer-{i}", consumer, drive, payload=scorer,
                        client=client))
            if self.watcher is not None:
                self.watcher.attach(scorer)

    def pump_once(self) -> int:
        if self.watcher is not None:
            # swap-before-drive: a promotion lands on every member at
            # the same deterministic point (between fleet rounds)
            self.watcher.poll_once()
        return super().pump_once()

    def start(self, poll_interval_s: float = 0.05) -> "_Fleet":
        if self.watcher is not None:
            self.watcher.start()
        return super().start(poll_interval_s)

    def stop(self) -> None:
        if self.watcher is not None:
            self.watcher.stop()
        super().stop()

    def scored(self) -> int:
        return sum(m.payload.scored for m in self.members)


class PumpFleet(_Fleet):
    """N group-elastic KSQL pump members over one task class.

    Each member is an independent ``StreamTask`` instance whose consumer
    is a ``GroupConsumer`` on the shared group — the task's source
    partitions split across members and rebalance on death, turning the
    single-threaded KSQL pump into the reference's scalable
    stream-processing tier.

    Write plane: members whose task implements ``process_raw`` (the
    AVRO CSAS's fused JSON leg) convert+frame each chunk natively and
    produce RAW batches to their owned partitions through the member's
    ``ClusterClient.produce_raw`` — routed to the owning shard and
    appended segment-verbatim (ARCHITECTURE §21).  The process knobs
    IOTML_RAW_PRODUCE / IOTML_PRODUCE_BATCH_BYTES (``cluster up
    --raw-produce / --produce-batch-bytes``) select the plane for every
    member at once; extension-less shards pin members back to classic
    PRODUCE.
    """

    def __init__(self, client_factory, task_factory, n_members: int,
                 src_topic: str, group: str = "pump-fleet",
                 session_timeout_ms: int = 10_000):
        super().__init__()
        self.group = group
        for i in range(n_members):
            client = client_factory()
            coord = RemoteGroupCoordinator(
                client, group, session_timeout_ms=session_timeout_ms)
            consumer = GroupConsumer(coord, [src_topic])
            task = task_factory(client, consumer)

            def drive(task=task):
                try:
                    return task.process_available()
                except ConnectionError:
                    task.consumer.rewind_to_committed()
                    return 0

            self.members.append(
                _Member(f"pump-{i}", consumer, drive, payload=task,
                        client=client))
