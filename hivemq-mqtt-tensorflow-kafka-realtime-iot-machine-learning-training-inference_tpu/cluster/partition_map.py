"""PartitionMap — who leads each (topic, partition), at which epoch.

The reference's data plane spreads 10-partition topics over a 3-broker
cluster (PAPER.md L3: `01_installConfluentPlatform.sh:180-183`), with
ZooKeeper-backed controllers tracking per-partition leadership.  The
rebuild's equivalent generalises the single-leader
``iotml.supervise.Topology`` — one ``(leader, epoch)`` cell — into a map
of them: one cell **per shard**, plus a static partition→shard policy.

Design decisions:

- **Shard identity is stable; addresses move.**  A shard keeps its id
  across failovers — the promoted follower inherits the shard, the map
  publishes its new ``(address, epoch)``, and every other shard's cell
  is untouched.  "Follower promotion moves one shard, not the world."
- **The policy is a pure function** (``partition % n_shards``): every
  party — brokers deciding what they own, clients deciding where to
  route, the controller deciding what to boot — computes the same
  answer with no coordination.  The wire protocol's Metadata responses
  carry the materialized map for external clients.
- **Cells are ``supervise.Topology`` objects**, so per-shard wire
  clients built with ``topology=map.cell(shard)`` inherit the whole
  PR 4 failover machinery unchanged: reconnects re-resolve the shard's
  live address, and the shard's fencing epoch rides every request as
  the ``@e<N>`` client-id tag — a moved partition fences its stale
  leader exactly like the single-leader plane did.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..supervise.topology import Topology


class PartitionMap:
    """Thread-safe (topic, partition) → (broker address, epoch) map.

    Args:
      leaders: initial leader address per shard (index = shard id).
      epochs: initial fencing epoch per shard (default all 0).
      coordinator_shard: the shard whose live leader holds every
        consumer group's membership and offset state (FIND_COORDINATOR
        is pinned here — group state must live in exactly one place).
    """

    def __init__(self, leaders: List[str],
                 epochs: Optional[List[int]] = None,
                 coordinator_shard: int = 0):
        if not leaders:
            raise ValueError("a cluster needs at least one shard")
        epochs = epochs or [0] * len(leaders)
        if len(epochs) != len(leaders):
            raise ValueError("one epoch per shard")
        if not 0 <= coordinator_shard < len(leaders):
            raise ValueError(f"coordinator shard {coordinator_shard} "
                             f"outside 0..{len(leaders) - 1}")
        self._lock = threading.Lock()
        # every OTHER shard's address is each cell's fallback list: a
        # client that cannot reach its shard's leader still finds a
        # live broker to refresh metadata from
        self._cells = [
            Topology(addr, epoch=epochs[i],
                     fallback=[a for j, a in enumerate(leaders) if j != i])
            for i, addr in enumerate(leaders)]
        self._coordinator_shard = coordinator_shard
        self._topics: Dict[str, int] = {}

    # ------------------------------------------------------------ policy
    @property
    def n_shards(self) -> int:
        return len(self._cells)

    def shard_for(self, topic: str, partition: int) -> int:
        """The owning shard — a pure function of the partition index, so
        brokers, clients and the controller agree with no coordination."""
        return int(partition) % len(self._cells)

    # ------------------------------------------------------------ topics
    def register_topic(self, name: str, partitions: int) -> None:
        with self._lock:
            self._topics[name] = int(partitions)

    def topics(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._topics)

    def partitions_of(self, shard: int, topic: str) -> List[int]:
        """The partition indexes of `topic` this shard owns."""
        with self._lock:
            n = self._topics.get(topic, 0)
        return [p for p in range(n) if self.shard_for(topic, p) == shard]

    # ----------------------------------------------------------- resolve
    def cell(self, shard: int) -> Topology:
        """The shard's live (leader, epoch) cell — hand it to
        ``KafkaWireBroker(topology=...)`` and the client re-resolves the
        shard's address + fencing epoch on every reconnect."""
        return self._cells[shard]

    def resolve(self, topic: str, partition: int
                ) -> Tuple[List[str], int]:
        """(servers, epoch) for the shard owning (topic, partition):
        live leader first, every other known broker as fallback."""
        return self._cells[self.shard_for(topic, partition)].resolve()

    def leader(self, shard: int) -> str:
        return self._cells[shard].leader

    def epoch(self, shard: int) -> int:
        return self._cells[shard].epoch

    def addresses(self) -> List[str]:
        """Current leader address per shard (index = shard id)."""
        return [c.leader for c in self._cells]

    @property
    def generation(self) -> int:
        """Cheap change detector: total publishes across all cells."""
        return sum(c.generation for c in self._cells)

    # ------------------------------------------------------- coordinator
    @property
    def coordinator_shard(self) -> int:
        with self._lock:
            return self._coordinator_shard

    def coordinator(self) -> Tuple[int, str]:
        """(shard id, live address) of the pinned group coordinator."""
        with self._lock:
            shard = self._coordinator_shard
        return shard, self._cells[shard].leader

    def set_coordinator(self, shard: int) -> None:
        """Re-pin group coordination (operator/controller action after a
        coordinator broker is lost beyond its own shard failover)."""
        if not 0 <= shard < len(self._cells):
            raise ValueError(f"no shard {shard}")
        with self._lock:
            self._coordinator_shard = shard

    # ----------------------------------------------------------- publish
    def publish(self, shard: int, leader: str, epoch: int) -> None:
        """Install a shard's new leadership term (failover): ONE cell
        moves; the Topology's monotonic-epoch check rejects a belated
        publish from a slow failover path.  Every other cell learns the
        new address as a fallback replacement for the old one."""
        old = self._cells[shard].leader
        self._cells[shard].publish(leader, epoch)
        for i, c in enumerate(self._cells):
            if i != shard:
                # swap the moved shard's address in the other cells'
                # fallback lists so metadata refreshes keep working
                # through any shard's client
                c.replace_fallback(old, leader)
