"""ShardBroker — a Broker that serves only its shard's partitions.

Each cluster node runs one of these: topics carry their full cluster
partition count (so metadata, key-hash partitioning and consumer-group
assignment all see the real width), but only the partitions the shard
OWNS are materialized — in memory, or as mounted ``iotml.store``
per-partition segment dirs under the shard's own store directory.  Any
touch of an unowned partition raises ``NotLeaderForPartitionError``,
which the wire server answers as Kafka error 6 and routing clients
(``ClusterClient``) turn into a metadata refresh + re-route.

Consumer-group offsets are deliberately NOT ownership-filtered: the
cluster pins all group state to one coordinator broker, and that broker
commits/serves offsets for every partition regardless of which shard
stores the records.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..stream.broker import Broker
from ..stream.kafka_wire import NotLeaderForPartitionError


class _UnownedPartition:
    """Placeholder for a partition this shard does not lead: every
    touch-point raises the routing signal.  Nothing is mounted — the
    shard's store dir holds only its own partitions' segments."""

    __slots__ = ("topic", "partition")

    def __init__(self, topic: str, partition: int):
        self.topic = topic
        self.partition = partition

    def _refuse(self, *_a, **_kw):
        raise NotLeaderForPartitionError(self.topic, self.partition)

    # the full _Partition touch-point surface, all refusing
    append = append_at = append_raw = sync_batch = note_replay = _refuse
    end = base = read = read_raw = drop_head = enforce_retention = _refuse
    align_base = reset = offset_for_timestamp = _refuse


class ShardBroker(Broker):
    """``Broker`` whose partitions are filtered by an ownership predicate.

    Args:
      owns: ``(topic, partition) -> bool`` — typically
        ``lambda t, p: pmap.shard_for(t, p) == shard_id``.  Must be pure
        and stable for the broker's lifetime: ownership *moves* by
        promoting this shard's follower (a new broker object), never by
        mutating a live broker's predicate.
      shard_id: this node's id in the cluster (metadata/diagnostics).
      store_dir / store_policy: as ``Broker`` — only owned partitions
        mount segment logs under the dir.
    """

    def __init__(self, owns: Callable[[str, int], bool],
                 shard_id: Optional[int] = None,
                 store_dir: Optional[str] = None, store_policy=None):
        # set BEFORE super().__init__: a durable mount re-creates the
        # manifest's topics during construction, which calls
        # _make_partition for every partition
        self._owns_fn = owns
        self.shard_id = shard_id
        super().__init__(store_dir=store_dir, store_policy=store_policy)

    def owns(self, topic: str, partition: int) -> bool:
        return bool(self._owns_fn(topic, partition))

    def _make_partition(self, topic: str, partition: int):
        if not self._owns_fn(topic, partition):
            return _UnownedPartition(topic, partition)
        return super()._make_partition(topic, partition)
