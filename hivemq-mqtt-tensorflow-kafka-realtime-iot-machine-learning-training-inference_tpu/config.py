"""One typed configuration system for the whole framework.

The reference spreads configuration across five uncoordinated layers —
positional sys.argv CLIs, env-var defaults in shell scripts, Terraform
variables, Helm values, and two XML dialects — with the SASL credentials
repeated verbatim in three of them (SURVEY §5, reference cardata-v3.py:7-15,
gcp.yaml:29-32, kafka-config.yaml:12-17).  Here every knob lives in one
dataclass tree with one resolution order:

    defaults  <  config file (JSON)  <  environment  <  CLI flags

Environment keys: ``IOTML_<SECTION>_<FIELD>`` (e.g. ``IOTML_TRAIN_EPOCHS``).
CLI flags: ``--<section>.<field>=<value>`` (e.g. ``--train.epochs=20``).
Values are coerced to the dataclass field's type, so a typo'd type fails
loudly at load time instead of deep inside a job.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, get_args, get_origin


# --------------------------------------------------------------- sections
@dataclasses.dataclass
class BrokerConfig:
    """Stream-broker connection (the reference's Kafka client config)."""

    servers: str = "emulator"     # emulator[:n] | host:port,...
    sasl_username: str = ""       # reference: hard-coded 'test' — never again
    sasl_password: str = ""
    partitions: int = 10          # reference topic provisioning
    retention_messages: int = 0   # 0 = unbounded (reference: retention.ms)


@dataclasses.dataclass
class StreamConfig:
    """Topics and cursor — the reference CLI's positional args."""

    topic: str = "SENSOR_DATA_S_AVRO"
    result_topic: str = "model-predictions"
    offset: int = 0
    group: str = "cardata-autoencoder"


@dataclasses.dataclass
class TrainConfig:
    """The reference train job's knobs (cardata-v3.py:176-218)."""

    epochs: int = 20
    batch_size: int = 100
    take_batches: int = 100
    learning_rate: float = 1e-3
    only_normal: bool = True
    model: str = "autoencoder"    # autoencoder | lstm | sensorformer


@dataclasses.dataclass
class ServeConfig:
    """Continuous scorer (fixes the restart-the-pod loop)."""

    skip_batches: int = 100
    take_batches: int = 100
    poll_interval_s: float = 0.5
    checkpoint_every_batches: int = 50
    threshold: float = 0.0   # >0: append anomaly verdicts (notebook thr 5)


@dataclasses.dataclass
class ArtifactConfig:
    root: str = "/tmp/iotml-artifacts"   # dir or gs:// bucket
    model_file: str = "model1"


@dataclasses.dataclass
class ScenarioConfig:
    """Fleet load generation (the XML scenario dialect, typed)."""

    num_cars: int = 25
    msgs_per_car: int = 40
    interval_s: float = 5.0
    ramp_up_s: float = 5.0
    qos: int = 1
    failure_rate: float = 0.01


@dataclasses.dataclass
class MeshConfig:
    """Device-mesh shape for pjit (data/model/sequence axes).

    NOTE: the env key ``IOTML_MESH_DATA`` is claimed by the multichip
    streaming PROCESS knob (data/pipeline.py, non_config below) — the
    ``data`` field here stays settable via ``--mesh.data`` and config
    files."""

    data: int = -1      # -1 = all devices on the data axis
    model: int = 1
    seq: int = 1


@dataclasses.dataclass
class StoreConfig:
    """Durable segmented-log storage (iotml.store).

    ``dir`` empty (the default) keeps the broker in-memory; set it
    (``IOTML_STORE_DIR=/var/lib/iotml``) — or pass ``--durable`` to the
    platform CLI — to mount a crash-recoverable log per partition.
    Retention here is the store-wide default; per-topic retention on
    TopicSpec overrides it."""

    dir: str = ""                    # empty = in-memory broker
    fsync: str = "interval"          # never | interval | always
    fsync_interval_s: float = 0.05
    segment_bytes: int = 16 * 1024 * 1024
    segment_age_s: float = 0.0       # 0 = roll by bytes only
    retention_bytes: int = 0         # 0 = unbounded
    retention_ms: int = 0            # 0 = unbounded (reference: 100000)
    retention_messages: int = 0      # 0 = unbounded (segment-granular)
    index_interval_bytes: int = 4096
    # cleanup.policy=compact topics: dirty-ratio trigger for the
    # background compactor and the tombstone grace window (Kafka's
    # min.cleanable.dirty.ratio / delete.retention.ms analogs)
    compact_min_dirty_ratio: float = 0.5
    compact_grace_ms: int = 60_000
    compact_interval_s: float = 5.0  # background compactor cadence


@dataclasses.dataclass
class TierConfig:
    """Object-store tiered log storage (iotml.store.tiered).

    ``uri`` empty (the default) keeps the durable log local-only; set
    it to a directory path or ``gs://bucket/prefix``
    (``IOTML_TIER_URI``) — or pass ``--tier-uri`` to the platform CLI —
    and sealed segments offload to the ArtifactStore-backed remote
    tier, with reads falling through transparently below the local
    base.  Only meaningful alongside a durable store (``store.dir``)."""

    uri: str = ""                 # empty = no remote tier
    local_hot_bytes: int = 0      # hot-tier budget/partition; 0 = no evict
    upload_lag_s: float = 0.0     # min sealed age before upload
    remote_retention_ms: int = 0  # remote history age cap; 0 = forever
    cache_segments: int = 4       # RemoteSegmentCache entries/partition
    interval_s: float = 5.0       # background TierUploader cadence


@dataclasses.dataclass
class MlopsConfig:
    """Model lifecycle (iotml.mlops): versioned registry + async
    checkpointing + rollout.

    ``registry_dir`` empty (the default) keeps the legacy artifact-
    store pointer flow; set it (``IOTML_MLOPS_REGISTRY_DIR``) — or pass
    ``--registry`` to the live/up CLIs — to publish every training
    round as a committed, offsets-stamped registry version that scorers
    hot-swap to."""

    registry_dir: str = ""        # empty = no registry
    queue_depth: int = 2          # pending snapshots before drop-oldest
    auto_promote: bool = True     # serving follows every publish
    watch_poll_s: float = 0.25    # scorer-side channel poll cadence
    save_opt_state: bool = True   # archive optimizer moments per version
    keep_versions: int = 16       # prune beyond newest N (0 = keep all)


@dataclasses.dataclass
class OnlineConfig:
    """True online learning (iotml.online): per-window incremental
    updates with drift-triggered adaptation.

    The learner itself is constructed explicitly (``python -m
    iotml.online run`` or the drill); these knobs set its detector
    thresholds and adaptation policy.  Detector deltas are unit-free
    (the monitor normalizes the error signal by its own stable
    baseline)."""

    window: int = 100            # records per incremental SGD update
    detector: str = "both"       # ph | adwin | both
    ph_delta: float = 0.15       # Page-Hinkley drift allowance
    ph_threshold: float = 2.5    # Page-Hinkley trip level (lambda)
    adwin_delta: float = 0.002   # ADWIN cut confidence
    adapt: str = "auto"          # boost | refit | reset | auto
    lr_boost: float = 5.0        # LR multiplier while adapting
    boost_updates: int = 80      # windows the boost stays active
    refit_epochs: int = 2        # replay-buffer passes on "refit"
    publish_every: int = 20      # windows between steady-state publishes
    buffer_batches: int = 32     # replay-buffer depth (windows)


@dataclasses.dataclass
class SloConfig:
    """Burn-rate SLO engine over the log-native TSDB (iotml.obs.slo).

    ``rules_path`` empty (the default) materializes the canary-backed
    starter pair (``iotml.obs.canary.default_slo_rules``); set it
    (``IOTML_SLO_RULES_PATH``) to a JSON file holding a list of
    declarative rule dicts in the ``SloRule.from_dict`` shape.
    ``window_scale`` compresses every rule's burn windows by the same
    factor (a drill runs the 5 m/1 h pair in seconds without changing
    the alert logic)."""

    rules_path: str = ""         # JSON list of SLO rule dicts
    window_scale: float = 1.0    # burn-window compression factor
    interval_s: float = 2.0      # engine evaluation cadence
    tsdb_chunk_ms: int = 60_000  # TSDB appender chunk window


def slo_rules(cfg: SloConfig) -> list:
    """Materialize the declarative rule dicts an ``SloEngine`` takes:
    the JSON file when configured, the canary defaults otherwise
    (``window_scale`` applies to both)."""
    if not cfg.rules_path:
        from .obs.canary import default_slo_rules
        return default_slo_rules(window_scale=cfg.window_scale)
    with open(cfg.rules_path) as f:
        docs = json.load(f)
    if not isinstance(docs, list):
        raise ValueError(f"{cfg.rules_path}: expected a JSON list of "
                         f"SLO rule dicts, got {type(docs).__name__}")
    for doc in docs:
        doc.setdefault("window_scale", cfg.window_scale)
    return docs


@dataclasses.dataclass
class Config:
    broker: BrokerConfig = dataclasses.field(default_factory=BrokerConfig)
    stream: StreamConfig = dataclasses.field(default_factory=StreamConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    artifacts: ArtifactConfig = dataclasses.field(default_factory=ArtifactConfig)
    scenario: ScenarioConfig = dataclasses.field(default_factory=ScenarioConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    store: StoreConfig = dataclasses.field(default_factory=StoreConfig)
    tier: TierConfig = dataclasses.field(default_factory=TierConfig)
    mlops: MlopsConfig = dataclasses.field(default_factory=MlopsConfig)
    online: OnlineConfig = dataclasses.field(default_factory=OnlineConfig)
    slo: SloConfig = dataclasses.field(default_factory=SloConfig)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def dumps(self) -> str:
        return json.dumps(self.as_dict(), indent=2, sort_keys=True)


def env_key_names(cfg: Optional[Config] = None) -> list:
    """Every IOTML_<SECTION>_<FIELD> env var the resolver accepts — the
    deploy manifests are validated against this list so a typo'd env name
    fails in CI, not silently in the pod."""
    cfg = cfg or Config()
    names = []
    for section, sub in dataclasses.asdict(cfg).items():
        for field in sub:
            names.append(f"IOTML_{section.upper()}_{field.upper()}")
    return names


# -------------------------------------------------------------- resolution
def _coerce(value: Any, typ: type, where: str) -> Any:
    if get_origin(typ) is not None:  # Optional[...] etc.
        args = [a for a in get_args(typ) if a is not type(None)]
        if len(args) == 1:
            if value is None:
                return None
            typ = args[0]
    if isinstance(value, typ) and not (typ is int and isinstance(value, bool)):
        return value
    if typ is bool:
        if isinstance(value, str):
            low = value.strip().lower()
            if low in ("1", "true", "yes", "on"):
                return True
            if low in ("0", "false", "no", "off"):
                return False
        raise ValueError(f"{where}: cannot parse {value!r} as bool")
    try:
        return typ(value)
    except (TypeError, ValueError) as e:
        raise ValueError(f"{where}: cannot parse {value!r} as "
                         f"{typ.__name__}") from e


def _apply(cfg: Any, dotted: str, value: Any,
           applied: Optional[set] = None) -> None:
    section, _, field = dotted.partition(".")
    if not field:
        raise ValueError(f"config key {dotted!r}: expected section.field")
    if not hasattr(cfg, section):
        raise ValueError(f"unknown config section {section!r} "
                         f"(have: {[f.name for f in dataclasses.fields(cfg)]})")
    sub = getattr(cfg, section)
    flds = {f.name: f for f in dataclasses.fields(sub)}
    if field not in flds:
        raise ValueError(f"unknown config key {dotted!r} "
                         f"(section {section!r} has: {sorted(flds)})")
    typ = flds[field].type
    if isinstance(typ, str):  # from __future__ annotations
        typ = {"int": int, "float": float, "str": str, "bool": bool}.get(typ, str)
    setattr(sub, field, _coerce(value, typ, dotted))
    if applied is not None:
        applied.add(dotted)


def load_config(argv: Optional[Sequence[str]] = None,
                env: Optional[Dict[str, str]] = None,
                path: Optional[str] = None) -> Tuple[Config, List[str]]:
    """Resolve a Config. Returns (config, leftover_argv).

    argv: flags of the form --section.field=value (or --section.field value);
      anything else is passed through in leftover_argv (so positional CLIs
      keep working in front of this).
    env: mapping (defaults to os.environ); keys IOTML_<SECTION>_<FIELD>.
    path: JSON config file; also honors env IOTML_CONFIG.
    """
    cfg = Config()
    applied: set = set()
    env = dict(os.environ if env is None else env)

    path = path or env.get("IOTML_CONFIG")
    if path:
        with open(path) as fh:
            doc = json.load(fh)
        for section, sub in doc.items():
            if not isinstance(sub, dict):
                raise ValueError(f"config file {path}: section {section!r} "
                                 f"must be an object")
            for field, value in sub.items():
                _apply(cfg, f"{section}.{field}", value, applied)

    sections = {f.name for f in dataclasses.fields(cfg)}
    # process-level toggles that are NOT config: the test platform pin
    # (tests/conftest.py), the runtime lock-order detector switches
    # (iotml.analysis.lockcheck), the record-trace switches
    # (iotml.obs.tracing), the fault-injection switches
    # (iotml.chaos.faults) and the supervision switches (iotml.cli.up /
    # iotml.supervise) ride the IOTML_ prefix but configure the harness
    # around the process, not the pipeline inside it
    non_config = {"IOTML_CONFIG", "IOTML_TEST_PLATFORM",
                  "IOTML_LOCKCHECK", "IOTML_LOCKCHECK_STRICT",
                  "IOTML_TRACECHECK",
                  "IOTML_TRACE", "IOTML_TRACE_SAMPLE", "IOTML_TRACE_PATH",
                  "IOTML_CHAOS", "IOTML_CHAOS_SEED",
                  "IOTML_CHAOS_SCENARIO", "IOTML_CHAOS_RECORDS",
                  "IOTML_DEVSIM_DIR",
                  "IOTML_SUPERVISE", "IOTML_SUPERVISE_POLL_S",
                  "IOTML_SUPERVISE_MAX_RESTARTS",
                  # zero-copy pipeline knobs (data/pipeline.py): they
                  # tune the process's decode/prefetch machinery, not
                  # the pipeline's logical config — and their names
                  # predate the SECTION_FIELD convention
                  "IOTML_PREFETCH_DEPTH", "IOTML_DECODE_RING_BUFFERS",
                  "IOTML_RAW_BATCH_BYTES",
                  # write-plane knobs (ISSUE 12): same family — they
                  # select the process's produce machinery (RAW_PRODUCE
                  # vs classic), not the pipeline's logical config
                  "IOTML_RAW_PRODUCE", "IOTML_PRODUCE_BATCH_BYTES",
                  # fleet-scope observability (ISSUE 13): watermark
                  # toggle, the process name stamped into span logs,
                  # and the metrics-endpoint manifest path the
                  # federation collector scrapes
                  "IOTML_WATERMARK", "IOTML_PROC",
                  "IOTML_OBS_ENDPOINTS",
                  # multi-chip streaming training (ISSUE 15): the data-
                  # mesh size and the device-side normalization toggle
                  # select the process's training machinery, same
                  # family as the decode/prefetch knobs above
                  "IOTML_MESH_DATA", "IOTML_DEVICE_NORMALIZE",
                  # REST serving plane (ISSUE 20): the concurrent-
                  # connection ceiling every RestServer sheds 503s
                  # past — a process-protection knob, not pipeline
                  # config
                  "IOTML_REST_MAX_CONCURRENCY"}
    for key, value in env.items():
        if not key.startswith("IOTML_") or key in non_config:
            continue
        rest = key[len("IOTML_"):].lower()
        section, _, field = rest.partition("_")
        if section not in sections:
            # an IOTML_-prefixed var is an explicit instruction to this
            # process — a typo'd section must fail as loudly as a typo'd
            # field, not silently fall back to the default
            raise ValueError(f"env {key}: unknown config section "
                             f"{section!r} (have: {sorted(sections)})")
        _apply(cfg, f"{section}.{field}", value, applied)

    leftover: List[str] = []
    argv = list(argv or [])
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--") and "." in a:
            body = a[2:]
            if "=" in body:
                dotted, value = body.split("=", 1)
            elif i + 1 < len(argv):
                dotted, value = body, argv[i + 1]
                i += 1
            else:
                raise ValueError(f"flag {a!r} is missing a value")
            _apply(cfg, dotted, value, applied)
        else:
            leftover.append(a)
        i += 1
    # which keys any layer explicitly set — lets callers distinguish
    # "configured" from "default" (CLIs keep their own defaults otherwise)
    cfg.applied = applied
    return cfg, leftover
