from .connectors import (DocumentStoreSink, FileStreamSource, HoistFieldKey,
                         ObjectStoreSink)
from .runtime import ConnectWorker, SinkConnector, SourceConnector, SourceRecord

__all__ = ["ConnectWorker", "SourceConnector", "SinkConnector", "SourceRecord",
           "FileStreamSource", "DocumentStoreSink", "ObjectStoreSink",
           "HoistFieldKey"]
