from .connectors import (DocumentStoreSink, FileStreamSource, HoistFieldKey,
                         ObjectStoreSink)
from .runtime import ConnectWorker, SinkConnector, SourceConnector, SourceRecord
from .server import ConnectServer

__all__ = ["ConnectWorker", "ConnectServer", "SourceConnector", "SinkConnector", "SourceRecord",
           "FileStreamSource", "DocumentStoreSink", "ObjectStoreSink",
           "HoistFieldKey"]
