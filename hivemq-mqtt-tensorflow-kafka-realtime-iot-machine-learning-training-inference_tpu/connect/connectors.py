"""The reference's three connectors, re-provided for the in-process runtime.

- `FileStreamSource`: line-by-line file replay into a topic — the offline
  test fixture (reference `file_stream_demo_standalone.properties:2-8`,
  topic `car-data-csv`).  Tails the file across `poll()` calls, so appended
  lines flow like a live stream.
- `DocumentStoreSink`: the MongoDB digital-twin sink (reference
  `mongodb-connector-configmap.yaml:6-23`).  JSON values upserted by `_id`,
  with the reference's HoistField$Key SMT semantics: the record's String
  key becomes the `_id` field.  Persists as a JSON file (the "Atlas"
  stand-in) and supports point lookups — one document per car, latest state
  wins, which is exactly the digital-twin contract.
- `ObjectStoreSink`: the GCS data-lake sink (reference
  `kafka-connect/gcs/README.md:21-43`).  Confluent-framed Avro messages
  are unframed and rolled into standard `.avro` Object Container Files
  named `<topic>+<partition>+<start_offset>.avro` — the GCS connector's
  object-naming scheme — under a local or mounted directory.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

from ..core.schema import RecordSchema
from ..ops.avro_container import ContainerWriter
from ..ops.framing import strip_frame
from ..stream.broker import Message
from .runtime import SinkConnector, SourceConnector, SourceRecord


class FileStreamSource(SourceConnector):
    """Replay/tail a text file into a topic, one line per record."""

    def __init__(self, path: str, topic: str, skip_header: bool = False,
                 batch_lines: int = 1000):
        self.path = path
        self.topic = topic
        self.skip_header = skip_header
        self.batch_lines = batch_lines
        self._pos = 0
        self._header_skipped = not skip_header

    def poll(self) -> List[SourceRecord]:
        if not os.path.exists(self.path):
            return []
        out: List[SourceRecord] = []
        with open(self.path, "rb") as fh:
            fh.seek(self._pos)
            while len(out) < self.batch_lines:
                line = fh.readline()
                if not line or not line.endswith(b"\n"):
                    break  # EOF or partial line still being written
                self._pos = fh.tell()
                if not self._header_skipped:
                    self._header_skipped = True
                    continue
                stripped = line.rstrip(b"\r\n")
                if stripped:
                    out.append(SourceRecord(topic=self.topic, value=stripped))
        return out

    def state(self) -> dict:
        return {"pos": self._pos, "header_skipped": self._header_skipped}

    def restore(self, state: dict) -> None:
        self._pos = int(state.get("pos", 0))
        self._header_skipped = bool(state.get("header_skipped",
                                              not self.skip_header))


class HoistFieldKey:
    """SMT: wrap the record's key as a named field of the value document.

    Equivalent of the reference's `HoistField$Key` + `field: _id` transform
    (mongodb-connector-configmap.yaml:15-17): downstream sinks see the key
    inside the document.  Applied by DocumentStoreSink via `key_field`;
    usable standalone as a Message→Message transform producing JSON."""

    def __init__(self, field: str = "_id"):
        self.field = field

    def __call__(self, m: Message) -> Message:
        try:
            doc = json.loads(m.value) if m.value else {}
        except (ValueError, UnicodeDecodeError):
            # poison record: pass through untouched — the sink's own
            # malformed-record policy (DLQ/drop) decides, and the worker
            # must not wedge on it forever
            return m
        if not isinstance(doc, dict):
            doc = {"value": doc}
        doc[self.field] = (m.key or b"").decode()
        return Message(topic=m.topic, partition=m.partition, offset=m.offset,
                       value=json.dumps(doc).encode(), key=m.key,
                       timestamp_ms=m.timestamp_ms)


class DocumentStoreSink(SinkConnector):
    """Upsert JSON documents by `_id` — the MongoDB digital-twin stand-in."""

    def __init__(self, path: Optional[str] = None, id_field: str = "_id"):
        self.path = path
        self.id_field = id_field
        self.docs: Dict[str, dict] = {}
        if path and os.path.exists(path):
            with open(path) as fh:
                self.docs = json.load(fh)

    def put(self, messages: Sequence[Message]) -> None:
        for m in messages:
            try:
                doc = json.loads(m.value)
            except (ValueError, UnicodeDecodeError):
                continue  # non-JSON record: the reference sink would DLQ it
            if not isinstance(doc, dict):
                doc = {"value": doc}
            if self.id_field not in doc:
                doc[self.id_field] = (m.key or str(m.offset).encode()).decode()
            self.docs[str(doc[self.id_field])] = doc

    def flush(self) -> None:
        if self.path:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(self.docs, fh)
            os.replace(tmp, self.path)

    # digital-twin queries
    def find_one(self, doc_id: str) -> Optional[dict]:
        return self.docs.get(doc_id)

    def count(self) -> int:
        return len(self.docs)


class ObjectStoreSink(SinkConnector):
    """Roll framed-Avro topic messages into `.avro` container files."""

    def __init__(self, directory: str, schema: RecordSchema,
                 flush_size: int = 1000, framed: bool = True):
        self.directory = directory
        self.schema = schema
        self.flush_size = flush_size
        self.framed = framed
        os.makedirs(directory, exist_ok=True)
        # pending payloads per (topic, partition): [(offset, payload)]
        self._pending: Dict[tuple, List[tuple]] = {}
        self.files_written: List[str] = []

    def put(self, messages: Sequence[Message]) -> None:
        for m in messages:
            payload = strip_frame(m.value) if self.framed else m.value
            self._pending.setdefault((m.topic, m.partition), []) \
                .append((m.offset, payload))
        for key, pending in list(self._pending.items()):
            if len(pending) >= self.flush_size:
                self._roll(key, pending)
                self._pending[key] = []

    def _roll(self, key: tuple, pending: List[tuple]) -> None:
        if not pending:
            return
        topic, partition = key
        start = pending[0][0]
        # GCS connector object naming: <topic>+<partition>+<startoffset>.avro
        name = f"{topic}+{partition}+{start:010d}.avro"
        path = os.path.join(self.directory, name)
        with ContainerWriter(path, self.schema) as w:
            w.write_block([p for _, p in pending])
        self.files_written.append(path)

    def flush(self) -> None:
        for key, pending in list(self._pending.items()):
            self._roll(key, pending)
            self._pending[key] = []
