"""Kafka-Connect-equivalent runtime: sources and sinks around the broker.

The reference runs a two-node Connect cluster hosting three connectors
(SURVEY §2.2): a FileStreamSource replaying the test CSV
(`testdata/Test-Load-csv/file_stream_demo_standalone.properties`), a MongoDB
sink building the digital twin (`infrastructure/kafka-connect/mongodb/`),
and a GCS sink archiving the Avro topic
(`infrastructure/kafka-connect/gcs/`).  The runtime contract those share is
what this module provides: named connector instances driven by a worker,
source offsets tracked so restarts resume, sink progress tracked via
consumer-group commits, and single-message transforms (SMTs) applied
between the log and the sink.

Incremental (`run_once`) like `streamproc.tasks`, so tests and demo drivers
interleave connectors with producers deterministically; `run_forever` is the
daemon form.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..stream.broker import Broker, Message
from ..stream.consumer import StreamConsumer


@dataclasses.dataclass
class SourceRecord:
    """What a source connector emits: destination topic + key/value."""

    topic: str
    value: bytes
    key: Optional[bytes] = None


class SourceConnector:
    """Produce records into the broker.  Subclasses implement `poll()`
    returning a list of SourceRecord ([] = nothing new) and may persist
    position via `state()` / `restore(state)`."""

    def poll(self) -> List[SourceRecord]:  # pragma: no cover - interface
        raise NotImplementedError

    def state(self) -> dict:
        return {}

    def restore(self, state: dict) -> None:
        pass


class SinkConnector:
    """Consume records from the broker.  `put(messages)` handles a batch;
    `flush()` makes side effects durable (called after each drained run)."""

    def put(self, messages: Sequence[Message]) -> None:  # pragma: no cover
        raise NotImplementedError

    def flush(self) -> None:
        pass


@dataclasses.dataclass
class _SourceEntry:
    name: str
    connector: SourceConnector


@dataclasses.dataclass
class _SinkEntry:
    name: str
    connector: SinkConnector
    consumer: StreamConsumer
    transforms: tuple


class ConnectWorker:
    """Drives registered connectors against one broker."""

    def __init__(self, broker: Broker):
        self.broker = broker
        self._sources: List[_SourceEntry] = []
        self._sinks: List[_SinkEntry] = []

    def add_source(self, name: str, connector: SourceConnector) -> None:
        self._sources.append(_SourceEntry(name, connector))

    def add_sink(self, name: str, connector: SinkConnector,
                 topics: Sequence[str],
                 transforms: Sequence[Callable[[Message], Message]] = (),
                 from_committed: bool = True) -> None:
        """transforms: SMT chain applied to each message before `put`.
        Sink progress rides the consumer group `connect-<name>` so a
        restarted worker resumes from the last commit."""
        group = f"connect-{name}"
        specs = []
        for t in topics:
            self.broker.create_topic(t)
            n = self.broker.topic(t).partitions
            for p in range(n):
                off = self.broker.committed(group, t, p) if from_committed \
                    else None
                specs.append(f"{t}:{p}:{off if off is not None else 0}")
        consumer = StreamConsumer(self.broker, specs, group=group)
        self._sinks.append(_SinkEntry(name, connector, consumer,
                                      tuple(transforms)))

    def remove(self, name: str) -> bool:
        """Unregister a connector by name (Connect's DELETE). Sink progress
        stays committed under `connect-<name>`, so re-adding the connector
        resumes where it left off."""
        n0 = len(self._sources) + len(self._sinks)
        self._sources = [s for s in self._sources if s.name != name]
        self._sinks = [k for k in self._sinks if k.name != name]
        return len(self._sources) + len(self._sinks) < n0

    # ------------------------------------------------------------- driving
    def run_once(self, max_messages: int = 4096) -> Dict[str, int]:
        """One pass: drain every source, then deliver available messages to
        every sink (committing after put+flush). Returns per-connector
        record counts."""
        counts: Dict[str, int] = {}
        for s in self._sources:
            produced = 0
            # bounded drain: a source tailing an actively-growing file must
            # not starve the sinks (leftovers flow on the next pass)
            while produced < max_messages:
                records = s.connector.poll()
                if not records:
                    break
                for r in records:
                    self.broker.produce(r.topic, r.value, key=r.key)
                produced += len(records)
            counts[s.name] = produced
        for k in self._sinks:
            delivered = 0
            while True:
                msgs = k.consumer.poll(max_messages)
                if not msgs:
                    break
                for t in k.transforms:
                    msgs = [t(m) for m in msgs]
                k.connector.put(msgs)
                delivered += len(msgs)
            k.connector.flush()
            k.consumer.commit()
            counts[k.name] = delivered
        return counts

    def run_forever(self, poll_interval_s: float = 0.5,
                    should_stop: Optional[Callable[[], bool]] = None) -> None:
        while not (should_stop and should_stop()):
            self.run_once()
            time.sleep(poll_interval_s)
