"""Kafka-Connect REST API over `ConnectWorker`.

The reference manages its connectors entirely through Connect's REST
interface — `POST /connectors` with `{"name", "config"}` JSON, status
checks, and deletes (reference `infrastructure/kafka-connect/mongodb/
README.md:139-175`, `gcs/README.md:21-43`) — with connector behavior
chosen by the `connector.class` config key.  This server provides that
surface over the in-process runtime, mapping the reference's three
connector classes onto the native implementations:

  FileStreamSource (`file_stream_demo_standalone.properties:2-8`)
      config: file, topic, skip.header
  DocumentStoreSink  (the MongoDB digital-twin sink,
      `mongodb-connector-configmap.yaml:6-23`)
      config: topics, path, hoist.key.field (HoistField$Key SMT)
  ObjectStoreSink    (the GCS data-lake sink, `gcs/README.md:21-43`)
      config: topics, directory, flush.size

Endpoints:
  GET    /connectors                      → ["name", ...]
  POST   /connectors                      {"name","config"} → created entry
  GET    /connectors/{name}               → {"name","config","tasks"}
  GET    /connectors/{name}/config        → config
  GET    /connectors/{name}/status        → RUNNING + per-pass record count
  DELETE /connectors/{name}               → 204
  GET    /connector-plugins               → available classes

With a digital twin attached (`attach_twin`, iotml.twin), the surface
the reference queried MongoDB for is served here directly:
  GET    /twin                            → {"count", "cars": [ids...]}
  GET    /twin/{car_id}                   → latest state + rolling
                                            aggregates (404 unknown car)
  DELETE /twin/{car_id}                   → 204; tombstones the car out
                                            of the compacted changelog

A background thread drives `ConnectWorker.run_once()` continuously
(Connect's task threads); `pump_now()` runs one deterministic pass for
tests.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..core.schema import KSQL_CAR_SCHEMA
from ..utils.rest import RestError, RestServer
from .connectors import (DocumentStoreSink, FileStreamSource, HoistFieldKey,
                         ObjectStoreSink)
from .runtime import ConnectWorker

#: connector.class aliases accepted in configs (reference-style FQCNs too).
PLUGIN_ALIASES = {
    "filestreamsource": "FileStreamSource",
    "org.apache.kafka.connect.file.filestreamsourceconnector": "FileStreamSource",
    "documentstoresink": "DocumentStoreSink",
    "com.mongodb.kafka.connect.mongosinkconnector": "DocumentStoreSink",
    "objectstoresink": "ObjectStoreSink",
    "io.confluent.connect.gcs.gcssinkconnector": "ObjectStoreSink",
}


def _required(config: dict, key: str) -> str:
    v = config.get(key)
    if not v:
        raise RestError(400, f"missing required config {key!r}")
    return v


class ConnectServer(RestServer):
    """REST front-end + task-driver thread for one `ConnectWorker`."""

    def __init__(self, worker: ConnectWorker, host: str = "127.0.0.1",
                 port: int = 0, poll_interval_s: float = 0.05):
        super().__init__(host, port, name="iotml-connect")
        self.worker = worker
        self.poll_interval_s = poll_interval_s
        self._lock = threading.Lock()
        self._configs: Dict[str, dict] = {}
        self._kinds: Dict[str, str] = {}
        self._counts: Dict[str, int] = {}
        self._stop = threading.Event()
        self._driver: Optional[threading.Thread] = None
        self.twin = None  # iotml.twin.TwinService via attach_twin

        name = r"([^/]+)"
        self.route("GET", r"/connectors", self._list)
        self.route("POST", r"/connectors", self._create)
        self.route("GET", rf"/connectors/{name}", self._get)
        self.route("GET", rf"/connectors/{name}/config", self._config)
        self.route("GET", rf"/connectors/{name}/status", self._status)
        self.route("DELETE", rf"/connectors/{name}", self._delete)
        self.route("GET", r"/connector-plugins", lambda m, b: (
            200, [{"class": c, "type": "source" if "Source" in c else "sink"}
                  for c in sorted(set(PLUGIN_ALIASES.values()))]))

    # --------------------------------------------------------- lifecycle
    def start(self):
        from ..supervise.registry import register_thread

        super().start()
        self._driver = register_thread(threading.Thread(
            target=self._drive, daemon=True, name="iotml-connect-driver"))
        self._driver.start()
        return self

    def stop(self):
        self._stop.set()
        if self._driver is not None:
            self._driver.join(timeout=2)
        super().stop()

    def _drive(self):
        while not self._stop.wait(self.poll_interval_s):
            self.pump_now()

    def pump_now(self) -> Dict[str, int]:
        """One deterministic worker pass; updates per-connector counts."""
        with self._lock:
            counts = self.worker.run_once()
            for k, v in counts.items():
                self._counts[k] = self._counts.get(k, 0) + v
            return counts

    # ------------------------------------------------------ construction
    def _instantiate(self, name: str, config: dict) -> str:
        cls = PLUGIN_ALIASES.get(
            str(config.get("connector.class", "")).lower())
        if cls is None:
            raise RestError(400, f"unknown connector.class "
                            f"{config.get('connector.class')!r}")
        if cls == "FileStreamSource":
            src = FileStreamSource(
                path=_required(config, "file"),
                topic=_required(config, "topic"),
                skip_header=str(config.get("skip.header", "false")).lower()
                == "true")
            self.worker.add_source(name, src)
        elif cls == "DocumentStoreSink":
            topics = [t.strip() for t in _required(config, "topics").split(",")]
            sink = DocumentStoreSink(path=config.get("path"))
            transforms = []
            hoist = config.get("hoist.key.field")
            if hoist:
                transforms.append(HoistFieldKey(field=hoist))
            self.worker.add_sink(name, sink, topics, transforms=transforms)
        else:  # ObjectStoreSink
            topics = [t.strip() for t in _required(config, "topics").split(",")]
            sink = ObjectStoreSink(
                directory=_required(config, "directory"),
                schema=KSQL_CAR_SCHEMA,
                flush_size=int(config.get("flush.size", 1000)),
                framed=str(config.get("framed", "true")).lower() == "true")
            self.worker.add_sink(name, sink, topics)
        return cls

    # ----------------------------------------------------- registration
    def register_sink(self, name: str, connector, topics, kind: str,
                      config: Optional[dict] = None,
                      transforms=()) -> None:
        """Register an ALREADY-CONSTRUCTED sink under the server's own
        bookkeeping (config/kind/count, under the lock) — the
        programmatic twin of the REST create path, for hosts that wire a
        connector instance directly (cli/up.py's car-health twin) rather
        than describing one by config."""
        with self._lock:
            if name in self._configs:
                raise ValueError(f"connector {name} already exists")
            self.worker.add_sink(name, connector, topics,
                                 transforms=transforms)
            self._configs[name] = dict(config or {})
            self._kinds[name] = kind
            self._counts[name] = 0

    # ------------------------------------------------------------ twin
    def attach_twin(self, twin) -> None:
        """Serve a TwinService's table over this REST surface — the
        reference's 'query MongoDB for the car document' becomes a GET
        against the connect API the operators already talk to.  Reads
        go straight to the in-memory table (no lock: the table is
        mutated by one pump thread and read lock-free, same discipline
        as the broker's metric gauges)."""
        self.twin = twin
        self.route("GET", r"/twin", self._twin_list)
        self.route("GET", r"/twin/([^/]+)", self._twin_get)
        self.route("DELETE", r"/twin/([^/]+)", self._twin_delete)

    def attach_tsdb(self, broker, partition: int = 0) -> None:
        """Serve the telemetry TSDB over this REST surface (ISSUE 17):
        `GET /query?query=<expr>[&time_ms=]` for instant evaluation and
        `GET /query_range?query=&start_ms=&end_ms=[&step_ms=]` for
        stepped series — the Prometheus HTTP API's shape, answered from
        the `_IOTML_TSDB` log replay instead of a separate TSDB
        process."""
        self.tsdb_broker = broker
        self.tsdb_partition = partition
        self.route("GET", r"/query", self._tsdb_query)
        self.route("GET", r"/query_range", self._tsdb_query_range)

    def _tsdb_series(self, start_ms=None):
        from ..obs import tsdb

        return tsdb.read_series(self.tsdb_broker, start_ms=start_ms,
                                partition=self.tsdb_partition)

    def _tsdb_query(self, m, body):
        from ..obs import tsdb

        expr = body.get("query") or body.get("expr")
        if not expr:
            raise RestError(400, "missing 'query' parameter")
        at_ms = int(body["time_ms"]) if body.get("time_ms") else None
        try:
            result = tsdb.query(self._tsdb_series(), expr, at_ms=at_ms)
        except ValueError as e:
            raise RestError(400, f"bad query: {e}")
        return 200, {"status": "success", "data": result}

    def _tsdb_query_range(self, m, body):
        from ..obs import tsdb

        expr = body.get("query") or body.get("expr")
        if not expr:
            raise RestError(400, "missing 'query' parameter")
        try:
            start = int(body["start_ms"])
            end = int(body["end_ms"])
        except (KeyError, ValueError):
            raise RestError(400, "range query needs integer 'start_ms' "
                            "and 'end_ms'")
        step = int(body.get("step_ms") or 15_000)
        # replay from before the range start: rate()/increase() at the
        # first steps look back across the range boundary
        horizon = start - 2 * tsdb.DEFAULT_LOOKBACK_MS
        try:
            result = tsdb.query(self._tsdb_series(start_ms=horizon), expr,
                                start_ms=start, end_ms=end, step_ms=step)
        except ValueError as e:
            raise RestError(400, f"bad query: {e}")
        return 200, {"status": "success", "data": result}

    #: page-size ceiling for GET /twin: a 100k-car table must never emit
    #: a multi-megabyte id dump per poll (ISSUE 20) — callers page with
    #: limit/offset or take the count_only fast path
    TWIN_LIST_DEFAULT_LIMIT = 1000
    TWIN_LIST_MAX_LIMIT = 10_000

    def _twin_list(self, m, body):
        out = {"count": self.twin.count(),
               "rebuilt_from_changelog": self.twin.rebuilt_records}
        if str(body.get("count_only", "")).lower() in ("1", "true", "yes"):
            # fast path: len() of the table, no id list materialised
            return 200, out
        try:
            limit = int(body.get("limit", self.TWIN_LIST_DEFAULT_LIMIT))
            offset = int(body.get("offset", 0))
        except (TypeError, ValueError):
            raise RestError(400, "limit/offset must be integers")
        if limit < 0 or offset < 0:
            raise RestError(400, "limit/offset must be >= 0")
        limit = min(limit, self.TWIN_LIST_MAX_LIMIT)
        cars = self.twin.cars()
        page = cars[offset:offset + limit]
        out["cars"] = page
        out["offset"] = offset
        out["limit"] = limit
        # the resume cursor: None signals the last page, so pollers
        # walk `next_offset` until it nulls instead of guessing from
        # page fill (a filtered backend may return short pages)
        nxt = offset + len(page)
        out["next_offset"] = nxt if nxt < len(cars) else None
        return 200, out

    def _twin_get(self, m, body):
        doc = self.twin.get(m.group(1))
        if doc is None:
            raise RestError(404, f"no twin for car {m.group(1)!r}")
        return 200, doc

    def _twin_delete(self, m, body):
        if not self.twin.retire(m.group(1)):
            raise RestError(404, f"no twin for car {m.group(1)!r}")
        return 204, {}

    # ------------------------------------------------------------- routes
    def _list(self, m, body):
        with self._lock:
            return 200, sorted(self._configs)

    def _create(self, m, body):
        name = body.get("name")
        config = body.get("config", {})
        if not name:
            raise RestError(400, "missing connector name")
        with self._lock:
            if name in self._configs:
                # Connect's 409 on duplicate create
                raise RestError(409, f"connector {name} already exists")
            kind = self._instantiate(name, config)
            self._configs[name] = dict(config)
            self._kinds[name] = kind
            self._counts[name] = 0
        return 201, {"name": name, "config": config,
                     "tasks": [{"connector": name, "task": 0}]}

    def _entry(self, name: str) -> dict:
        if name not in self._configs:
            raise RestError(404, f"connector {name} not found")
        return {"name": name, "config": self._configs[name],
                "type": "source" if "Source" in self._kinds[name] else "sink",
                "tasks": [{"connector": name, "task": 0}]}

    def _get(self, m, body):
        with self._lock:
            return 200, self._entry(m.group(1))

    def _config(self, m, body):
        with self._lock:
            self._entry(m.group(1))
            return 200, self._configs[m.group(1)]

    def _status(self, m, body):
        with self._lock:
            entry = self._entry(m.group(1))
            return 200, {
                "name": entry["name"],
                "connector": {"state": "RUNNING", "worker_id": self.url},
                "tasks": [{"id": 0, "state": "RUNNING",
                           "records_processed": self._counts[m.group(1)]}],
                "type": entry["type"],
            }

    def _delete(self, m, body):
        name = m.group(1)
        with self._lock:
            self._entry(name)
            self.worker.remove(name)
            del self._configs[name]
            del self._kinds[name]
            self._counts.pop(name, None)
        return 204, {}
