from .schema import (  # noqa: F401
    CAR_SCHEMA,
    KSQL_CAR_SCHEMA,
    Field,
    RecordSchema,
    SENSOR_FIELDS,
)
from .normalize import Normalizer, CAR_NORMALIZER  # noqa: F401
