"""Per-field affine normalization as a single vectorized jax op.

The reference normalizes field-by-field in TF graph code
(cardata-v3.py:105-148): each sensor is affinely mapped from a hand-picked
(lo, hi) range to (-1, 1), and four fields the authors never calibrated
(coolant_temp, intake_air_flow_speed, battery_voltage, current_draw) are
hard-zeroed ("TODO" in the reference).

TPU-first design: instead of 18 scalar ops, normalization is one fused
``x * scale + shift`` with a zero-mask — a single VPU-friendly elementwise
kernel XLA fuses into whatever consumes it.  The constants are derived from
the schema's field table, so producer- and KSQL-variant records normalize
identically.

``parity=True`` (default) reproduces the reference exactly, including the
zeroed fields.  ``parity=False`` normalizes the four TODO fields too, using
ranges estimated from the reference's own 10k-row CSV fixture.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .schema import RecordSchema, CAR_SCHEMA

# Calibrated ranges for the four fields the reference leaves as TODO, taken
# from min/max of reference testdata/car-sensor-data.csv (rounded out).
_FIXED_RANGES = {
    "coolant_temp": (15.0, 60.0),
    "intake_air_flow_speed": (0.0, 170.0),
    "battery_voltage": (190.0, 255.0),
    "current_draw": (0.0, 40.0),
}


class Normalizer:
    """Precomputed scale/shift vectors for one record schema.

    normalize(x) == (x - lo) / (hi - lo) * 2 - 1, per field, with zeroed
    fields masked to 0.  Exposed as ``scale``/``shift``/``mask`` numpy
    constants so they can be baked into jitted programs or Pallas kernels.
    """

    def __init__(self, schema: RecordSchema = CAR_SCHEMA, parity: bool = True,
                 dtype=jnp.float32):
        fields = schema.sensor_fields
        n = len(fields)
        scale = np.zeros((n,), np.float64)
        shift = np.zeros((n,), np.float64)
        mask = np.zeros((n,), np.float64)
        for i, f in enumerate(fields):
            base = f.name.lower()
            rng = f.norm
            if rng is None and not parity:
                rng = _FIXED_RANGES.get(base)
            if rng is None:
                continue  # masked to zero
            lo, hi = rng
            scale[i] = 2.0 / (hi - lo)
            shift[i] = -2.0 * lo / (hi - lo) - 1.0
            mask[i] = 1.0
        self.schema = schema
        self.dtype = dtype
        # HOST numpy constants, not device arrays: the default normalizer
        # is built at import time, and materializing device buffers there
        # initializes the XLA backend — which must not happen before
        # jax.distributed.initialize() on multi-host.  jnp.asarray inside
        # __call__ constant-folds under jit just the same.
        np_dtype = np.dtype(jnp.dtype(dtype).name)
        self.scale = scale.astype(np_dtype)
        self.shift = shift.astype(np_dtype)
        self.mask = mask.astype(np_dtype)

    def __call__(self, x):
        """Normalize a [..., num_sensors] array."""
        x = jnp.asarray(x, self.dtype)
        return (x * jnp.asarray(self.scale) + jnp.asarray(self.shift)) \
            * jnp.asarray(self.mask)

    def np(self, x: np.ndarray) -> np.ndarray:
        """Host-side numpy twin (for data-plane preprocessing off-device)."""
        x = np.asarray(x, np.float64)
        out = (x * np.asarray(self.scale, np.float64)
               + np.asarray(self.shift, np.float64)) * np.asarray(self.mask, np.float64)
        return out.astype(np.dtype(self.dtype.__name__ if isinstance(self.dtype, type)
                                   else jnp.dtype(self.dtype).name))


class IdentityNormalizer:
    """Raw-columns pass-through for DEVICE-side normalization (ISSUE 15).

    When the affine map is folded into the jitted train step
    (``parallel.data_parallel.ShardedTrainer(normalizer=...)``), the host
    pipeline must ship the decoder's raw float32 columns untouched — this
    is the batcher-side half of that contract.  ``np`` is a cast-only
    view (no arithmetic, no copy when already float32): the last
    per-element host work disappears, exactly what the multichip data
    plane wants.  The device-side fold uses the REAL normalizer's
    ``scale``/``shift``/``mask`` constants, so the two halves cannot
    drift."""

    def __init__(self, dtype=jnp.float32):
        self.dtype = dtype
        self._np_dtype = np.dtype(jnp.dtype(dtype).name)

    def __call__(self, x):
        return jnp.asarray(x, self.dtype)

    def np(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(x).astype(self._np_dtype, copy=False)


#: The one raw-columns instance the streaming pipelines share.
RAW_COLUMNS = IdentityNormalizer()

# The default normalizer used across the framework (reference parity mode).
CAR_NORMALIZER = Normalizer(CAR_SCHEMA, parity=True)

# Full normalization: the four reference-TODO fields carry signal instead
# of being zeroed.  This is the DETECTION-grade normalizer — the battery
# failure mode's entire signature (voltage sag + current spike) lives in
# two fields the parity normalizer masks to 0, so a parity-normalized
# model is structurally blind to it (measured: battery faults move
# aggregate reconstruction MSE by only ~2%).  The live services accept
# either; the reference-contract CLIs stay on parity.
FULL_NORMALIZER = Normalizer(CAR_SCHEMA, parity=False)

normalize = jax.jit(lambda x: CAR_NORMALIZER(x))
