"""Typed record schemas for the car-sensor domain.

The reference system has two Avro schemas for the same logical record
(see reference `testdata/cardata-v1.avsc` and
`python-scripts/AUTOENCODER-TensorFlow-IO-Kafka/cardata-v1.avsc`):

1. the *producer* schema — 18 required fields, lower_snake_case, float/int
   primitives — used by the device fleet when publishing over MQTT, and
2. the *KSQL-derived* schema — the 18 fields renamed to UPPER_CASE (with the
   KSQL quirk that `tire_pressure_1_1 → TIRE_PRESSURE11` etc.), widened to
   nullable `["null","double"]` / `["null","int"]` unions, plus a 19th field
   `FAILURE_OCCURRED: ["null","string"]` (the anomaly label) — this is what
   the ML layer actually consumes.

Rather than shipping two JSON files and a generic Avro parser as the source
of truth, we define one field table and *derive* both schema variants (and
their Avro JSON) from it.  The Avro JSON emitted here is wire-compatible
with the reference schemas.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

import numpy as np

# Avro primitive → numpy dtype for the columnar decode path.
_AVRO_NP = {
    "float": np.float32,
    "double": np.float64,
    "int": np.int32,
    "long": np.int64,
    "boolean": np.bool_,
    "string": object,
    "bytes": object,
}


@dataclasses.dataclass(frozen=True)
class Field:
    """One field of a record schema.

    ``norm`` is the affine normalization range (lo, hi) mapping to (-1, 1);
    ``None`` means the reference zeroes the field out (its normalize_fn TODOs,
    reference cardata-v3.py:108-124) — we preserve that for parity, and expose
    a corrected path behind a flag in `core.normalize`.
    """

    name: str
    avro_type: str  # primitive name: float/double/int/string/...
    nullable: bool = False
    doc: str = ""
    norm: Optional[tuple] = None

    @property
    def np_dtype(self):
        return _AVRO_NP[self.avro_type]

    def avro_json(self) -> dict:
        t = [self.avro_type] if not self.nullable else ["null", self.avro_type]
        out = {"name": self.name, "type": t[0] if len(t) == 1 else t}
        if self.nullable:
            out["default"] = None
        if self.doc:
            out["doc"] = self.doc
        return out


@dataclasses.dataclass(frozen=True)
class RecordSchema:
    """An Avro record schema plus framework metadata."""

    name: str
    namespace: str
    fields: tuple  # tuple[Field, ...]
    label_field: Optional[str] = None  # name of the anomaly-label field, if any
    #: non-sensor payload fields (e.g. the v2 writer's REGION cohort
    #: tag): carried on the wire, excluded from the model's input
    #: matrix exactly like the label
    meta_fields: tuple = ()

    def avro_json(self) -> str:
        return json.dumps(
            {
                "type": "record",
                "name": self.name,
                "namespace": self.namespace,
                "fields": [f.avro_json() for f in self.fields],
            },
            indent=2,
        )

    @property
    def field_names(self):
        return tuple(f.name for f in self.fields)

    @property
    def sensor_fields(self):
        """Fields that feed the model (everything except the label
        and any meta fields)."""
        return tuple(f for f in self.fields
                     if f.name != self.label_field
                     and f.name not in self.meta_fields)

    @property
    def num_sensors(self) -> int:
        return len(self.sensor_fields)

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)


def _ksql_name(name: str) -> str:
    """KSQL column naming as observed in the reference KSQL-derived schema:
    upper-case, and single digits separated by underscores are collapsed
    (``tire_pressure_1_1`` → ``TIRE_PRESSURE11``,
    ``accelerometer_1_1_value`` → ``ACCELEROMETER11_VALUE``)."""
    parts = name.split("_")
    out, digits = [], []
    for p in parts:
        if len(p) == 1 and p.isdigit():
            digits.append(p)
        else:
            if digits:
                out[-1] = out[-1] + "".join(digits)
                digits = []
            out.append(p)
    if digits:
        out[-1] = out[-1] + "".join(digits)
    return "_".join(out).upper()


# The single source of truth: 18 sensor fields, their Avro primitive type in
# the *producer* schema, and the normalization spec from the reference
# normalize_fn (cardata-v3.py:105-148).  norm=None ⇒ zeroed (reference TODO).
SENSOR_FIELDS = (
    Field("coolant_temp", "float", doc="battery/engine coolant temperature in degC", norm=None),
    Field("intake_air_temp", "float", doc="air intake temperature in degC", norm=(15.0, 40.0)),
    Field("intake_air_flow_speed", "float", doc="air intake mass g/s", norm=None),
    Field("battery_percentage", "float", doc="battery cell total percentage left", norm=(0.0, 100.0)),
    Field("battery_voltage", "float", doc="battery pack voltage in mV", norm=None),
    Field("current_draw", "float", doc="current in A drawn from the battery", norm=None),
    Field("speed", "float", doc="vehicle speed in m/s", norm=(0.0, 50.0)),
    Field("engine_vibration_amplitude", "float", doc="engine vibration in mV", norm=(0.0, 7500.0)),
    Field("throttle_pos", "float", doc="throttle position [0..1]", norm=(0.0, 1.0)),
    Field("tire_pressure_1_1", "int", doc="tire pressure psi front left", norm=(20.0, 35.0)),
    Field("tire_pressure_1_2", "int", doc="tire pressure psi front right", norm=(20.0, 35.0)),
    Field("tire_pressure_2_1", "int", doc="tire pressure psi back left", norm=(20.0, 35.0)),
    Field("tire_pressure_2_2", "int", doc="tire pressure psi back right", norm=(20.0, 35.0)),
    Field("accelerometer_1_1_value", "float", doc="accel m/s^2 front left", norm=(0.0, 7.0)),
    Field("accelerometer_1_2_value", "float", doc="accel m/s^2 front right", norm=(0.0, 7.0)),
    Field("accelerometer_2_1_value", "float", doc="accel m/s^2 back left", norm=(0.0, 7.0)),
    Field("accelerometer_2_2_value", "float", doc="accel m/s^2 back right", norm=(0.0, 7.0)),
    Field("control_unit_firmware", "int", doc="firmware version [1000|2000]", norm=(1000.0, 2000.0)),
)

# Producer-side schema: what devices publish over MQTT (18 required fields).
CAR_SCHEMA = RecordSchema(
    name="CarData",
    namespace="com.hivemq.avro",
    fields=SENSOR_FIELDS,
)

# KSQL-derived schema: what the ML layer consumes (19 nullable upper-case
# fields; floats widened to double; label appended).
KSQL_CAR_SCHEMA = RecordSchema(
    name="KsqlDataSourceSchema",
    namespace="io.confluent.ksql.avro_schemas",
    fields=tuple(
        [
            Field(
                _ksql_name(f.name),
                "double" if f.avro_type == "float" else f.avro_type,
                nullable=True,
                norm=f.norm,
            )
            for f in SENSOR_FIELDS
        ]
        + [Field("FAILURE_OCCURRED", "string", nullable=True)]
    ),
    label_field="FAILURE_OCCURRED",
)

# Writer-schema v2: the schema-evolution case a live fleet actually
# produces — a new optional field (REGION, the regional-cohort tag)
# added with a null default.  KSQL-style schema regeneration emits the
# new column BEFORE the label it appends last, so a v1 reader that
# decoded v2 bytes positionally would read REGION's union branch as
# the label — exactly the mixed-version failure `ops.avro
# .ResolvingCodec` resolves by name instead (Avro resolution rules:
# reader fields match writer fields by NAME; reader-missing writer
# fields are skipped, writer-missing reader fields take their
# default).  v2 is a WRITER schema: the ML layer always reads through
# the v1 reader projection, so REGION never reaches the model input.
KSQL_CAR_SCHEMA_V2 = RecordSchema(
    name="KsqlDataSourceSchema",
    namespace="io.confluent.ksql.avro_schemas",
    fields=tuple(
        list(KSQL_CAR_SCHEMA.fields[:-1])
        + [Field("REGION", "string", nullable=True,
                 doc="fleet cohort region (added in writer schema v2)"),
           Field("FAILURE_OCCURRED", "string", nullable=True)]
    ),
    label_field="FAILURE_OCCURRED",
    meta_fields=("REGION",),
)

#: the evolved writer's frame id.  NOT 2: Confluent frame ids are
#: registry-scoped, and the in-process SchemaRegistry allocates small
#: ints from 1 — a CSAS output legitimately registered at id 2 must
#: not be mistaken for (and mis-decoded as) car-schema v2.  The
#: framework's evolved car schemas live in a reserved band the
#: registry never allocates into (`stream.registry.RESERVED_ID_BASE`).
CAR_SCHEMA_V2_ID = 1002

#: Confluent-frame schema id → writer schema, for readers resolving a
#: mixed-version topic (`ops.framing` carries the id on every message).
WRITER_SCHEMAS = {1: KSQL_CAR_SCHEMA, CAR_SCHEMA_V2_ID: KSQL_CAR_SCHEMA_V2}

#: human-facing writer version → (schema, frame id) — what
#: ``JsonToAvro(schema_version=2)`` and the fleet's schema-mix
#: condition write with
WRITER_VERSIONS = {1: (KSQL_CAR_SCHEMA, 1),
                   2: (KSQL_CAR_SCHEMA_V2, CAR_SCHEMA_V2_ID)}

# Offline CSV fixture layout (reference testdata/car-sensor-data.csv):
# header `time,car,<18 sensor columns in producer order>`.
CSV_COLUMNS = ("time", "car") + CAR_SCHEMA.field_names
