// iotml native stream engine: columnar Avro codec + Confluent framing.
//
// TPU-native replacement for the C++ half of the reference's data plane
// (the tensorflow_io.kafka ops: decode_avro / KafkaDataset framing strip —
// reference cardata-v3.py:46-74).  Python hands a contiguous blob of
// messages + offsets; we decode straight into caller-owned columnar
// buffers (doubles, row-major [n_rows x n_numeric]) plus a fixed-stride
// label column — the exact layout `jax.device_put` wants, no Python-object
// round trip.
//
// Schema support is what the car/KSQL schemas need (SURVEY §2.4): the
// primitives float/double/int/long/boolean/string and the nullable
// 2-branch union ["null", T].  Schemas arrive pre-compiled as a type/flag
// descriptor array, so the inner loop is branch-light and allocation-free.
//
// Build: make -C iotml/cpp   (g++ -O3 -shared; no external deps)

#include <cstdint>
#include <cstring>

#include "utf8_check.h"

namespace {

enum FieldType : int8_t {
  F_FLOAT = 0,
  F_DOUBLE = 1,
  F_INT = 2,
  F_LONG = 3,
  F_STRING = 4,
  F_BOOLEAN = 5,
};

// Avro zigzag varint. Returns new position, or -1 on truncation.
// `overlong` (optional) reports a non-minimal encoding — a multi-byte
// varint whose final byte is 0x00 encodes a value a shorter varint could
// carry; the canonical re-encode would differ byte-wise, which strict
// (pass-through) callers must reject.
inline int64_t read_varint(const uint8_t* buf, int64_t pos, int64_t end,
                           int64_t* out, bool* overlong = nullptr) {
  uint64_t acc = 0;
  int shift = 0;
  int64_t start = pos;
  while (pos < end) {
    uint8_t b = buf[pos++];
    // 10th byte: only its lowest bit fits in 64 (the Avro long limit).
    // Without this check the high payload bits would shift out silently
    // and a >64-bit varint would validate with a truncated value.
    if (shift == 63 && (b & 0x7E)) return -1;
    acc |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
      if (overlong) *overlong = (b == 0x00 && pos - start > 1);
      return pos;
    }
    shift += 7;
    if (shift > 63) return -1;
  }
  return -1;
}

inline int64_t write_varint(uint8_t* buf, int64_t pos, int64_t v) {
  uint64_t z = (static_cast<uint64_t>(v) << 1) ^
               static_cast<uint64_t>(v >> 63);
  while (true) {
    uint8_t b = z & 0x7F;
    z >>= 7;
    if (z) {
      buf[pos++] = b | 0x80;
    } else {
      buf[pos++] = b;
      return pos;
    }
  }
}

}  // namespace

extern "C" {

// Decode n_msgs Avro records.
//   blob/offsets: messages live at blob[offsets[i] .. offsets[i+1])
//   types/nullable: per-field descriptors, n_fields entries
//   strip: bytes to skip at each message head (5 for Confluent framing)
//   out_numeric: [n_msgs x n_numeric] row-major doubles (numeric fields in
//                schema order; string fields excluded). Nulls decode as 0.
//   out_labels/label_stride: every string field's bytes are copied (NUL-
//                terminated, truncated to stride-1) into consecutive slots:
//                row-major [n_msgs x n_strings] with the given stride.
// Returns number of rows decoded; a malformed message stops decoding and
// returns the negative of (rows_ok + 1) so callers can pinpoint it.
static int64_t decode_impl(const uint8_t* blob,
                           const int64_t* offsets, int64_t n_msgs,
                           const int8_t* types,
                           const uint8_t* nullable, int64_t n_fields,
                           int64_t strip, double* out_numeric,
                           char* out_labels, int64_t label_stride,
                           uint8_t* out_nulls, bool strict) {
  // out_nulls: optional [n_msgs * n_fields] bitmap (1 = the nullable
  // union chose the null branch).  The columnar outputs cannot represent
  // null distinctly (numeric null -> 0.0, string null -> ""), so callers
  // needing exact null semantics check the bitmap and fall back.
  //
  // strict mode is the pass-through/count validation gate: it rejects
  // anything the Python codec would reject (invalid UTF-8 in a string,
  // union branch outside {0,1}) OR would silently CANONICALIZE on a
  // decode→re-encode round trip (trailing bytes after the record,
  // non-minimal varints) — exactly the conditions under which forwarding
  // the original bytes unchanged would diverge from the per-row path.
  //
  // Precompute per-field output slot (numeric col or string col).
  int64_t n_numeric = 0, n_strings = 0;
  for (int64_t f = 0; f < n_fields; ++f) {
    if (types[f] == F_STRING) ++n_strings; else ++n_numeric;
  }
  for (int64_t i = 0; i < n_msgs; ++i) {
    const uint8_t* buf = blob;
    int64_t pos = offsets[i] + strip;
    int64_t end = offsets[i + 1];
    if (pos > end) return -(i + 1);
    double* num_row = out_numeric + i * n_numeric;
    char* lab_row = out_labels + i * n_strings * label_stride;
    int64_t ncol = 0, scol = 0;
    for (int64_t f = 0; f < n_fields; ++f) {
      bool is_null = false;
      if (nullable[f]) {
        int64_t branch;
        bool overlong = false;
        pos = read_varint(buf, pos, end, &branch, &overlong);
        if (pos < 0) return -(i + 1);
        if (strict && (overlong || (branch != 0 && branch != 1)))
          return -(i + 1);
        is_null = (branch == 0);
      }
      if (out_nulls) out_nulls[i * n_fields + f] = is_null ? 1 : 0;
      switch (types[f]) {
        case F_FLOAT: {
          double v = 0.0;
          if (!is_null) {
            if (pos + 4 > end) return -(i + 1);
            float fv;
            std::memcpy(&fv, buf + pos, 4);
            pos += 4;
            v = fv;
          }
          num_row[ncol++] = v;
          break;
        }
        case F_DOUBLE: {
          double v = 0.0;
          if (!is_null) {
            if (pos + 8 > end) return -(i + 1);
            std::memcpy(&v, buf + pos, 8);
            pos += 8;
          }
          num_row[ncol++] = v;
          break;
        }
        case F_INT:
        case F_LONG: {
          int64_t v = 0;
          if (!is_null) {
            bool overlong = false;
            pos = read_varint(buf, pos, end, &v, &overlong);
            if (pos < 0 || (strict && overlong)) return -(i + 1);
          }
          num_row[ncol++] = static_cast<double>(v);
          break;
        }
        case F_BOOLEAN: {
          double v = 0.0;
          if (!is_null) {
            if (pos + 1 > end) return -(i + 1);
            v = buf[pos++] ? 1.0 : 0.0;
          }
          num_row[ncol++] = v;
          break;
        }
        case F_STRING: {
          char* slot = lab_row + scol * label_stride;
          ++scol;
          if (is_null) {
            slot[0] = '\0';
            break;
          }
          int64_t len;
          bool overlong = false;
          pos = read_varint(buf, pos, end, &len, &overlong);
          if (pos < 0 || len < 0 || pos + len > end) return -(i + 1);
          if (strict && (overlong ||
                         !iotml::valid_utf8(buf + pos, buf + pos + len)))
            return -(i + 1);
          int64_t copy = len < label_stride - 1 ? len : label_stride - 1;
          std::memcpy(slot, buf + pos, copy);
          slot[copy] = '\0';
          pos += len;
          break;
        }
        default:
          return -(i + 1);
      }
    }
    if (strict && pos != end) return -(i + 1);  // trailing bytes
  }
  return n_msgs;
}

int64_t iotml_decode_batch_nulls(const uint8_t* blob,
                                 const int64_t* offsets, int64_t n_msgs,
                                 const int8_t* types,
                                 const uint8_t* nullable, int64_t n_fields,
                                 int64_t strip, double* out_numeric,
                                 char* out_labels, int64_t label_stride,
                                 uint8_t* out_nulls) {
  return decode_impl(blob, offsets, n_msgs, types, nullable, n_fields,
                     strip, out_numeric, out_labels, label_stride,
                     out_nulls, /*strict=*/false);
}

int64_t iotml_decode_batch(const uint8_t* blob, const int64_t* offsets,
                           int64_t n_msgs, const int8_t* types,
                           const uint8_t* nullable, int64_t n_fields,
                           int64_t strip, double* out_numeric,
                           char* out_labels, int64_t label_stride) {
  return decode_impl(blob, offsets, n_msgs, types, nullable, n_fields,
                     strip, out_numeric, out_labels, label_stride, nullptr,
                     /*strict=*/false);
}

// Strict validation decode for the pass-through/count fast paths (see
// decode_impl): rejects what the Python codec rejects or would
// canonicalize, so "validated" means "forwarding the original bytes is
// byte-identical to decode→re-encode".
int64_t iotml_decode_batch_strict(const uint8_t* blob,
                                  const int64_t* offsets, int64_t n_msgs,
                                  const int8_t* types,
                                  const uint8_t* nullable, int64_t n_fields,
                                  int64_t strip, double* out_numeric,
                                  char* out_labels, int64_t label_stride) {
  return decode_impl(blob, offsets, n_msgs, types, nullable, n_fields,
                     strip, out_numeric, out_labels, label_stride, nullptr,
                     /*strict=*/true);
}

// Encode n_msgs records from columnar input (the decode layout in reverse).
//   out: caller-allocated; out_offsets[n_msgs+1] filled with message bounds.
//   frame_schema_id: >= 0 writes the Confluent 5-byte header (magic 0 +
//                big-endian id); < 0 emits bare Avro.
// Returns total bytes written, or -1 if out_capacity would overflow.
int64_t iotml_encode_batch_nulls(const double* numeric, const char* labels,
                                 int64_t label_stride, int64_t n_msgs,
                                 const int8_t* types, const uint8_t* nullable,
                                 int64_t n_fields, int64_t frame_schema_id,
                                 uint8_t* out, int64_t out_capacity,
                                 int64_t* out_offsets,
                                 const uint8_t* nulls) {
  int64_t n_numeric = 0, n_strings = 0;
  for (int64_t f = 0; f < n_fields; ++f) {
    if (types[f] == F_STRING) ++n_strings; else ++n_numeric;
  }
  int64_t pos = 0;
  for (int64_t i = 0; i < n_msgs; ++i) {
    out_offsets[i] = pos;
    // worst case per row: 5 frame + fields * (10 varint + 8 payload) + strings
    if (pos + 5 + n_fields * 20 + n_strings * label_stride > out_capacity)
      return -1;
    if (frame_schema_id >= 0) {
      out[pos++] = 0;
      uint32_t id = static_cast<uint32_t>(frame_schema_id);
      out[pos++] = (id >> 24) & 0xFF;
      out[pos++] = (id >> 16) & 0xFF;
      out[pos++] = (id >> 8) & 0xFF;
      out[pos++] = id & 0xFF;
    }
    const double* num_row = numeric + i * n_numeric;
    const char* lab_row = labels + i * n_strings * label_stride;
    const uint8_t* null_row = nulls ? nulls + i * n_fields : nullptr;
    int64_t ncol = 0, scol = 0;
    for (int64_t f = 0; f < n_fields; ++f) {
      if (null_row && null_row[f]) {
        // null value: branch 0 of the ["null", T] union, no payload.
        // A null in a non-nullable field has no encoding — reject so the
        // caller's Python path decides (it raises there too).
        if (!nullable[f]) return -1;
        pos = write_varint(out, pos, 0);
        if (types[f] == F_STRING) ++scol; else ++ncol;
        continue;
      }
      if (nullable[f]) pos = write_varint(out, pos, 1);  // branch 1 = value
      switch (types[f]) {
        case F_FLOAT: {
          float fv = static_cast<float>(num_row[ncol++]);
          std::memcpy(out + pos, &fv, 4);
          pos += 4;
          break;
        }
        case F_DOUBLE: {
          double v = num_row[ncol++];
          std::memcpy(out + pos, &v, 8);
          pos += 8;
          break;
        }
        case F_INT:
        case F_LONG:
          pos = write_varint(out, pos,
                             static_cast<int64_t>(num_row[ncol++]));
          break;
        case F_BOOLEAN:
          out[pos++] = num_row[ncol++] != 0.0 ? 1 : 0;
          break;
        case F_STRING: {
          const char* s = lab_row + scol * label_stride;
          ++scol;
          int64_t len = 0;
          while (len < label_stride && s[len]) ++len;
          pos = write_varint(out, pos, len);
          std::memcpy(out + pos, s, len);
          pos += len;
          break;
        }
        default:
          return -1;
      }
    }
  }
  out_offsets[n_msgs] = pos;
  return pos;
}

int64_t iotml_encode_batch(const double* numeric, const char* labels,
                           int64_t label_stride, int64_t n_msgs,
                           const int8_t* types, const uint8_t* nullable,
                           int64_t n_fields, int64_t frame_schema_id,
                           uint8_t* out, int64_t out_capacity,
                           int64_t* out_offsets) {
  return iotml_encode_batch_nulls(numeric, labels, label_stride, n_msgs,
                                  types, nullable, n_fields, frame_schema_id,
                                  out, out_capacity, out_offsets, nullptr);
}

// Bumped whenever the C ABI grows; stream/native.py rebuilds stale .so files.
// ABI history: 1 = avro batch codec; 2 = + kafka wire client;
// 3 = + iotml_decode_batch_nulls (null-bitmap decode);
// 4 = + iotml_json_decode_batch (batch JSON → columnar, json_engine.cc)
//     + iotml_encode_batch_nulls (null-bitmap encode);
// 5 = + iotml_format_rows_f32/f64 (batch np.array2string, fmt_engine.cc);
// 6 = + tombstone round-trip (produce_nulls / staged_value_nulls);
// 7 = + iotml_frames_decode_columnar (store-frame columnar decoder,
//       frame_engine.cc) + iotml_kafka_set_pinned_id_limit (pinned
//       writer-id guard on the fused fetch_decode paths);
// 8 = + write-path frame codec (frame_engine.cc:
//       iotml_frames_encode_columnar / iotml_frames_encode_values /
//       iotml_frames_restamp / iotml_frames_validate) +
//       iotml_kafka_produce_raw (RAW_PRODUCE wire extension)
int64_t iotml_engine_version() { return 9; }

}  // extern "C"
