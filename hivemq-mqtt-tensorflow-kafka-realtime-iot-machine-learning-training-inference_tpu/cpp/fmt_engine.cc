// iotml native stream engine: batch np.array2string row formatter.
//
// The serve path's payload contract is np.array2string(row) — the exact
// bytes the reference's OutputCallback produced (cardata-v3.py:247) — and
// profiling shows formatting IS the serve bottleneck (~90% of a drain's
// wall, serve/fastfmt.py).  fastfmt made it 2× by driving dragon4
// per-element from Python; this engine formats the whole drain in one
// call: per-element shortest-repr + cutoff formatting via std::to_chars,
// then numpy's exact padding/wrap/bracket assembly, all in C++.
//
// Byte parity relies on two identities (pinned by tests/test_fastfmt.py
// against numpy on adversarial inputs):
//   1. dragon4(unique=True, precision=8, fractional=True) equals the
//      shortest round-trip representation when that fits in 8 fractional
//      digits — to_chars's shortest form, same closest-among-shortest
//      digit selection;
//   2. when the shortest form needs more than 8 fractional digits,
//      dragon4's cutoff rounding equals the correctly-rounded fixed
//      8-fractional-digit conversion of the EXACT binary value (both
//      round-to-nearest, ties-to-even over the exact value) — to_chars
//      fixed form on the double-widened element.
// trim='.' semantics: trailing zeros trimmed, the trailing point kept
// ("1." for 1.0), matching numpy's positional float repr.
//
// Eligibility mirrors fastfmt.format_rows exactly (finite rows, no
// exponential trigger: max|x| < 1e8, nonzero min|x| >= 1e-4,
// max/min <= 1000, all compared in float64): ineligible rows are flagged
// and the Python side formats them through np.array2string itself.

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

// GCC 10's libstdc++ ships integer std::to_chars only — the
// floating-point overloads (P0067R5) arrived in GCC 11.  The engine's
// contract needs exactly two conversions: the shortest round-trip form
// and the correctly-rounded fixed 8-fractional-digit form.  Where FP
// to_chars exists we use it; otherwise a portable snprintf-based
// fallback supplies the same bytes: shortest = the smallest %.*e
// precision that round-trips through strtof/strtod (correct rounding at
// the minimal precision selects the same closest-among-shortest digits
// to_chars does), fixed-8 = %.8f (glibc printf is correctly rounded).
// Parity across both paths is pinned by tests/test_fastfmt.py.
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
#define IOTML_HAVE_FP_TO_CHARS 1
#else
#define IOTML_HAVE_FP_TO_CHARS 0
#endif

namespace {

#if IOTML_HAVE_FP_TO_CHARS

template <typename T>
int shortest_chars(T value, char* buf, int cap) {
  auto res = std::to_chars(buf, buf + cap, value);
  return static_cast<int>(res.ptr - buf);
}

int fixed8_chars(double value, char* buf, int cap) {
  auto res = std::to_chars(buf, buf + cap, value,
                           std::chars_format::fixed, 8);
  return static_cast<int>(res.ptr - buf);
}

#else  // GCC 10 fallback: snprintf + round-trip minimal precision

inline bool roundtrips(const char* buf, float value) {
  return std::strtof(buf, nullptr) == value;
}
inline bool roundtrips(const char* buf, double value) {
  return std::strtod(buf, nullptr) == value;
}

template <typename T>
int shortest_chars(T value, char* buf, int cap) {
  // max_digits10: 9 (float) / 17 (double) always round-trips
  const int max_prec = sizeof(T) == 4 ? 9 : 17;
  int n = 0;
  for (int prec = 1; prec <= max_prec; ++prec) {
    // %.*e prints `prec` significant digits (1 before the point,
    // prec-1 after): the scientific form parses identically to
    // to_chars general output in format_elem's digit/exponent split
    n = std::snprintf(buf, cap, "%.*e", prec - 1, double(value));
    if (roundtrips(buf, value)) break;
  }
  // canonicalize to the to_chars shape the parser expects: strip a
  // zero-padded fraction ("1.000000e+01" never appears at minimal
  // precision) and the exponent's leading zeros/'+' don't matter to
  // the parser, so the snprintf form is accepted as-is.
  return n;
}

int fixed8_chars(double value, char* buf, int cap) {
  return std::snprintf(buf, cap, "%.8f", value);
}

#endif  // IOTML_HAVE_FP_TO_CHARS

constexpr int kLinewidth = 75;
constexpr int kElemW = kLinewidth - 1;  // minus max(len(sep.rstrip()), ']')

// Format one element into `word` (no padding): sign + integer digits +
// '.' + fractional digits (possibly none), trim='.' applied.  Returns
// length, and the dot position via *dot (index of '.').  `shortest` is
// the to_chars shortest form of the value at ITS OWN precision (f32
// elements use float shortest — dragon4 runs at array dtype precision);
// `exact` is the element widened to double for the cutoff conversion.
template <typename T>
int format_elem(T value, double exact, char* word, int* dot) {
  char buf[64];
  int n = shortest_chars(value, buf, sizeof buf);
  buf[n] = '\0';
  // parse shortest form: [-]digits[.digits][e±dd]
  int w = 0;
  const char* p = buf;
  bool neg = false;
  if (*p == '-') {
    neg = true;
    ++p;
  }
  // split into digit string + decimal exponent
  char digits[40];
  int nd = 0;
  int exp10 = 0;       // position of decimal point after digits[0]
  bool seen_dot = false;
  int int_digits = 0;  // digits before the '.' in the shortest form
  for (; *p; ++p) {
    if (*p == '.') {
      seen_dot = true;
      int_digits = nd;
    } else if (*p == 'e' || *p == 'E') {
      int e = 0, sign = 1;
      ++p;
      if (*p == '-') {
        sign = -1;
        ++p;
      } else if (*p == '+') {
        ++p;
      }
      for (; *p; ++p) e = e * 10 + (*p - '0');
      exp10 = sign * e;
      break;
    } else {
      digits[nd++] = *p;
    }
  }
  if (!seen_dot && exp10 == 0 && int_digits == 0) int_digits = nd;
  // decimal value = 0.digits × 10^point_pos
  int point;
  if (seen_dot || (!seen_dot && exp10 == 0)) {
    point = int_digits;      // "dd.ddd" or "ddd"
    // to_chars never emits both a dot and an exponent in general form?
    // It can ("1.2345e+08") — exp10 shifts the point.
    point += exp10;
  } else {
    point = 1 + exp10;       // "de±x": one leading digit
  }
  // strip trailing zero digits (shortest form shouldn't have any, except
  // the single "0")
  while (nd > 1 && digits[nd - 1] == '0' && nd > point) --nd;
  int frac = nd - point;     // fractional digit count (may be <= 0)
  if (frac > 8) {
    // cutoff: correctly-rounded fixed 8-fractional-digit conversion of
    // the exact value, trailing zeros trimmed
    int n2 = fixed8_chars(exact, buf, sizeof buf);
    // trim='.': strip ALL trailing zeros, keep the bare point ("1.").
    // The loop cannot cross the '.': eligibility guarantees a nonzero
    // digit somewhere (mn >= 1e-4), and integer-part zeros sit left of
    // the point, which is a non-'0' stopper.
    while (n2 > 1 && buf[n2 - 1] == '0') --n2;
    std::memcpy(word, buf, n2);
    word[n2] = '\0';
    const char* d = static_cast<const char*>(std::memchr(word, '.', n2));
    *dot = static_cast<int>(d - word);
    return n2;
  }
  // positional render from digits/point
  if (neg) word[w++] = '-';
  if (point <= 0) {
    word[w++] = '0';
    *dot = w;
    word[w++] = '.';
    for (int k = 0; k < -point; ++k) word[w++] = '0';
    for (int k = 0; k < nd; ++k) word[w++] = digits[k];
  } else if (point >= nd) {
    for (int k = 0; k < nd; ++k) word[w++] = digits[k];
    for (int k = 0; k < point - nd; ++k) word[w++] = '0';
    *dot = w;
    word[w++] = '.';
  } else {
    for (int k = 0; k < point; ++k) word[w++] = digits[k];
    *dot = w;
    word[w++] = '.';
    for (int k = point; k < nd; ++k) word[w++] = digits[k];
  }
  word[w] = '\0';
  return w;
}

// numpy 1-D assembly: pad every word to common (left, right) widths
// around the '.', hanging indent ' ', separator ' ', wrap when the next
// word would cross elem_width, strip the indent of the first line,
// wrap in brackets.
template <typename T>
int64_t format_rows_impl(const T* rows, int64_t n, int64_t f, char* out,
                         int64_t cap, int64_t* offsets, uint8_t* fallback) {
  // per-row scratch: formatted words and their dot positions
  char* words = new char[f * 40];
  int* wlen = new int[f];
  int* wdot = new int[f];
  int64_t pos = 0;
  for (int64_t r = 0; r < n; ++r) {
    offsets[r] = pos;
    const T* row = rows + r * f;
    // ---- eligibility (exactly fastfmt.format_rows's predicate)
    bool finite = true;
    double mx = 0.0, mn = 0.0;
    bool has_nz = false;
    for (int64_t j = 0; j < f; ++j) {
      double a = static_cast<double>(row[j]);
      if (!std::isfinite(a)) {
        finite = false;
        break;
      }
      a = std::fabs(a);
      if (a > 0.0) {
        if (!has_nz) {
          mx = mn = a;
          has_nz = true;
        } else {
          if (a > mx) mx = a;
          if (a < mn) mn = a;
        }
      }
    }
    bool exp_trigger =
        has_nz && (mx >= 1e8 || mn < 1e-4 || mx / mn > 1000.0);
    if (!finite || exp_trigger) {
      fallback[r] = 1;
      continue;
    }
    // ---- per-element format + common pad widths
    int pad_left = 0, pad_right = 0;
    for (int64_t j = 0; j < f; ++j) {
      char* wp = words + j * 40;
      int dot;
      wlen[j] = format_elem(row[j], static_cast<double>(row[j]), wp, &dot);
      wdot[j] = dot;
      int left = dot;                 // chars before '.'
      int right = wlen[j] - dot - 1;  // chars after '.'
      if (left > pad_left) pad_left = left;
      if (right > pad_right) pad_right = right;
    }
    // worst-case row bytes: f * (padded word + sep) + newlines + brackets
    int64_t worst = f * (pad_left + pad_right + 2) + f + (f + 1) + 2;
    if (pos + worst > cap) {
      delete[] words;
      delete[] wlen;
      delete[] wdot;
      return -1;
    }
    // ---- assembly
    char* o = out + pos;
    int64_t w = 0;
    o[w++] = '[';
    int line_len = 1;  // the hanging indent ' ' (slot [0] becomes '[')
    int64_t line_start = 0;  // index in o of this line's first char
    for (int64_t j = 0; j < f; ++j) {
      int lead = pad_left - wdot[j];
      int trail = pad_right - (wlen[j] - wdot[j] - 1);
      int wordw = pad_left + pad_right + 1;
      if (line_len + wordw > kElemW && line_len > 1) {
        // wrap: rstrip the current line, newline, hang indent
        while (w > line_start && o[w - 1] == ' ') --w;
        o[w++] = '\n';
        line_start = w;
        o[w++] = ' ';
        line_len = 1;
      }
      for (int k = 0; k < lead; ++k) o[w++] = ' ';
      std::memcpy(o + w, words + j * 40, wlen[j]);
      w += wlen[j];
      for (int k = 0; k < trail; ++k) o[w++] = ' ';
      line_len += wordw;
      if (j != f - 1) {
        o[w++] = ' ';
        line_len += 1;
      }
    }
    o[w++] = ']';
    pos += w;
  }
  offsets[n] = pos;
  delete[] words;
  delete[] wlen;
  delete[] wdot;
  return pos;
}

}  // namespace

extern "C" {

// Format n rows of f float32 elements; out/offsets as in the other batch
// APIs, fallback[r]=1 marks rows the caller must np.array2string itself
// (their offsets span zero bytes).  Returns total bytes or -1 on a full
// output buffer.
int64_t iotml_format_rows_f32(const float* rows, int64_t n, int64_t f,
                              char* out, int64_t cap, int64_t* offsets,
                              uint8_t* fallback) {
  return format_rows_impl(rows, n, f, out, cap, offsets, fallback);
}

int64_t iotml_format_rows_f64(const double* rows, int64_t n, int64_t f,
                              char* out, int64_t cap, int64_t* offsets,
                              uint8_t* fallback) {
  return format_rows_impl(rows, n, f, out, cap, offsets, fallback);
}

}  // extern "C"
