// iotml native stream engine: columnar STORE-FRAME batch codec.
//
// The zero-copy data plane's native half, BOTH directions: one call
// walks (or builds) a raw batch of segmented-log frames
// (store/segment.py layout, the ONE wire→disk→host contract)
//
//     u32 length | u32 crc32c | u8 attrs | i64 offset | i64 ts |
//     i32 key_len | key | u32 value_len | value | [headers]
//
// verifies each frame's CRC32C, checks the value's Confluent header
// (magic 0 + big-endian writer-schema id) against the reader's pinned
// id, and Avro-decodes the payload straight into CALLER-OWNED
// preallocated float32 / fixed-stride label / fixed-stride key column
// buffers — zero per-record allocations on either side of the ABI.
// Live consume and timestamp-replay backfill both enter through this
// one function (via stream.native.FrameDecoder), so the two paths
// cannot drift.
//
// Stop conditions (decoding always stops BEFORE the offending frame so
// the caller's cursor lands exactly on it):
//   - torn/corrupt frame (short buffer, bad CRC): flag bit 0 — the
//     recovery contract, same as store.segment.scan_records;
//   - Confluent schema-id mismatch (an evolved writer on a supposedly
//     pinned topic): flag bit 1 — the caller falls back to the
//     name-resolving Python path for that chunk instead of mis-reading
//     v2 bytes positionally;
//   - caller buffers full (cap_rows).
// Tombstones (attrs bit 1, compaction delete markers) carry no Avro
// payload: they are skipped and counted, never decoded.
//
// The WRITE path (ISSUE 12) lives here too — frame_engine.cc is the
// byte-layout owner:
//   iotml_frames_encode_columnar  columnar rows → Confluent-framed Avro
//                                 values → ready-to-append store frames
//                                 (the KSQL pump's fused produce leg);
//   iotml_frames_encode_values    opaque value bytes → store frames
//                                 (the MQTT bridge's JSON leg and the
//                                 generic durable produce_many fusion);
//   iotml_frames_restamp          broker-side RAW_PRODUCE landing: CRC-
//                                 validate a pre-framed batch and stamp
//                                 the real log offsets into the heads
//                                 (CRCs recomputed in place);
//   iotml_frames_validate         CRC + offset-monotonicity walk for
//                                 the replica's zero-copy mirror leg.
// Byte parity with store/segment.py's encode_record is pinned by
// tests (ops.framing is the oracle): a RAW_PRODUCE-ingested segment is
// byte-identical to the same records produced classically.
//
// Build: part of libiotml_stream.so (see Makefile).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

enum FieldType : int8_t {
  FR_FLOAT = 0,
  FR_DOUBLE = 1,
  FR_INT = 2,
  FR_LONG = 3,
  FR_STRING = 4,
  FR_BOOLEAN = 5,
};

// frame geometry (store/segment.py): length prefix + fixed head
constexpr int64_t kLenSize = 4;
constexpr int64_t kHeadSize = 4 + 1 + 8 + 8 + 4;  // crc, attrs, offset, ts, key_len
constexpr int64_t kMinBody = kHeadSize + 4;       // + value_len
constexpr uint8_t kAttrHeaders = 0x01;
constexpr uint8_t kAttrNullValue = 0x02;

// ---------------------------------------------------------------- crc32c
// Castagnoli (reflected 0x82F63B78), table built on first use — the
// byte-parity oracle is store/segment.py's _crc32c_py.
const uint32_t* crc32c_table() {
  static uint32_t table[256];
  static bool ready = false;
  if (!ready) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k)
        crc = (crc & 1) ? (crc >> 1) ^ 0x82F63B78u : crc >> 1;
      table[i] = crc;
    }
    ready = true;
  }
  return table;
}

inline uint32_t crc32c(const uint8_t* data, int64_t n) {
  const uint32_t* table = crc32c_table();
  uint32_t crc = 0xFFFFFFFFu;
  for (int64_t i = 0; i < n; ++i)
    crc = table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

inline uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

inline int64_t be64(const uint8_t* p) {
  return (int64_t(be32(p)) << 32) | int64_t(be32(p + 4));
}

// Avro zigzag varint (same contract as avro_engine.cc's reader).
inline int64_t frame_read_varint(const uint8_t* buf, int64_t pos,
                                 int64_t end, int64_t* out) {
  uint64_t acc = 0;
  int shift = 0;
  while (pos < end) {
    uint8_t b = buf[pos++];
    if (shift == 63 && (b & 0x7E)) return -1;
    acc |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
      return pos;
    }
    shift += 7;
    if (shift > 63) return -1;
  }
  return -1;
}

// Avro-decode one record body into a float32 numeric row + fixed-stride
// label slots.  Returns true on success.  float32 by contract: the
// device batch is float32, and a single double→float rounding here is
// bit-identical to numpy's astype on the Python oracle path.
bool decode_avro_row(const uint8_t* buf, int64_t pos, int64_t end,
                     const int8_t* types, const uint8_t* nullable,
                     int64_t n_fields, float* num_row, char* lab_row,
                     int64_t label_stride) {
  int64_t ncol = 0, scol = 0;
  for (int64_t f = 0; f < n_fields; ++f) {
    bool is_null = false;
    if (nullable[f]) {
      int64_t branch;
      pos = frame_read_varint(buf, pos, end, &branch);
      if (pos < 0) return false;
      is_null = (branch == 0);
    }
    switch (types[f]) {
      case FR_FLOAT: {
        float v = 0.0f;
        if (!is_null) {
          if (pos + 4 > end) return false;
          std::memcpy(&v, buf + pos, 4);
          pos += 4;
        }
        num_row[ncol++] = v;
        break;
      }
      case FR_DOUBLE: {
        double v = 0.0;
        if (!is_null) {
          if (pos + 8 > end) return false;
          std::memcpy(&v, buf + pos, 8);
          pos += 8;
        }
        num_row[ncol++] = static_cast<float>(v);
        break;
      }
      case FR_INT:
      case FR_LONG: {
        int64_t v = 0;
        if (!is_null) {
          pos = frame_read_varint(buf, pos, end, &v);
          if (pos < 0) return false;
        }
        num_row[ncol++] = static_cast<float>(static_cast<double>(v));
        break;
      }
      case FR_BOOLEAN: {
        float v = 0.0f;
        if (!is_null) {
          if (pos + 1 > end) return false;
          v = buf[pos++] ? 1.0f : 0.0f;
        }
        num_row[ncol++] = v;
        break;
      }
      case FR_STRING: {
        char* slot = lab_row + scol * label_stride;
        ++scol;
        if (is_null) {
          slot[0] = '\0';
          break;
        }
        int64_t len;
        pos = frame_read_varint(buf, pos, end, &len);
        if (pos < 0 || len < 0 || pos + len > end) return false;
        int64_t copy = len < label_stride - 1 ? len : label_stride - 1;
        std::memcpy(slot, buf + pos, copy);
        slot[copy] = '\0';
        pos += len;
        break;
      }
      default:
        return false;
    }
  }
  return true;
}

}  // namespace

extern "C" {

// flag bits reported through *out_flags
enum FrameFlags : int64_t {
  FRAMES_STOP_TORN = 1,      // torn/corrupt frame parked the scan
  FRAMES_STOP_SCHEMA = 2,    // Confluent schema id != expect_schema_id
};

// Decode a raw batch of store frames into columnar buffers.
//
//   buf/buf_len: contiguous frame bytes (a segment byte range, a wire
//       RAW_FETCH payload, or the emulator's re-framed batch).  May
//       begin with frames below start_offset (sparse-index alignment:
//       skipped after CRC verification) and end mid-frame (the torn
//       tail ends the batch, exactly like crash recovery).
//   start_offset: frames with offset < start_offset are skipped.
//   types/nullable/n_fields: the reader schema's compiled descriptors.
//   pinned_id_limit: the EXCLUSIVE upper bound on positionally-safe
//       Confluent writer ids (>= 0).  Registry-allocated v1-compatible
//       schemas get small ids; EVOLVED writer schemas live in the
//       reserved band at/above stream.registry.RESERVED_ID_BASE — a
//       value that is not magic-0 framed, or whose id is >= this limit,
//       stops the scan with FRAMES_STOP_SCHEMA (the caller resolves by
//       name in Python; nothing is blind-stripped).  < 0 decodes the
//       value as BARE Avro (no header, no strip) — the store-native
//       form.
//   out_numeric: [cap_rows x n_numeric] float32, row-major.
//   out_labels/label_stride: string fields, NUL-terminated slots.
//   out_keys/key_stride: optional (NULL) per-row message key copies,
//       zero-padded, truncated at stride-1 (the routing identity).
//   out_next_offset: cursor after the last CONSUMED frame (decoded or
//       skipped-tombstone); unchanged when nothing was consumed.
//   out_flags / out_skipped: stop reason bits; tombstones skipped.
//
//   out_ts_min / out_ts_max (the _ts variant): event-time bounds (ms)
//       over the frames CONSUMED at/after start_offset this call —
//       decoded rows and skipped tombstones alike (both advance the
//       stream's event-time watermark).  -1 when nothing was consumed.
//       This is the zero-per-record-cost watermark source: the
//       timestamps are already in every frame head, so batch min/max
//       falls out of the walk the decoder does anyway.
//
// Returns rows decoded (>= 0), or -1 on invalid arguments.
int64_t iotml_frames_decode_columnar_ts(
    const uint8_t* buf, int64_t buf_len, int64_t start_offset,
    const int8_t* types, const uint8_t* nullable, int64_t n_fields,
    int64_t pinned_id_limit, float* out_numeric, char* out_labels,
    int64_t label_stride, char* out_keys, int64_t key_stride,
    int64_t cap_rows, int64_t* out_next_offset, int64_t* out_flags,
    int64_t* out_skipped, int64_t* out_ts_min, int64_t* out_ts_max) {
  if (!buf || !types || !nullable || !out_numeric || !out_labels ||
      label_stride < 1 || cap_rows < 0 || (out_keys && key_stride < 1))
    return -1;
  int64_t n_numeric = 0, n_strings = 0;
  for (int64_t f = 0; f < n_fields; ++f) {
    if (types[f] == FR_STRING) ++n_strings; else ++n_numeric;
  }
  int64_t rows = 0, skipped = 0, flags = 0;
  int64_t pos = 0;
  int64_t next_offset = start_offset;
  int64_t ts_min = -1, ts_max = -1;
  while (rows < cap_rows) {
    if (pos + kLenSize > buf_len) break;  // clean end of buffer
    int64_t length = static_cast<int64_t>(be32(buf + pos));
    int64_t body = pos + kLenSize;
    int64_t end = body + length;
    if (length < kMinBody || end > buf_len) {
      flags |= FRAMES_STOP_TORN;  // torn tail / corrupt length prefix
      break;
    }
    uint32_t crc = be32(buf + body);
    if (crc32c(buf + body + 4, length - 4) != crc) {
      flags |= FRAMES_STOP_TORN;  // corrupt frame: recovery's contract
      break;
    }
    uint8_t attrs = buf[body + 4];
    int64_t offset = be64(buf + body + 5);
    int64_t ts = be64(buf + body + 13);
    int32_t key_len = static_cast<int32_t>(be32(buf + body + 21));
    int64_t p = body + kHeadSize;
    const uint8_t* key = nullptr;
    int64_t kn = 0;
    if (key_len >= 0) {
      key = buf + p;
      kn = key_len;
      p += key_len;
    }
    if (p + 4 > end) {
      flags |= FRAMES_STOP_TORN;
      break;
    }
    int64_t value_len = static_cast<int64_t>(be32(buf + p));
    p += 4;
    if (p + value_len > end) {
      flags |= FRAMES_STOP_TORN;
      break;
    }
    if (offset < start_offset) {
      pos = end;  // sparse-index alignment: before the requested cursor
      continue;
    }
    if (attrs & kAttrNullValue) {
      // tombstone: no Avro payload to decode; consumed, counted — and
      // it still advances the event-time watermark
      ++skipped;
      next_offset = offset + 1;
      if (ts_min < 0 || ts < ts_min) ts_min = ts;
      if (ts > ts_max) ts_max = ts;
      pos = end;
      continue;
    }
    int64_t vpos = p;
    int64_t vend = p + value_len;
    if (pinned_id_limit >= 0) {
      if (value_len < 5 || buf[vpos] != 0 ||
          static_cast<int64_t>(be32(buf + vpos + 1)) >= pinned_id_limit) {
        flags |= FRAMES_STOP_SCHEMA;  // evolved writer: resolve in Python
        break;
      }
      vpos += 5;  // Confluent header verified, not blind-stripped
    }
    float* num_row = out_numeric + rows * n_numeric;
    char* lab_row = out_labels + rows * n_strings * label_stride;
    if (!decode_avro_row(buf, vpos, vend, types, nullable, n_fields,
                         num_row, lab_row, label_stride)) {
      flags |= FRAMES_STOP_TORN;  // malformed Avro inside a valid frame
      break;
    }
    if (out_keys) {
      char* krow = out_keys + rows * key_stride;
      std::memset(krow, 0, key_stride);
      if (key && kn > 0) {
        int64_t copy = kn < key_stride - 1 ? kn : key_stride - 1;
        std::memcpy(krow, key, copy);
      }
    }
    ++rows;
    next_offset = offset + 1;
    if (ts_min < 0 || ts < ts_min) ts_min = ts;
    if (ts > ts_max) ts_max = ts;
    pos = end;
  }
  if (out_next_offset) *out_next_offset = next_offset;
  if (out_flags) *out_flags = flags;
  if (out_skipped) *out_skipped = skipped;
  if (out_ts_min) *out_ts_min = ts_min;
  if (out_ts_max) *out_ts_max = ts_max;
  return rows;
}

// Pre-watermark ABI: the same decode without the event-time out-params
// (kept so a caller built against ABI <= 8 keeps its exact signature).
int64_t iotml_frames_decode_columnar(
    const uint8_t* buf, int64_t buf_len, int64_t start_offset,
    const int8_t* types, const uint8_t* nullable, int64_t n_fields,
    int64_t pinned_id_limit, float* out_numeric, char* out_labels,
    int64_t label_stride, char* out_keys, int64_t key_stride,
    int64_t cap_rows, int64_t* out_next_offset, int64_t* out_flags,
    int64_t* out_skipped) {
  return iotml_frames_decode_columnar_ts(
      buf, buf_len, start_offset, types, nullable, n_fields,
      pinned_id_limit, out_numeric, out_labels, label_stride, out_keys,
      key_stride, cap_rows, out_next_offset, out_flags, out_skipped,
      nullptr, nullptr);
}

// ------------------------------------------------------------ write path

// avro_engine.cc's columnar Avro encoder, linked into the same .so —
// the value bytes of the fused produce leg come from the ONE encoder.
int64_t iotml_encode_batch_nulls(const double* numeric, const char* labels,
                                 int64_t label_stride, int64_t n_msgs,
                                 const int8_t* types, const uint8_t* nullable,
                                 int64_t n_fields, int64_t frame_schema_id,
                                 uint8_t* out, int64_t out_capacity,
                                 int64_t* out_offsets, const uint8_t* nulls);

namespace {

inline void put32(uint8_t* p, uint32_t v) {
  p[0] = (v >> 24) & 0xFF;
  p[1] = (v >> 16) & 0xFF;
  p[2] = (v >> 8) & 0xFF;
  p[3] = v & 0xFF;
}

inline void put64(uint8_t* p, uint64_t v) {
  put32(p, static_cast<uint32_t>(v >> 32));
  put32(p + 4, static_cast<uint32_t>(v));
}

// One store frame around a ready value (or tombstone), byte-identical
// to store/segment.py encode_record.  Returns bytes written, or -1 if
// `cap` is too small.  `value_null` frames a tombstone (attrs bit 1,
// value_len 0) — byte-distinct from an empty value.
int64_t write_frame(uint8_t* out, int64_t cap, int64_t offset, int64_t ts,
                    const uint8_t* key, int64_t key_len, bool key_null,
                    const uint8_t* value, int64_t value_len,
                    bool value_null) {
  if (value_null) value_len = 0;
  int64_t body = kHeadSize + (key_null ? 0 : key_len) + 4 + value_len;
  if (kLenSize + body > cap) return -1;
  put32(out, static_cast<uint32_t>(body));
  uint8_t* b = out + kLenSize;
  b[4] = value_null ? kAttrNullValue : 0;  // attrs (headers never framed
  // natively: the traced/header path keeps the Python encoder)
  put64(b + 5, static_cast<uint64_t>(offset));
  put64(b + 13, static_cast<uint64_t>(ts));
  put32(b + 21, static_cast<uint32_t>(key_null ? -1 : key_len));
  uint8_t* p = b + kHeadSize;
  if (!key_null && key_len > 0) {
    std::memcpy(p, key, key_len);
  }
  if (!key_null) p += key_len;
  put32(p, static_cast<uint32_t>(value_len));
  p += 4;
  if (value_len > 0) std::memcpy(p, value, value_len);
  put32(b, crc32c(b + 4, body - 4));
  return kLenSize + body;
}

}  // namespace

// Fused produce leg: columnar rows → Confluent-framed Avro values →
// contiguous ready-to-append store frames.  Offsets are stamped
// base_offset + i (a producing client passes 0 and the broker restamps
// at append; an in-process caller holding the log end passes it
// directly so no restamp pass is needed).
//
//   numeric/labels/nulls: the columnar row layout of
//       iotml_encode_batch_nulls (avro_engine.cc) — nulls may be NULL.
//   keys/key_offsets/key_null: optional per-row message keys.  All
//       NULL = every key null (the unkeyed stream case).  With
//       key_offsets NULL but key_stride > 0, `keys` is a FIXED-STRIDE
//       [n x key_stride] block of NUL-terminated entries (an S-dtype
//       numpy column — the zero-per-record-object produce form).
//   timestamps: per-row record timestamps (ms).
//   schema_id: Confluent header id (>= 0) — the ONE framing point.
// Returns total frame bytes written into `out`, or -1 on overflow /
// impossible null.
int64_t iotml_frames_encode_columnar(
    const double* numeric, const char* labels, int64_t label_stride,
    int64_t n_msgs, const int8_t* types, const uint8_t* nullable,
    int64_t n_fields, int64_t schema_id, const uint8_t* nulls,
    const uint8_t* keys, const int64_t* key_offsets, int64_t key_stride,
    const uint8_t* key_null, const int64_t* timestamps,
    int64_t base_offset, uint8_t* out, int64_t out_capacity) {
  if (n_msgs < 0 || !out) return -1;
  if (n_msgs == 0) return 0;
  int64_t n_strings = 0;
  for (int64_t f = 0; f < n_fields; ++f)
    if (types[f] == FR_STRING) ++n_strings;
  // scratch for the Avro values: same worst-case bound the Avro encoder
  // itself uses (5 header + 20/field + label strides per row)
  int64_t vcap = n_msgs * (5 + n_fields * 20 + n_strings * label_stride) + 64;
  std::vector<uint8_t> values(static_cast<size_t>(vcap));
  std::vector<int64_t> voff(static_cast<size_t>(n_msgs + 1));
  int64_t total = iotml_encode_batch_nulls(
      numeric, labels, label_stride, n_msgs, types, nullable, n_fields,
      schema_id, values.data(), vcap, voff.data(), nulls);
  if (total < 0) return -1;
  int64_t pos = 0;
  for (int64_t i = 0; i < n_msgs; ++i) {
    bool knull = true;
    const uint8_t* kp = nullptr;
    int64_t kn = 0;
    if (keys && key_offsets) {
      knull = key_null != nullptr && key_null[i] != 0;
      kp = keys + key_offsets[i];
      kn = key_offsets[i + 1] - key_offsets[i];
    } else if (keys && key_stride > 0) {
      // fixed-stride NUL-terminated keys (an S-dtype numpy column)
      knull = key_null != nullptr && key_null[i] != 0;
      kp = keys + i * key_stride;
      while (kn < key_stride && kp[kn]) ++kn;
    }
    int64_t wrote = write_frame(
        out + pos, out_capacity - pos, base_offset + i,
        timestamps ? timestamps[i] : 0, kp, kn, knull,
        values.data() + voff[i], voff[i + 1] - voff[i], false);
    if (wrote < 0) return -1;
    pos += wrote;
  }
  return pos;
}

// Opaque-value framing: [(key, value, ts)] columnar blobs → contiguous
// store frames (the MQTT bridge's JSON leg, the rekey pass-through and
// the generic durable produce_many fusion — the value bytes are
// whatever the caller already holds; framing happens ONCE, here).
// value_null marks tombstones.  Returns frame bytes or -1 on overflow.
int64_t iotml_frames_encode_values(
    const uint8_t* values, const int64_t* value_offsets,
    const uint8_t* keys, const int64_t* key_offsets,
    const uint8_t* key_null, const uint8_t* value_null,
    const int64_t* timestamps, int64_t n_msgs, int64_t base_offset,
    uint8_t* out, int64_t out_capacity) {
  if (n_msgs < 0 || !out || !values || !value_offsets) return -1;
  int64_t pos = 0;
  for (int64_t i = 0; i < n_msgs; ++i) {
    bool knull = true;
    const uint8_t* kp = nullptr;
    int64_t kn = 0;
    if (keys && key_offsets) {
      knull = key_null != nullptr && key_null[i] != 0;
      kp = keys + key_offsets[i];
      kn = key_offsets[i + 1] - key_offsets[i];
    }
    bool vnull = value_null != nullptr && value_null[i] != 0;
    int64_t wrote = write_frame(
        out + pos, out_capacity - pos, base_offset + i,
        timestamps ? timestamps[i] : 0, kp, kn, knull,
        values + value_offsets[i], value_offsets[i + 1] - value_offsets[i],
        vnull);
    if (wrote < 0) return -1;
    pos += wrote;
  }
  return pos;
}

// Broker-side RAW_PRODUCE landing: CRC-validate every frame of a
// pre-framed batch and stamp the real log offsets (base_offset + i)
// into the frame heads, recomputing each CRC in place.  STRICT: any
// torn tail, corrupt frame or trailing garbage rejects the WHOLE batch
// (returns -(frames_ok + 1)) before a byte may land in the segment —
// Kafka CORRUPT_MESSAGE semantics.  On success returns the frame count
// with *out_max_ts the newest record timestamp (the timeindex anchor).
int64_t iotml_frames_restamp(uint8_t* buf, int64_t buf_len,
                             int64_t base_offset, int64_t* out_max_ts) {
  int64_t pos = 0, n = 0, max_ts = -1;
  while (pos < buf_len) {
    if (pos + kLenSize > buf_len) return -(n + 1);  // trailing garbage
    int64_t length = static_cast<int64_t>(be32(buf + pos));
    int64_t body = pos + kLenSize;
    int64_t end = body + length;
    if (length < kMinBody || end > buf_len) return -(n + 1);
    uint32_t crc = be32(buf + body);
    if (crc32c(buf + body + 4, length - 4) != crc) return -(n + 1);
    put64(buf + body + 5, static_cast<uint64_t>(base_offset + n));
    put32(buf + body, crc32c(buf + body + 4, length - 4));
    int64_t ts = be64(buf + body + 13);
    if (ts > max_ts) max_ts = ts;
    ++n;
    pos = end;
  }
  if (out_max_ts) *out_max_ts = max_ts;
  return n;
}

// Replica mirror-leg validation: walk a raw fetch batch, CRC-verify
// every frame, and report the byte range + offset span of the frames
// at/after `start_offset` (leading frames below it are the sparse-index
// alignment the read path documents; a torn TAIL ends the batch
// cleanly when strict == 0, rejects it when strict != 0).  Offsets must
// be strictly increasing.  Returns the frame count in range with
//   *out_first/*out_last   offset span (first == -1 when empty),
//   *out_start/*out_end    byte range [start, end) of those frames,
//   *out_max_ts            newest timestamp in range,
//   *out_contiguous        1 when last - first + 1 == count (no holes).
// A corrupt frame (strict) or non-monotone offset returns -(count+1).
int64_t iotml_frames_validate(const uint8_t* buf, int64_t buf_len,
                              int64_t start_offset, int64_t strict,
                              int64_t* out_first, int64_t* out_last,
                              int64_t* out_start, int64_t* out_end,
                              int64_t* out_max_ts,
                              int64_t* out_contiguous) {
  int64_t pos = 0, n = 0;
  int64_t first = -1, last = -1, max_ts = -1;
  int64_t byte_start = -1, byte_end = 0;
  int64_t prev_off = -1;
  while (pos < buf_len) {
    if (pos + kLenSize > buf_len) {
      if (strict) return -(n + 1);
      break;
    }
    int64_t length = static_cast<int64_t>(be32(buf + pos));
    int64_t body = pos + kLenSize;
    int64_t end = body + length;
    if (length < kMinBody || end > buf_len ||
        crc32c(buf + body + 4, length - 4) != be32(buf + body)) {
      if (strict) return -(n + 1);
      break;  // torn tail: the valid prefix is the batch
    }
    int64_t offset = be64(buf + body + 5);
    if (offset <= prev_off) return -(n + 1);  // non-monotone: corrupt
    prev_off = offset;
    if (offset >= start_offset) {
      if (first < 0) {
        first = offset;
        byte_start = pos;
      }
      last = offset;
      int64_t ts = be64(buf + body + 13);
      if (ts > max_ts) max_ts = ts;
      byte_end = end;
      ++n;
    }
    pos = end;
  }
  if (out_first) *out_first = first;
  if (out_last) *out_last = last;
  if (out_start) *out_start = byte_start < 0 ? 0 : byte_start;
  if (out_end) *out_end = byte_end;
  if (out_max_ts) *out_max_ts = max_ts;
  if (out_contiguous)
    *out_contiguous = (n == 0 || last - first + 1 == n) ? 1 : 0;
  return n;
}

}  // extern "C"
