// iotml native stream engine: batch JSON → columnar decoder.
//
// The KSQL-equivalent pipeline's input leg (reference
// infrastructure/confluent/01_installConfluentPlatform.sh:229-236 —
// SENSOR_DATA_S over VALUE_FORMAT='JSON') parses one flat JSON object per
// fleet message.  Per-message Python json.loads dominated that stage
// (~12.6k records/s captured in round 2); this decoder parses a whole
// poll's worth of messages in one call, straight into the same columnar
// (float64 matrix + fixed-stride labels) layout the Avro engine uses, so
// the CSAS JSON→AVRO leg can go native end to end.
//
// Exactness stance (mirrors _NativeAvroSource): anything this parser
// cannot reproduce byte-for-byte against the Python path marks the ROW for
// fallback — Python re-decodes just those rows.  Fallback triggers:
// escapes in strings, strings at/over the label stride, nested
// objects/arrays, NaN/Infinity literals, type mismatches, non-decimal
// number spellings (hex), floats in integer columns, |int| >= 2^53 (the
// float64-exact bound), and null/missing on a NON-nullable column.
// Missing columns and explicit nulls on nullable columns are NOT
// fallbacks: they set the per-field null bitmap (the realistic fleet
// payload always has them — the KSQL name-mangling quirk makes the
// underscore-digit columns permanently null).  Unknown keys are skipped
// (the star projection ignores them), matching dict semantics; duplicate
// known keys overwrite (Python dict: last wins).
//
// Number parity: strtod and Python's float() are both correctly-rounded
// IEEE-754 decimal conversions, so any decimal token lands on the same
// double.  Tokens are pre-scanned to reject spellings strtod accepts but
// JSON does not (hex, leading '+', "1.", ".5", infinity).
//
// Strictness parity: Python's json.loads(bytes) first utf-8-decodes the
// whole message (invalid UTF-8 → UnicodeDecodeError → row dropped) and
// rejects raw control characters inside strings ("Invalid control
// character") — each row is therefore UTF-8-validated up front, and the
// string scans treat any byte < 0x20 as a fallback trigger.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <locale.h>

#include "utf8_check.h"

namespace {

// strtod is LC_NUMERIC-sensitive: under a non-C locale (an embedding app
// setting de_DE) every fractional token would parse short and silently
// demote the whole fast path to 0% hit rate.  Pin a C locale once and use
// strtod_l so number parity with Python's float() holds regardless of the
// process locale.  Never freed: one per process, alive for its lifetime.
inline locale_t c_numeric_locale() {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  return loc;
}

enum FieldType : int8_t {
  F_FLOAT = 0,
  F_DOUBLE = 1,
  F_INT = 2,
  F_LONG = 3,
  F_STRING = 4,
  F_BOOLEAN = 5,
};

constexpr double kIntExact = 9007199254740992.0;  // 2^53

inline bool is_ws(char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r';
}

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && is_ws(*p)) ++p;
  return p;
}

// Validate a JSON number token [p, q) per RFC 8259 grammar:
//   -?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?
// `is_integral` reports no '.'/exponent (safe for int columns).
bool valid_json_number(const char* p, const char* q, bool* is_integral) {
  const char* s = p;
  if (s < q && *s == '-') ++s;
  if (s >= q) return false;
  if (*s == '0') {
    ++s;
  } else if (*s >= '1' && *s <= '9') {
    while (s < q && *s >= '0' && *s <= '9') ++s;
  } else {
    return false;
  }
  bool integral = true;
  if (s < q && *s == '.') {
    integral = false;
    ++s;
    if (s >= q || *s < '0' || *s > '9') return false;
    while (s < q && *s >= '0' && *s <= '9') ++s;
  }
  if (s < q && (*s == 'e' || *s == 'E')) {
    integral = false;
    ++s;
    if (s < q && (*s == '+' || *s == '-')) ++s;
    if (s >= q || *s < '0' || *s > '9') return false;
    while (s < q && *s >= '0' && *s <= '9') ++s;
  }
  *is_integral = integral;
  return s == q;
}

struct Column {
  const char* name;  // uppercase
  int64_t name_len;
  int8_t type;
  int64_t slot;  // index into the numeric matrix or the label row
};

using iotml::valid_utf8;

}  // namespace

extern "C" {

// Parse n_msgs flat JSON objects into columnar buffers.
//
//   blob/offsets      — concatenated messages, offsets[n_msgs+1]
//   names_blob/name_offsets — concatenated UPPERCASE column names in schema
//                       order (the sink schema: numeric fields fill
//                       `numeric` left-to-right, string fields fill
//                       `labels`), name_offsets[n_fields+1]
//   types[n_fields]   — FieldType per column
//   nullable[n_fields]— 1 where the column is a ["null", T] union
//   numeric           — [n_msgs x n_numeric] float64, row-major
//   labels            — [n_msgs x n_strings] fixed-stride bytes, caller
//                       zeroed
//   nulls             — [n_msgs x n_fields] uint8, caller zeroed; set to 1
//                       where the column is null/missing in that row
//   fallback[n_msgs]  — set to 1 where the row needs the Python path
//
// Returns the number of rows decoded natively (n_msgs - fallbacks), or -1
// on invalid arguments.  Rows marked fallback have undefined column
// contents — the caller re-decodes them in Python.
int64_t iotml_json_decode_batch(
    const char* blob, const int64_t* offsets, int64_t n_msgs,
    const char* names_blob, const int64_t* name_offsets,
    const int8_t* types, const uint8_t* nullable, int64_t n_fields,
    double* numeric, int64_t n_numeric,
    char* labels, int64_t n_strings, int64_t stride,
    uint8_t* nulls, uint8_t* fallback) {
  if (n_fields <= 0 || n_fields > 64) return -1;
  Column cols[64];
  {
    int64_t num_slot = 0, str_slot = 0;
    for (int64_t i = 0; i < n_fields; ++i) {
      cols[i].name = names_blob + name_offsets[i];
      cols[i].name_len = name_offsets[i + 1] - name_offsets[i];
      cols[i].type = types[i];
      cols[i].slot = (types[i] == F_STRING) ? str_slot++ : num_slot++;
    }
    if (num_slot != n_numeric || str_slot != n_strings) return -1;
  }

  int64_t ok_rows = 0;
  char keybuf[128];
  for (int64_t r = 0; r < n_msgs; ++r) {
    const char* p = blob + offsets[r];
    const char* end = blob + offsets[r + 1];
    double* num_row = numeric + r * n_numeric;
    char* lab_row = labels + r * n_strings * stride;
    uint8_t* null_row = nulls + r * n_fields;
    uint64_t found = 0;
    bool bad = false;

    // json.loads(bytes) utf-8-decodes the whole message first: a row the
    // Python path would reject with UnicodeDecodeError must fall back
    if (!valid_utf8(reinterpret_cast<const uint8_t*>(p),
                    reinterpret_cast<const uint8_t*>(end)))
      bad = true;
    if (!bad) p = skip_ws(p, end);
    if (!bad && (p >= end || *p != '{')) bad = true;
    if (!bad) {
      ++p;
      p = skip_ws(p, end);
      if (p < end && *p == '}') {
        ++p;  // empty object: every column is missing → all-null below
      } else {
        for (;;) {
          // ---- key
          p = skip_ws(p, end);
          if (p >= end || *p != '"') { bad = true; break; }
          ++p;
          int64_t klen = 0;
          while (p < end && *p != '"' && *p != '\\' &&
                 (uint8_t)*p >= 0x20 && (uint8_t)*p < 0x80 &&
                 klen < (int64_t)sizeof keybuf) {
            char c = *p++;
            keybuf[klen++] = (c >= 'a' && c <= 'z') ? c - 32 : c;
          }
          // stops on escape, raw control char, an over-long key, or a
          // non-ASCII key byte → Python (its str.upper() is Unicode-aware:
          // 'ﬂow'.upper() == 'FLOW' could match a column this byte-wise
          // fold cannot)
          if (p >= end || *p != '"') { bad = true; break; }
          ++p;
          p = skip_ws(p, end);
          if (p >= end || *p != ':') { bad = true; break; }
          ++p;
          p = skip_ws(p, end);
          if (p >= end) { bad = true; break; }

          // ---- column lookup (19-ish columns: linear memcmp is fine)
          int64_t ci = -1;
          for (int64_t i = 0; i < n_fields; ++i) {
            if (cols[i].name_len == klen &&
                memcmp(cols[i].name, keybuf, klen) == 0) {
              ci = i;
              break;
            }
          }

          // ---- value
          char c = *p;
          if (c == '"') {
            ++p;
            const char* s = p;
            while (p < end && *p != '"' && *p != '\\' &&
                   (uint8_t)*p >= 0x20)
              ++p;
            // stops on escape or raw control char (json.loads strict mode
            // rejects both) → Python decides
            if (p >= end || *p != '"') { bad = true; break; }
            int64_t slen = p - s;
            ++p;
            if (ci >= 0) {
              if (cols[ci].type != F_STRING || slen >= stride) {
                bad = true;
                break;
              }
              char* slot = lab_row + cols[ci].slot * stride;
              memcpy(slot, s, slen);
              // duplicate key overwriting a longer value: clear the tail
              // (otherwise stale bytes from the first value survive)
              if (slen < stride) memset(slot + slen, 0, stride - slen);
              null_row[ci] = 0;
              found |= 1ull << ci;
            }
          } else if (c == '-' || (c >= '0' && c <= '9')) {
            const char* s = p;
            while (p < end && (*p == '-' || *p == '+' || *p == '.' ||
                               *p == 'e' || *p == 'E' ||
                               (*p >= '0' && *p <= '9')))
              ++p;
            bool integral = false;
            if (!valid_json_number(s, p, &integral)) { bad = true; break; }
            if (ci >= 0) {
              int8_t t = cols[ci].type;
              if (t == F_STRING || t == F_BOOLEAN) { bad = true; break; }
              if ((t == F_INT || t == F_LONG) && !integral) {
                bad = true;  // float into an integer column: Python decides
                break;
              }
              char* tok_end = nullptr;
              locale_t cloc = c_numeric_locale();
              // newlocale can fail (ENOMEM): plain strtod is only wrong
              // under a non-C locale, and a wrong parse trips tok_end !=
              // p → Python fallback (slow, never incorrect)
              double v = cloc ? strtod_l(s, &tok_end, cloc)
                              : strtod(s, &tok_end);
              if (tok_end != p) { bad = true; break; }
              if ((t == F_INT || t == F_LONG) &&
                  (v >= kIntExact || v <= -kIntExact)) {
                bad = true;  // beyond float64-exact int range
                break;
              }
              if (t == F_FLOAT && (v > 3.4028234663852886e38 ||
                                   v < -3.4028234663852886e38)) {
                // beyond float32 range (incl. strtod's ERANGE infinity):
                // Python's struct.pack('<f') raises for finite overflow —
                // the Python leg owns that error semantics
                bad = true;
                break;
              }
              num_row[cols[ci].slot] = v;
              null_row[ci] = 0;
              found |= 1ull << ci;
            }
          } else if (c == 't' && end - p >= 4 && memcmp(p, "true", 4) == 0) {
            p += 4;
            if (ci >= 0) {
              if (cols[ci].type != F_BOOLEAN) { bad = true; break; }
              num_row[cols[ci].slot] = 1.0;
              null_row[ci] = 0;
              found |= 1ull << ci;
            }
          } else if (c == 'f' && end - p >= 5 && memcmp(p, "false", 5) == 0) {
            p += 5;
            if (ci >= 0) {
              if (cols[ci].type != F_BOOLEAN) { bad = true; break; }
              num_row[cols[ci].slot] = 0.0;
              null_row[ci] = 0;
              found |= 1ull << ci;
            }
          } else if (c == 'n' && end - p >= 4 && memcmp(p, "null", 4) == 0) {
            p += 4;
            if (ci >= 0) {
              if (!nullable[ci]) { bad = true; break; }  // Python raises
              null_row[ci] = 1;
              if (cols[ci].type == F_STRING)  // deterministic contents
                memset(lab_row + cols[ci].slot * stride, 0, stride);
              else
                num_row[cols[ci].slot] = 0.0;
              found |= 1ull << ci;
            }
          } else {
            // nested object/array, NaN/Infinity, garbage → Python
            bad = true;
            break;
          }

          p = skip_ws(p, end);
          if (p < end && *p == ',') {
            ++p;
            continue;
          }
          if (p < end && *p == '}') {
            ++p;
            break;
          }
          bad = true;
          break;
        }
      }
    }
    if (!bad) {
      p = skip_ws(p, end);
      if (p != end) bad = true;  // trailing garbage
    }
    if (!bad) {
      // columns never seen: null when the schema allows, else Python
      // (a missing non-nullable column raises on the Python path too —
      // that path owns the error semantics)
      for (int64_t i = 0; i < n_fields && !bad; ++i) {
        if (!(found & (1ull << i))) {
          if (!nullable[i]) {
            bad = true;
          } else {
            null_row[i] = 1;
            if (cols[i].type != F_STRING) num_row[cols[i].slot] = 0.0;
            // (string slots: caller-zeroed labels are already empty)
          }
        }
      }
    }
    if (bad) {
      fallback[r] = 1;
    } else {
      ++ok_rows;
    }
  }
  return ok_rows;
}

}  // extern "C"
