// iotml native Kafka wire client — the C++ half of the stream data plane.
//
// TPU-native replacement for the reference's librdkafka-backed tf.data ops
// (tensorflow_io.kafka KafkaDataset / KafkaOutputSequence, reference
// cardata-v3.py:46-47, :238-252): a blocking TCP client speaking the classic
// Kafka protocol subset the framework's wire layer defines
// (stream/kafka_wire.py): request header v1; MessageSet v1 (magic 1, CRC32
// over magic..value); Produce v2, Fetch v2, ListOffsets v1, Metadata v1,
// OffsetCommit v2, OffsetFetch v1, SaslHandshake v0 + raw PLAIN token,
// ApiVersions v0, CreateTopics v0.
//
// The headline entry point is iotml_kafka_fetch_decode(): one call performs
// fetch → Confluent 5-byte framing strip → schema-compiled Avro decode
// (via iotml_decode_batch from avro_engine.cc, linked into the same .so)
// straight into caller-owned columnar buffers — poll-to-matrix with zero
// Python-object traffic, the exact job KafkaDataset+decode_avro did in the
// reference's C++ layer.
//
// Error convention: functions return >= 0 on success; -2 for socket/frame
// IO failure (-1 is reserved: OffsetFetch uses it for "no committed
// offset"); -(1000 + kafka_error_code) for protocol-level errors, so
// Python can map e.g. -1003 back to UNKNOWN_TOPIC_OR_PARTITION.

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

extern "C" int64_t iotml_decode_batch(const uint8_t* blob,
                                      const int64_t* offsets, int64_t n_msgs,
                                      const int8_t* types,
                                      const uint8_t* nullable,
                                      int64_t n_fields, int64_t strip,
                                      double* out_numeric, char* out_labels,
                                      int64_t label_stride);

namespace {

constexpr int16_t API_PRODUCE = 0, API_FETCH = 1, API_LIST_OFFSETS = 2,
                  API_METADATA = 3, API_OFFSET_COMMIT = 8,
                  API_OFFSET_FETCH = 9, API_SASL_HANDSHAKE = 17,
                  API_CREATE_TOPICS = 19, API_RAW_PRODUCE = 65;
constexpr int16_t ERR_NONE = 0, ERR_TOPIC_EXISTS = 36;
constexpr int64_t K_EIO = -2;  // -1 would collide with OffsetFetch's "no committed offset"
// The fused decode found a Confluent schema id outside the pinned band
// at the CURRENT cursor (nothing decoded): the caller re-reads the
// chunk through the name-resolving Python path (native_kafka maps this
// to SchemaIdMismatchError).  -1999 sits between the protocol-error
// band (-1000 - code) and the decode-error band (-(row + 1) - 2000),
// colliding with neither.
constexpr int64_t K_ESCHEMA = -1999;

inline int64_t proto_err(int16_t code) { return -(1000 + (int64_t)code); }

// ---------------------------------------------------------------- crc32
uint32_t crc32_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc32_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32(const uint8_t* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = crc32_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ------------------------------------------------------------ buffers
struct Writer {
  std::vector<uint8_t> buf;
  void raw(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf.insert(buf.end(), b, b + n);
  }
  void i8(int8_t v) { buf.push_back(static_cast<uint8_t>(v)); }
  void i16(int16_t v) {
    buf.push_back((v >> 8) & 0xFF);
    buf.push_back(v & 0xFF);
  }
  void i32(int32_t v) {
    for (int s = 24; s >= 0; s -= 8) buf.push_back((v >> s) & 0xFF);
  }
  void u32(uint32_t v) {
    for (int s = 24; s >= 0; s -= 8) buf.push_back((v >> s) & 0xFF);
  }
  void i64(int64_t v) {
    for (int s = 56; s >= 0; s -= 8) buf.push_back((v >> s) & 0xFF);
  }
  void str(const char* s) {  // non-null Kafka STRING
    int16_t n = s ? static_cast<int16_t>(strlen(s)) : 0;
    i16(n);
    if (s) raw(s, n);
  }
  void null_str() { i16(-1); }
  void bytes(const uint8_t* p, int32_t n) {  // n < 0 → null BYTES
    i32(n);
    if (n > 0) raw(p, n);
  }
};

struct Reader {
  const uint8_t* buf;
  size_t len, pos = 0;
  bool fail = false;
  Reader(const uint8_t* b, size_t n) : buf(b), len(n) {}
  bool need(size_t n) {
    if (pos + n > len) { fail = true; return false; }
    return true;
  }
  int8_t i8() { return need(1) ? static_cast<int8_t>(buf[pos++]) : 0; }
  int16_t i16() {
    if (!need(2)) return 0;
    int16_t v = (buf[pos] << 8) | buf[pos + 1];
    pos += 2;
    return v;
  }
  int32_t i32() {
    if (!need(4)) return 0;
    int32_t v = 0;
    for (int i = 0; i < 4; ++i) v = (v << 8) | buf[pos++];
    return v;
  }
  uint32_t u32() { return static_cast<uint32_t>(i32()); }
  int64_t i64() {
    if (!need(8)) return 0;
    int64_t v = 0;
    for (int i = 0; i < 8; ++i) v = (v << 8) | buf[pos++];
    return v;
  }
  void skip_str() {
    int16_t n = i16();
    if (n > 0 && need(n)) pos += n;
  }
  std::string str() {
    int16_t n = i16();
    if (n <= 0 || !need(n)) return "";
    std::string s(reinterpret_cast<const char*>(buf + pos), n);
    pos += n;
    return s;
  }
  // BYTES: returns length (-1 null) and sets *out to the in-place pointer.
  int32_t bytes(const uint8_t** out) {
    int32_t n = i32();
    if (n < 0) { *out = nullptr; return -1; }
    if (!need(n)) { *out = nullptr; return -1; }
    *out = buf + pos;
    pos += n;
    return n;
  }
};

// ------------------------------------------------------------- messages
struct Staged {
  int64_t offset;
  int64_t timestamp;
  std::vector<uint8_t> key;
  bool key_null;
  std::vector<uint8_t> value;
  bool value_null = false;  // tombstone (compacted-topic delete marker):
                            // wire length -1, distinct from empty
};

struct Client {
  int fd = -1;
  int32_t corr = 0;
  std::string client_id;
  std::vector<Staged> staged;
  int64_t staged_high_watermark = -1;
  // Exclusive upper bound on positionally-safe Confluent writer ids
  // for the fused fetch_decode paths (< 0 = no check, the legacy
  // blind-strip behavior).  Evolved writer schemas live in the
  // reserved id band (stream.registry.RESERVED_ID_BASE and up): a
  // staged value that is not magic-0 framed or whose id is >= this
  // limit stops the decode BEFORE that message — an evolved (v2)
  // writer on a supposedly-v1 topic surfaces as K_ESCHEMA instead of
  // being positionally mis-read.
  int64_t pinned_id_limit = -1;
};

// MessageSet v1 encode: entries share one timestamp array layout from caller.
// value_null (optional) marks tombstones: encoded as wire length -1, the
// compacted-topic delete marker — never as an empty payload.
void encode_message_set(Writer& w, const uint8_t* values,
                        const int64_t* val_off, const uint8_t* keys,
                        const int64_t* key_off, const uint8_t* key_null,
                        const int64_t* timestamps, int64_t n,
                        const uint8_t* value_null = nullptr) {
  for (int64_t i = 0; i < n; ++i) {
    Writer body;
    body.i8(1);  // magic 1
    body.i8(0);  // attributes
    body.i64(timestamps ? timestamps[i] : 0);
    if (keys && !(key_null && key_null[i])) {
      int32_t kn = static_cast<int32_t>(key_off[i + 1] - key_off[i]);
      body.bytes(keys + key_off[i], kn);  // kn == 0 → empty (non-null) key
    } else {
      body.bytes(nullptr, -1);
    }
    if (value_null && value_null[i]) {
      body.bytes(nullptr, -1);
    } else {
      body.bytes(values + val_off[i],
                 static_cast<int32_t>(val_off[i + 1] - val_off[i]));
    }
    w.i64(0);  // offset (assigned broker-side on produce)
    w.i32(static_cast<int32_t>(body.buf.size() + 4));
    w.u32(crc32(body.buf.data(), body.buf.size()));
    w.raw(body.buf.data(), body.buf.size());
  }
}

// MessageSet v1 decode into staged entries; tolerates a truncated tail.
bool decode_message_set(const uint8_t* buf, size_t len, int64_t min_offset,
                        int64_t max_messages, std::vector<Staged>& out) {
  Reader r(buf, len);
  while (r.pos + 12 <= len &&
         out.size() < static_cast<size_t>(max_messages)) {
    int64_t offset = r.i64();
    int32_t size = r.i32();
    if (size < 0 || r.pos + static_cast<size_t>(size) > len) break;  // tail
    size_t end = r.pos + size;
    uint32_t crc = r.u32();
    if (crc32(buf + r.pos, end - r.pos) != crc) return false;
    int8_t magic = r.i8();
    r.i8();  // attributes (no compression in this subset)
    int64_t ts = magic >= 1 ? r.i64() : 0;
    const uint8_t* kp;
    int32_t kn = r.bytes(&kp);
    const uint8_t* vp;
    int32_t vn = r.bytes(&vp);
    if (r.fail) return false;
    r.pos = end;
    if (offset < min_offset) continue;
    Staged s;
    s.offset = offset;
    s.timestamp = ts;
    s.key_null = kn < 0;
    s.value_null = vn < 0;
    if (kn > 0) s.key.assign(kp, kp + kn);
    if (vn > 0) s.value.assign(vp, vp + vn);
    out.push_back(std::move(s));
  }
  return true;
}

// ------------------------------------------------------------ transport
bool send_all(int fd, const uint8_t* p, size_t n) {
  while (n) {
    ssize_t k = ::send(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= k;
  }
  return true;
}

bool recv_all(int fd, uint8_t* p, size_t n) {
  while (n) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= k;
  }
  return true;
}

bool send_frame(Client* c, const std::vector<uint8_t>& payload) {
  uint8_t hdr[4];
  uint32_t n = static_cast<uint32_t>(payload.size());
  for (int i = 0; i < 4; ++i) hdr[i] = (n >> (24 - 8 * i)) & 0xFF;
  return send_all(c->fd, hdr, 4) &&
         send_all(c->fd, payload.data(), payload.size());
}

bool recv_frame(Client* c, std::vector<uint8_t>& out) {
  uint8_t hdr[4];
  if (!recv_all(c->fd, hdr, 4)) return false;
  int32_t n = 0;
  for (int i = 0; i < 4; ++i) n = (n << 8) | hdr[i];
  if (n < 0 || n > (1 << 30)) return false;
  out.resize(n);
  return n == 0 || recv_all(c->fd, out.data(), n);
}

// Send header+body, receive response, verify correlation id.  Returns the
// response bytes after the correlation id via `resp` (empty on failure).
bool request(Client* c, int16_t api, int16_t version, const Writer& body,
             std::vector<uint8_t>& resp) {
  Writer w;
  w.i16(api);
  w.i16(version);
  int32_t corr = ++c->corr;
  w.i32(corr);
  w.str(c->client_id.c_str());
  w.raw(body.buf.data(), body.buf.size());
  if (!send_frame(c, w.buf)) return false;
  std::vector<uint8_t> frame;
  if (!recv_frame(c, frame)) return false;
  Reader r(frame.data(), frame.size());
  if (r.i32() != corr) return false;
  resp.assign(frame.begin() + 4, frame.end());
  return true;
}

}  // namespace

extern "C" {

// ------------------------------------------------ standalone msgset codec
// MessageSet v1 encode/decode WITHOUT a connection handle: the wire
// SERVER's hot loops (kafka_wire.py fetch responses / produce requests)
// were pure-Python per-record Writer/Reader + crc32 — at tens of
// thousands of records/s through the platform process that cost a large
// slice of its core.  Same wire bytes as the Python codec (the oracle);
// kafka_wire.py falls back to it whenever these return an error.

// Encode n records (columnar) into out_buf.  offsets may be NULL (all 0,
// the client-produce convention).  Returns bytes written, or -(needed)
// when out_cap is too small (caller re-calls with a bigger buffer).
int64_t iotml_msgset_encode(const uint8_t* values, const int64_t* val_off,
                            const uint8_t* keys, const int64_t* key_off,
                            const uint8_t* key_null,
                            const int64_t* timestamps,
                            const int64_t* offsets, int64_t n,
                            uint8_t* out_buf, int64_t out_cap) {
  Writer w;
  w.buf.reserve(static_cast<size_t>(
      n * 34 + (n ? val_off[n] : 0) + (keys && n ? key_off[n] : 0)));
  for (int64_t i = 0; i < n; ++i) {
    Writer body;
    body.i8(1);  // magic 1
    body.i8(0);  // attributes
    body.i64(timestamps ? timestamps[i] : 0);
    if (keys && !(key_null && key_null[i])) {
      body.bytes(keys + key_off[i],
                 static_cast<int32_t>(key_off[i + 1] - key_off[i]));
    } else {
      body.bytes(nullptr, -1);
    }
    body.bytes(values + val_off[i],
               static_cast<int32_t>(val_off[i + 1] - val_off[i]));
    w.i64(offsets ? offsets[i] : 0);
    w.i32(static_cast<int32_t>(body.buf.size() + 4));
    w.u32(crc32(body.buf.data(), body.buf.size()));
    w.raw(body.buf.data(), body.buf.size());
  }
  int64_t total = static_cast<int64_t>(w.buf.size());
  if (total > out_cap) return -total;
  if (total) memcpy(out_buf, w.buf.data(), total);
  return total;
}

// Decode up to max_n records into columnar outputs.  Returns the record
// count; -1 on CRC mismatch / malformed framing (caller falls back to the
// Python decoder for its exact error semantics); -2 when the caller's
// key/value capacity is too small.  A truncated trailing record is
// dropped, matching the Python decoder (Kafka fetch responses may carry
// partial tails).  Null keys set key_null=1; null values decode as empty
// with val_null=1 so the caller can preserve None-ness.
int64_t iotml_msgset_decode(const uint8_t* buf, int64_t len, int64_t max_n,
                            int64_t* offsets, int64_t* ts,
                            int64_t* key_off, uint8_t* key_null,
                            uint8_t* keys, int64_t keys_cap,
                            int64_t* val_off, uint8_t* val_null,
                            uint8_t* values, int64_t values_cap) {
  Reader r(buf, static_cast<size_t>(len));
  int64_t n = 0;
  int64_t kpos = 0, vpos = 0;
  key_off[0] = 0;
  val_off[0] = 0;
  while (r.pos + 12 <= static_cast<size_t>(len) && n < max_n) {
    int64_t offset = r.i64();
    int32_t size = r.i32();
    if (size < 0 || r.pos + static_cast<size_t>(size) >
                        static_cast<size_t>(len)) {
      break;  // partial trailing message
    }
    size_t end = r.pos + size;
    uint32_t crc = r.u32();
    if (crc32(buf + r.pos, end - r.pos) != crc) return -1;
    int8_t magic = r.i8();
    r.i8();  // attributes (no compression in this subset)
    int64_t t = magic >= 1 ? r.i64() : 0;
    const uint8_t* kp;
    int32_t kn = r.bytes(&kp);
    const uint8_t* vp;
    int32_t vn = r.bytes(&vp);
    if (r.fail) return -1;
    r.pos = end;
    if (kn > 0 && kpos + kn > keys_cap) return -2;
    if (vn > 0 && vpos + vn > values_cap) return -2;
    offsets[n] = offset;
    ts[n] = t;
    key_null[n] = kn < 0;
    if (kn > 0) {
      memcpy(keys + kpos, kp, kn);
      kpos += kn;
    }
    key_off[n + 1] = kpos;
    val_null[n] = vn < 0;
    if (vn > 0) {
      memcpy(values + vpos, vp, vn);
      vpos += vn;
    }
    val_off[n + 1] = vpos;
    ++n;
  }
  return n;
}

// Connect (optionally SASL/PLAIN-authenticating, the reference cluster's
// mandatory mechanism — gcp.yaml:29-32).  Returns an opaque handle or NULL.
void* iotml_kafka_connect(const char* host, int32_t port,
                          const char* client_id, const char* user,
                          const char* password, double timeout_s) {
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char portbuf[16];
  snprintf(portbuf, sizeof portbuf, "%d", port);
  if (getaddrinfo(host, portbuf, &hints, &res) != 0 || !res) return nullptr;
  // Non-blocking connect with the caller's deadline — a plain ::connect
  // ignores SO_SNDTIMEO and can block for the kernel TCP timeout (~2 min).
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc != 0 && errno == EINPROGRESS) {
      pollfd pfd{fd, POLLOUT, 0};
      if (::poll(&pfd, 1, static_cast<int>(timeout_s * 1000)) == 1) {
        int err = 0;
        socklen_t len = sizeof err;
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
        rc = err == 0 ? 0 : -1;
      } else {
        rc = -1;  // timeout
      }
    }
    if (rc == 0) {
      fcntl(fd, F_SETFL, flags);  // back to blocking for send/recv timeouts
      break;
    }
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return nullptr;
  timeval tv;
  tv.tv_sec = static_cast<long>(timeout_s);
  tv.tv_usec = static_cast<long>((timeout_s - tv.tv_sec) * 1e6);
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof one);

  Client* c = new Client;
  c->fd = fd;
  c->client_id = client_id ? client_id : "iotml-native";

  if (user) {
    Writer body;
    body.str("PLAIN");
    std::vector<uint8_t> resp;
    if (!request(c, API_SASL_HANDSHAKE, 0, body, resp)) {
      delete c; ::close(fd); return nullptr;
    }
    Reader r(resp.data(), resp.size());
    if (r.i16() != ERR_NONE) { delete c; ::close(fd); return nullptr; }
    // raw PLAIN token frame (pre-KIP-152): \0 user \0 password
    std::vector<uint8_t> token;
    token.push_back(0);
    token.insert(token.end(), user, user + strlen(user));
    token.push_back(0);
    const char* pw = password ? password : "";
    token.insert(token.end(), pw, pw + strlen(pw));
    std::vector<uint8_t> ok;
    if (!send_frame(c, token) || !recv_frame(c, ok) || !ok.empty()) {
      delete c; ::close(fd); return nullptr;
    }
  }
  return c;
}

void iotml_kafka_close(void* h) {
  Client* c = static_cast<Client*>(h);
  if (!c) return;
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

// Pin the exclusive upper bound on positionally-safe writer ids that
// the fused fetch_decode paths verify before their strip=5 decode
// (< 0 disables the check — the legacy blind-strip behavior).  Per
// handle, not per call, so the existing fetch_decode ABI is untouched.
void iotml_kafka_set_pinned_id_limit(void* h, int64_t limit) {
  static_cast<Client*>(h)->pinned_id_limit = limit;
}

// Partition count for one topic (Metadata v1); 0 = unknown topic.
int64_t iotml_kafka_metadata(void* h, const char* topic) {
  Client* c = static_cast<Client*>(h);
  Writer body;
  body.i32(1);
  body.str(topic);
  std::vector<uint8_t> resp;
  if (!request(c, API_METADATA, 1, body, resp)) return K_EIO;
  Reader r(resp.data(), resp.size());
  int32_t n_brokers = r.i32();
  for (int32_t i = 0; i < n_brokers; ++i) {
    r.i32();        // node id
    r.skip_str();   // host
    r.i32();        // port
    r.skip_str();   // rack
  }
  r.i32();  // controller
  int32_t n_topics = r.i32();
  int64_t parts = 0;
  for (int32_t t = 0; t < n_topics; ++t) {
    int16_t err = r.i16();
    std::string name = r.str();
    r.i8();  // is_internal
    int32_t n_parts = r.i32();
    for (int32_t p = 0; p < n_parts; ++p) {
      r.i16();  // err
      r.i32();  // partition id
      r.i32();  // leader
      int32_t nr = r.i32();
      for (int32_t k = 0; k < nr; ++k) r.i32();
      int32_t ni = r.i32();
      for (int32_t k = 0; k < ni; ++k) r.i32();
    }
    if (name == topic && err == ERR_NONE) parts = n_parts;
  }
  return r.fail ? K_EIO : parts;
}

// CreateTopics with an optional cleanup.policy config entry (NULL/empty =
// none): the compacted-changelog client path (CAR_TWIN) needs the policy
// to ride topic creation like the Python wire client's.
int64_t iotml_kafka_create_topic_cfg(void* h, const char* topic,
                                     int32_t partitions,
                                     const char* cleanup_policy) {
  Client* c = static_cast<Client*>(h);
  Writer body;
  body.i32(1);
  body.str(topic);
  body.i32(partitions);
  body.i16(1);   // replication factor
  body.i32(0);   // replica assignments
  if (cleanup_policy && *cleanup_policy) {
    body.i32(1);  // one config entry
    body.str("cleanup.policy");
    body.str(cleanup_policy);
  } else {
    body.i32(0);  // configs
  }
  body.i32(10000);  // timeout ms
  std::vector<uint8_t> resp;
  if (!request(c, API_CREATE_TOPICS, 0, body, resp)) return K_EIO;
  Reader r(resp.data(), resp.size());
  int32_t n = r.i32();
  int64_t existed = 0;
  for (int32_t i = 0; i < n; ++i) {
    r.skip_str();
    int16_t err = r.i16();
    if (err == ERR_TOPIC_EXISTS) existed = 1;
    else if (err != ERR_NONE) return proto_err(err);
  }
  // 0 = created as requested; 1 = already existed (caller must refresh the
  // real partition count — the requested one may be wrong)
  return r.fail ? K_EIO : existed;
}

int64_t iotml_kafka_create_topic(void* h, const char* topic,
                                 int32_t partitions) {
  return iotml_kafka_create_topic_cfg(h, topic, partitions, nullptr);
}

// ListOffsets v1: timestamp -1 → end offset, -2 → begin offset.
int64_t iotml_kafka_list_offset(void* h, const char* topic, int32_t partition,
                                int64_t timestamp) {
  Client* c = static_cast<Client*>(h);
  Writer body;
  body.i32(-1);  // replica id
  body.i32(1);
  body.str(topic);
  body.i32(1);
  body.i32(partition);
  body.i64(timestamp);
  std::vector<uint8_t> resp;
  if (!request(c, API_LIST_OFFSETS, 1, body, resp)) return K_EIO;
  Reader r(resp.data(), resp.size());
  int32_t n_topics = r.i32();
  for (int32_t t = 0; t < n_topics; ++t) {
    r.skip_str();
    int32_t n_parts = r.i32();
    for (int32_t p = 0; p < n_parts; ++p) {
      r.i32();  // partition
      int16_t err = r.i16();
      r.i64();  // timestamp
      int64_t off = r.i64();
      if (r.fail) return K_EIO;
      if (err != ERR_NONE) return proto_err(err);
      return off;
    }
  }
  return K_EIO;
}

// Produce v2, one (topic, partition), acks=all.  Values (and optional keys)
// arrive as a contiguous blob + n+1 offsets — the encode_batch layout.
// Returns the broker-assigned base offset of the batch.
static int64_t kafka_produce_impl(void* h, const char* topic,
                                  int32_t partition, const uint8_t* values,
                                  const int64_t* val_offsets,
                                  const uint8_t* keys,
                                  const int64_t* key_offsets,
                                  const uint8_t* key_null,
                                  const int64_t* timestamps, int64_t n,
                                  const uint8_t* value_null) {
  Client* c = static_cast<Client*>(h);
  Writer ms;
  encode_message_set(ms, values, val_offsets, keys, key_offsets, key_null,
                     timestamps, n, value_null);
  Writer body;
  body.i16(-1);     // acks = all
  body.i32(10000);  // timeout
  body.i32(1);
  body.str(topic);
  body.i32(1);
  body.i32(partition);
  body.bytes(ms.buf.data(), static_cast<int32_t>(ms.buf.size()));
  std::vector<uint8_t> resp;
  if (!request(c, API_PRODUCE, 2, body, resp)) return K_EIO;
  Reader r(resp.data(), resp.size());
  int32_t n_topics = r.i32();
  int64_t base = K_EIO;
  for (int32_t t = 0; t < n_topics; ++t) {
    r.skip_str();
    int32_t n_parts = r.i32();
    for (int32_t p = 0; p < n_parts; ++p) {
      r.i32();  // partition
      int16_t err = r.i16();
      int64_t b = r.i64();
      r.i64();  // log append time
      if (err != ERR_NONE) return proto_err(err);
      base = b;
    }
  }
  r.i32();  // throttle
  return r.fail ? K_EIO : base;
}

int64_t iotml_kafka_produce(void* h, const char* topic, int32_t partition,
                            const uint8_t* values, const int64_t* val_offsets,
                            const uint8_t* keys, const int64_t* key_offsets,
                            const uint8_t* key_null, const int64_t* timestamps,
                            int64_t n) {
  return kafka_produce_impl(h, topic, partition, values, val_offsets, keys,
                            key_offsets, key_null, timestamps, n, nullptr);
}

// Tombstone-capable produce: value_null[i] marks record i as a null-value
// delete marker (wire length -1).  Separate symbol so older .so consumers
// keep the exact ABI they linked against.
int64_t iotml_kafka_produce_nulls(void* h, const char* topic,
                                  int32_t partition, const uint8_t* values,
                                  const int64_t* val_offsets,
                                  const uint8_t* keys,
                                  const int64_t* key_offsets,
                                  const uint8_t* key_null,
                                  const uint8_t* value_null,
                                  const int64_t* timestamps, int64_t n) {
  return kafka_produce_impl(h, topic, partition, values, val_offsets, keys,
                            key_offsets, key_null, timestamps, n, value_null);
}

// RAW_PRODUCE (emulator-family extension, api 65 v0): ship a batch of
// PRE-FRAMED store frames the broker appends segment-verbatim (CRCs
// validated and offsets stamped server-side).  Returns the base offset,
// or -1035 (UNSUPPORTED_VERSION → the caller pins back to classic
// produce), -1002 (CORRUPT_MESSAGE → the whole batch was rejected,
// nothing appended), -1006 (NOT_LEADER), K_EIO on transport death.
// NOT idempotent: like produce, a lost connection mid-request surfaces
// as a transport error and the caller owns redelivery.
int64_t iotml_kafka_produce_raw(void* h, const char* topic,
                                int32_t partition, const uint8_t* frames,
                                int64_t frames_len) {
  Client* c = static_cast<Client*>(h);
  if (!frames || frames_len < 0) return K_EIO;
  Writer body;
  body.str(topic);
  body.i32(partition);
  body.bytes(frames, static_cast<int32_t>(frames_len));
  std::vector<uint8_t> resp;
  if (!request(c, API_RAW_PRODUCE, 0, body, resp)) return K_EIO;
  Reader r(resp.data(), resp.size());
  int16_t err = r.i16();
  if (err != ERR_NONE) return proto_err(err);
  int64_t base = r.i64();
  r.i32();  // count
  return r.fail ? K_EIO : base;
}

// Value-null flags of the staged fetch (1 byte per staged message).  Read
// BEFORE iotml_kafka_take (take clears the staging area); returns the
// staged count.
int64_t iotml_kafka_staged_value_nulls(void* h, uint8_t* out) {
  Client* c = static_cast<Client*>(h);
  int64_t n = static_cast<int64_t>(c->staged.size());
  for (int64_t i = 0; i < n; ++i) out[i] = c->staged[i].value_null ? 1 : 0;
  return n;
}

// Fetch v2 into the handle's staging area.  Returns messages staged (>= 0)
// or an error.  Staged data is then read out via iotml_kafka_staged_* /
// iotml_kafka_take, or decoded in place by iotml_kafka_fetch_decode.
int64_t iotml_kafka_fetch(void* h, const char* topic, int32_t partition,
                          int64_t offset, int64_t max_messages) {
  Client* c = static_cast<Client*>(h);
  c->staged.clear();
  Writer body;
  body.i32(-1);       // replica
  body.i32(0);        // max wait ms
  body.i32(1);        // min bytes
  body.i32(1);
  body.str(topic);
  body.i32(1);
  body.i32(partition);
  body.i64(offset);
  body.i32(4 << 20);  // max bytes
  std::vector<uint8_t> resp;
  if (!request(c, API_FETCH, 2, body, resp)) return K_EIO;
  Reader r(resp.data(), resp.size());
  r.i32();  // throttle
  int32_t n_topics = r.i32();
  for (int32_t t = 0; t < n_topics; ++t) {
    r.skip_str();
    int32_t n_parts = r.i32();
    for (int32_t p = 0; p < n_parts; ++p) {
      r.i32();  // partition id
      int16_t err = r.i16();
      int64_t hwm = r.i64();
      const uint8_t* ms;
      int32_t msn = r.bytes(&ms);
      if (r.fail) return K_EIO;
      if (err == 1 /*OFFSET_OUT_OF_RANGE*/) {
        // the broker trimmed the log head past this offset (retention).
        // Silently treating it as an empty poll livelocks the consumer
        // at the trimmed offset forever; surface it like every other
        // protocol error.  The iotml wire server rides the EARLIEST
        // retained offset in the hwm slot for this error (real brokers
        // send -1), so the caller can reset without a second round trip.
        c->staged_high_watermark = hwm;
        return proto_err(err);
      }
      if (err != ERR_NONE) return proto_err(err);
      c->staged_high_watermark = hwm;
      if (msn > 0 &&
          !decode_message_set(ms, msn, offset, max_messages, c->staged))
        return K_EIO;
    }
  }
  return static_cast<int64_t>(c->staged.size());
}

int64_t iotml_kafka_staged_bytes(void* h, int64_t* value_bytes,
                                 int64_t* key_bytes) {
  Client* c = static_cast<Client*>(h);
  int64_t vb = 0, kb = 0;
  for (const Staged& s : c->staged) {
    vb += static_cast<int64_t>(s.value.size());
    kb += static_cast<int64_t>(s.key.size());
  }
  if (value_bytes) *value_bytes = vb;
  if (key_bytes) *key_bytes = kb;
  return static_cast<int64_t>(c->staged.size());
}

int64_t iotml_kafka_high_watermark(void* h) {
  return static_cast<Client*>(h)->staged_high_watermark;
}

// Copy staged messages out as contiguous blobs + n+1 offset arrays.
// key_offsets[i] == key_offsets[i+1] and key_null marks distinguish empty
// vs null keys via the out_key_null bitmask (1 byte per message).
int64_t iotml_kafka_take(void* h, uint8_t* values, int64_t* val_offsets,
                         uint8_t* keys, int64_t* key_offsets,
                         uint8_t* key_null, int64_t* msg_offsets,
                         int64_t* timestamps) {
  Client* c = static_cast<Client*>(h);
  int64_t vp = 0, kp = 0;
  int64_t n = static_cast<int64_t>(c->staged.size());
  for (int64_t i = 0; i < n; ++i) {
    const Staged& s = c->staged[i];
    val_offsets[i] = vp;
    memcpy(values + vp, s.value.data(), s.value.size());
    vp += static_cast<int64_t>(s.value.size());
    key_offsets[i] = kp;
    if (!s.key.empty()) {
      memcpy(keys + kp, s.key.data(), s.key.size());
      kp += static_cast<int64_t>(s.key.size());
    }
    key_null[i] = s.key_null ? 1 : 0;
    msg_offsets[i] = s.offset;
    timestamps[i] = s.timestamp;
  }
  val_offsets[n] = vp;
  key_offsets[n] = kp;
  c->staged.clear();
  return n;
}

// The fused hot path: fetch + framing strip + columnar Avro decode in one
// native call (the KafkaDataset-equivalent).  Decodes at most max_rows
// messages starting at `offset` into out_numeric/out_labels (layouts as in
// iotml_decode_batch).  *next_offset receives the cursor after the last
// decoded message.  Returns rows decoded (0 = clean EOF/empty poll), or a
// negative error (decode failures surface as -(row + 1) - 2000).
// fetch_decode, optionally with per-message KEYS: when out_keys is
// non-null, each message's key is copied alongside the decode
// (key_stride bytes per row, zero-padded, truncated at stride-1).  The
// key is the record's routing identity (the MQTT topic → car id through
// the bridge/KSQL legs), which per-entity consumers (car-health
// detection) need alongside the decoded features.
int64_t iotml_kafka_fetch_decode_keys(
    void* h, const char* topic, int32_t partition, int64_t offset,
    const int8_t* types, const uint8_t* nullable, int64_t n_fields,
    int64_t strip, double* out_numeric, char* out_labels,
    int64_t label_stride, char* out_keys, int64_t key_stride,
    int64_t max_rows, int64_t* next_offset) {
  Client* c = static_cast<Client*>(h);
  int64_t n = iotml_kafka_fetch(h, topic, partition, offset, max_rows);
  if (n <= 0) {
    *next_offset = offset;
    return n;
  }
  // Runtime guard for the blind Confluent strip: with a pinned writer
  // id (set_expect_schema_id), decode only the prefix of staged
  // messages whose 5-byte header matches — the first evolved (v2)
  // frame ends the batch so the caller's cursor lands exactly on it
  // and the resolving Python path takes over for that chunk.
  if (strip == 5 && c->pinned_id_limit >= 0) {
    int64_t ok = 0;
    for (; ok < n; ++ok) {
      const std::vector<uint8_t>& v = c->staged[ok].value;
      if (c->staged[ok].value_null || v.size() < 5 || v[0] != 0) break;
      int64_t sid = (int64_t(v[1]) << 24) | (int64_t(v[2]) << 16) |
                    (int64_t(v[3]) << 8) | int64_t(v[4]);
      if (sid >= c->pinned_id_limit) break;
    }
    if (ok == 0) {
      *next_offset = offset;
      c->staged.clear();
      return K_ESCHEMA;
    }
    n = ok;  // decode the verified prefix; cursor stops before the rest
  }
  // Flatten staged values into one blob for the batch decoder.
  int64_t total = 0;
  for (const Staged& s : c->staged) total += (int64_t)s.value.size();
  std::vector<uint8_t> blob(total);
  std::vector<int64_t> offs(n + 1);
  int64_t pos = 0;
  for (int64_t i = 0; i < n; ++i) {
    offs[i] = pos;
    memcpy(blob.data() + pos, c->staged[i].value.data(),
           c->staged[i].value.size());
    pos += (int64_t)c->staged[i].value.size();
    if (out_keys) {
      char* krow = out_keys + i * key_stride;
      memset(krow, 0, key_stride);
      if (!c->staged[i].key_null) {
        int64_t kn = (int64_t)c->staged[i].key.size();
        if (kn > key_stride - 1) kn = key_stride - 1;
        memcpy(krow, c->staged[i].key.data(), kn);
      }
    }
  }
  offs[n] = pos;
  int64_t rc = iotml_decode_batch(blob.data(), offs.data(), n, types,
                                  nullable, n_fields, strip, out_numeric,
                                  out_labels, label_stride);
  if (rc < 0) return rc - 2000;
  *next_offset = c->staged[n - 1].offset + 1;
  c->staged.clear();
  return rc;
}

// Keyless form: one implementation, keys skipped.
int64_t iotml_kafka_fetch_decode(void* h, const char* topic,
                                 int32_t partition, int64_t offset,
                                 const int8_t* types, const uint8_t* nullable,
                                 int64_t n_fields, int64_t strip,
                                 double* out_numeric, char* out_labels,
                                 int64_t label_stride, int64_t max_rows,
                                 int64_t* next_offset) {
  return iotml_kafka_fetch_decode_keys(h, topic, partition, offset, types,
                                       nullable, n_fields, strip,
                                       out_numeric, out_labels,
                                       label_stride, nullptr, 0, max_rows,
                                       next_offset);
}

// OffsetCommit v2, simple-consumer style (generation -1, empty member).
// Commit many partitions of ONE topic in a single OffsetCommit request —
// the wire protocol always allowed it; the per-partition entry point
// below cost a round trip per partition (10 per training round on the
// reference's 10-partition topics, each waiting on the busy broker
// process's scheduler).
int64_t iotml_kafka_commit_many(void* h, const char* group,
                                const char* topic,
                                const int32_t* partitions,
                                const int64_t* next_offsets, int64_t n) {
  Client* c = static_cast<Client*>(h);
  Writer body;
  body.str(group);
  body.i32(-1);   // generation
  body.str("");   // member id
  body.i64(-1);   // retention: broker default
  body.i32(1);
  body.str(topic);
  body.i32(static_cast<int32_t>(n));
  for (int64_t i = 0; i < n; ++i) {
    body.i32(partitions[i]);
    body.i64(next_offsets[i]);
    body.null_str();  // metadata
  }
  std::vector<uint8_t> resp;
  if (!request(c, API_OFFSET_COMMIT, 2, body, resp)) return K_EIO;
  Reader r(resp.data(), resp.size());
  int32_t n_topics = r.i32();
  for (int32_t t = 0; t < n_topics; ++t) {
    r.skip_str();
    int32_t n_parts = r.i32();
    for (int32_t p = 0; p < n_parts; ++p) {
      r.i32();
      int16_t err = r.i16();
      if (err != ERR_NONE) return proto_err(err);
    }
  }
  return r.fail ? K_EIO : 0;
}

int64_t iotml_kafka_commit(void* h, const char* group, const char* topic,
                           int32_t partition, int64_t next_offset) {
  return iotml_kafka_commit_many(h, group, topic, &partition,
                                 &next_offset, 1);
}

// OffsetFetch v1 → committed next-offset, or -1 when the group has none.
int64_t iotml_kafka_committed(void* h, const char* group, const char* topic,
                              int32_t partition) {
  Client* c = static_cast<Client*>(h);
  Writer body;
  body.str(group);
  body.i32(1);
  body.str(topic);
  body.i32(1);
  body.i32(partition);
  std::vector<uint8_t> resp;
  if (!request(c, API_OFFSET_FETCH, 1, body, resp)) return K_EIO;
  Reader r(resp.data(), resp.size());
  int32_t n_topics = r.i32();
  for (int32_t t = 0; t < n_topics; ++t) {
    r.skip_str();
    int32_t n_parts = r.i32();
    for (int32_t p = 0; p < n_parts; ++p) {
      r.i32();
      int64_t off = r.i64();
      r.skip_str();  // metadata
      int16_t err = r.i16();
      if (r.fail) return K_EIO;
      if (err != ERR_NONE) return proto_err(err);
      return off;  // -1 = no committed offset
    }
  }
  return K_EIO;
}

}  // extern "C"
