// Native MQTT ingest front-end — the fleet-scale hot path in C++.
//
// Role: the reference's ingestion edge is a native (JVM) HiveMQ cluster
// whose job, for this pipeline, is exactly one thing: absorb qos-0/1
// PUBLISH floods from ~100k devices and hand the payloads to the Kafka
// extension (SURVEY L2).  The Python fronts (`mqtt.wire.MqttServer`,
// `mqtt.eventserver.MqttEventServer`) carry the full broker semantics
// (subscriptions, retained messages, QoS 2 exactly-once, backpressure);
// THIS engine is the specialized ingest-only listener for raw throughput:
// an epoll loop + MQTT frame parser in C++, accumulating extracted
// (topic, payload) pairs into a flat arena the Python side drains in bulk
// (one ctypes call per thousands of messages, zero per-message Python).
//
// Protocol surface (deliberately narrow — it is an ingest edge, not a
// broker): CONNECT/CONNACK (3.1.1 and 5), PUBLISH qos 0/1 (+PUBACK),
// PINGREQ/PINGRESP, DISCONNECT.  SUBSCRIBE is answered with the per-filter
// failure code 0x80 (this front has no outbound delivery); a QoS 2
// PUBLISH drops the connection (exactly-once lives on the Python fronts).
// Malformed frames drop only their own connection.
//
// C API (ctypes, see mqtt/native_ingest.py):
//   iotml_mqtt_ingest_create(port)         -> handle (0 on failure)
//   iotml_mqtt_ingest_port(h)              -> bound port
//   iotml_mqtt_ingest_poll(h, timeout_ms)  -> buffered message count
//   iotml_mqtt_ingest_drain(h, &blob, &tlens, &plens) -> n messages;
//       blob is [topic bytes][payload bytes] per message, lengths in the
//       two int32 arrays; pointers valid until the next poll/clear
//   iotml_mqtt_ingest_clear(h)             -> reset the arena
//   iotml_mqtt_ingest_conns(h)             -> live connection count
//   iotml_mqtt_ingest_close(h)

#include <arpa/inet.h>
#include <malloc.h>
#include <ctime>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <unordered_map>
#include <vector>

namespace {

constexpr uint8_t CONNECT = 1, PUBLISH = 3, PUBACK = 4, SUBSCRIBE = 8,
                  UNSUBSCRIBE = 10, PINGREQ = 12, DISCONNECT = 14;

struct Conn {
  std::vector<uint8_t> in;
  uint8_t level = 4;     // protocol level from CONNECT (4 = 3.1.1, 5 = v5)
  bool connected = false;
};

struct Ingest {
  int lfd = -1;
  int ep = -1;
  uint16_t port = 0;
  std::unordered_map<int, Conn> conns;
  // drained-message arena
  std::vector<uint8_t> blob;
  std::vector<int32_t> tlens;
  std::vector<int32_t> plens;
  int64_t last_trim_ms = 0;  // rate limit for malloc_trim (see clear())
};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void close_conn(Ingest* ig, int fd) {
  epoll_ctl(ig->ep, EPOLL_CTL_DEL, fd, nullptr);
  ::close(fd);
  ig->conns.erase(fd);
}

// best-effort small control response (CONNACK/PUBACK/PINGRESP fit kernel
// buffers virtually always; on EAGAIN with NOTHING sent the ack is dropped
// whole — qos1 senders retry, which is within at-least-once).  A PARTIAL
// write (0 < sent < n) is worse than a dropped ack: the client's inbound
// stream now starts mid-frame and every later ack misparses, so the only
// framing-safe move is to drop the connection.  Returns false in that case.
bool reply(int fd, const uint8_t* data, size_t n) {
  ssize_t sent = ::send(fd, data, n, MSG_NOSIGNAL);
  return !(sent >= 0 && static_cast<size_t>(sent) < n);
}

// parse one frame out of buf[pos..n); returns false if incomplete.
// On success sets ptype, flags, body span [bstart, bend) and new pos.
bool parse_frame(const std::vector<uint8_t>& buf, size_t& pos,
                 uint8_t& ptype, uint8_t& flags, size_t& bstart,
                 size_t& bend, bool& malformed) {
  size_t n = buf.size();
  if (n - pos < 2) return false;
  uint8_t h = buf[pos];
  size_t i = pos + 1;
  uint32_t mult = 1, length = 0;
  for (int k = 0; k < 4; ++k) {
    if (i >= n) return false;
    uint8_t b = buf[i++];
    length += (b & 0x7F) * mult;
    if (!(b & 0x80)) goto have_len;
    mult *= 128;
  }
  malformed = true;
  return false;
have_len:
  if (n - i < length) return false;
  ptype = h >> 4;
  flags = h & 0x0F;
  bstart = i;
  bend = i + length;
  pos = bend;
  return true;
}

// returns false when the connection must be dropped
bool handle_frame(Ingest* ig, int fd, Conn& c, uint8_t ptype, uint8_t flags,
                  const uint8_t* b, size_t n) {
  switch (ptype) {
    case CONNECT: {
      // [len][name][level][flags][keepalive][props?][client id...]
      if (n < 4) return false;
      size_t p = 2 + ((b[0] << 8) | b[1]);  // skip protocol name
      if (p >= n) return false;
      c.level = b[p];
      c.connected = true;
      if (c.level >= 5) {
        const uint8_t ack[] = {0x20, 0x03, 0x00, 0x00, 0x00};
        return reply(fd, ack, sizeof ack);
      }
      const uint8_t ack[] = {0x20, 0x02, 0x00, 0x00};
      return reply(fd, ack, sizeof ack);
    }
    case PUBLISH: {
      if (!c.connected) return false;
      uint8_t qos = (flags >> 1) & 0x03;
      if (qos > 1) return false;  // qos 2 belongs to the Python fronts
      if (n < 2) return false;
      size_t tlen = (b[0] << 8) | b[1];
      size_t p = 2 + tlen;
      if (p > n) return false;
      uint16_t pid = 0;
      if (qos == 1) {
        if (p + 2 > n) return false;
        pid = (b[p] << 8) | b[p + 1];
        p += 2;
      }
      if (c.level >= 5) {
        // properties: varint length then that many bytes
        uint32_t mult = 1, plen = 0;
        size_t q = p;
        for (int k = 0; k < 4; ++k) {
          if (q >= n) return false;
          uint8_t v = b[q++];
          plen += (v & 0x7F) * mult;
          if (!(v & 0x80)) break;
          mult *= 128;
        }
        p = q + plen;
        if (p > n) return false;
      }
      // append to the arena: [topic][payload]
      ig->blob.insert(ig->blob.end(), b + 2, b + 2 + tlen);
      ig->blob.insert(ig->blob.end(), b + p, b + n);
      ig->tlens.push_back(static_cast<int32_t>(tlen));
      ig->plens.push_back(static_cast<int32_t>(n - p));
      if (qos == 1) {
        const uint8_t ack[] = {0x40, 0x02, uint8_t(pid >> 8),
                               uint8_t(pid & 0xFF)};
        return reply(fd, ack, sizeof ack);
      }
      return true;
    }
    case SUBSCRIBE: {
      // ingest-only: refuse every filter (0x80), per-spec SUBACK shape
      if (n < 2) return false;
      // count filters: walk [len][filter][qos] tuples after pid (+props v5)
      size_t p = 2;
      if (c.level >= 5) {
        uint32_t mult = 1, plen = 0;
        for (int k = 0; k < 4 && p < n; ++k) {
          uint8_t v = b[p++];
          plen += (v & 0x7F) * mult;
          if (!(v & 0x80)) break;
          mult *= 128;
        }
        p += plen;
      }
      int filters = 0;
      while (p + 2 <= n) {
        size_t fl = (b[p] << 8) | b[p + 1];
        p += 2 + fl + 1;
        if (p <= n) ++filters;
      }
      if (filters <= 0) return false;
      std::vector<uint8_t> ack;
      size_t body = 2 + (c.level >= 5 ? 1 : 0) + filters;
      ack.push_back(0x90);
      // remaining length is a varint: >127 filters needs multiple bytes
      size_t rem = body;
      do {
        uint8_t v = rem % 128;
        rem /= 128;
        ack.push_back(rem ? (v | 0x80) : v);
      } while (rem);
      ack.push_back(b[0]);
      ack.push_back(b[1]);
      if (c.level >= 5) ack.push_back(0x00);
      for (int k = 0; k < filters; ++k) ack.push_back(0x80);
      return reply(fd, ack.data(), ack.size());
    }
    case UNSUBSCRIBE: {
      if (n < 2) return false;
      uint8_t ack[] = {0xB0, 0x02, b[0], b[1]};
      return reply(fd, ack, sizeof ack);
    }
    case PINGREQ: {
      const uint8_t ack[] = {0xD0, 0x00};
      return reply(fd, ack, sizeof ack);
    }
    case DISCONNECT:
      return false;
    default:
      return false;  // anything else is a protocol violation here
  }
}

}  // namespace

extern "C" {

void* iotml_mqtt_ingest_create(uint16_t port) {
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return nullptr;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  // deep receive buffers, inherited by accepted sockets with the right
  // window scale: under backpressure stalls the unread kernel buffers
  // overflow on loopback (drops → sender RTO exponential backoff, stuck
  // flows at rto ~29s) — a deep buffer rides the stall out instead
  int rcvbuf = 1 << 20;
  setsockopt(lfd, SOL_SOCKET, SO_RCVBUF, &rcvbuf, sizeof rcvbuf);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(port);
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0 ||
      listen(lfd, 1024) < 0) {
    ::close(lfd);
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  set_nonblock(lfd);
  int ep = epoll_create1(0);
  if (ep < 0) {
    ::close(lfd);
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = lfd;
  epoll_ctl(ep, EPOLL_CTL_ADD, lfd, &ev);
  auto* ig = new Ingest();
  ig->lfd = lfd;
  ig->ep = ep;
  ig->port = ntohs(addr.sin_port);
  return ig;
}

int iotml_mqtt_ingest_port(void* h) {
  return static_cast<Ingest*>(h)->port;
}

long iotml_mqtt_ingest_conns(void* h) {
  return static_cast<long>(static_cast<Ingest*>(h)->conns.size());
}

// Intake backpressure: when the drain side (Python) lags, stop reading —
// kernel socket buffers fill and TCP pushes back on the publishers, the
// same watermark stance as the Python event server.  Bounds both the
// arena and the size of any single drained batch.
// measured sweet spot: a smaller arena (16k msgs) serializes intake
// against the Python forward pass and halves sustained throughput; this
// size keeps intake running while a drained batch is being forwarded
constexpr size_t kMaxBufferedMsgs = 65536;
constexpr size_t kMaxBufferedBytes = 32u << 20;

long iotml_mqtt_ingest_poll(void* h, int timeout_ms) {
  auto* ig = static_cast<Ingest*>(h);
  if (ig->tlens.size() >= kMaxBufferedMsgs ||
      ig->blob.size() >= kMaxBufferedBytes) {
    return static_cast<long>(ig->tlens.size());
  }
  epoll_event evs[256];
  int nev = epoll_wait(ig->ep, evs, 256, timeout_ms);
  if (nev < 0 && errno != EINTR) return -1;
  for (int e = 0; e < nev; ++e) {
    int fd = evs[e].data.fd;
    if (fd == ig->lfd) {
      for (;;) {
        int cfd = ::accept(ig->lfd, nullptr, nullptr);
        if (cfd < 0) break;
        set_nonblock(cfd);
        int one = 1;
        setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        epoll_event cev{};
        cev.events = EPOLLIN;
        cev.data.fd = cfd;
        epoll_ctl(ig->ep, EPOLL_CTL_ADD, cfd, &cev);
        ig->conns.emplace(cfd, Conn{});
      }
      continue;
    }
    // mid-pass backpressure: once the arena is full, stop consuming the
    // remaining readable connections this pass — their data stays in the
    // kernel (level-triggered epoll re-reports them after the drain)
    if (ig->tlens.size() >= kMaxBufferedMsgs ||
        ig->blob.size() >= kMaxBufferedBytes) {
      break;
    }
    auto it = ig->conns.find(fd);
    if (it == ig->conns.end()) continue;
    Conn& c = it->second;
    bool drop = false;
    bool eof = false;
    for (;;) {
      uint8_t chunk[65536];
      ssize_t got = ::recv(fd, chunk, sizeof chunk, 0);
      if (got > 0) {
        c.in.insert(c.in.end(), chunk, chunk + got);
        if (got < static_cast<ssize_t>(sizeof chunk)) break;
        // bound per-event intake: a connection whose kernel buffer filled
        // during a backpressure stall must not balloon its parse buffer
        // (the capacity would be retained); the rest re-reports next pass
        if (c.in.size() >= (256u << 10)) break;
      } else if (got == 0) {
        eof = true;  // parse what arrived in this pass FIRST — frames
        break;       // read together with the FIN must not be discarded
      } else {
        if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
          drop = true;
        break;
      }
    }
    if (!drop) {
      size_t pos = 0;
      uint8_t ptype, flags;
      size_t bs, be;
      bool malformed = false;
      while (parse_frame(c.in, pos, ptype, flags, bs, be, malformed)) {
        if (!handle_frame(ig, fd, c, ptype, flags, c.in.data() + bs,
                          be - bs)) {
          drop = true;
          break;
        }
      }
      if (malformed) drop = true;
      if (!drop && pos > 0) {
        c.in.erase(c.in.begin(), c.in.begin() + pos);
        // shrink burst capacity: after a backlog burst (e.g. the post-
        // stop drain of a full fleet) EVERY connection's parse buffer
        // holds a tens-to-hundreds-of-KB capacity; at 9k connections the
        // old >256KB threshold retained over a GB of dead capacity.  The
        // 64KB threshold keeps steady-state buffers (a few KB per pass)
        // untouched — no shrink/regrow churn — while reclaiming the
        // drain-phase spikes (capacity cap is 256KB, the per-event
        // intake bound).
        if (c.in.capacity() > (64u << 10) && c.in.size() < 4096)
          c.in.shrink_to_fit();
      }
    }
    if (drop || eof) close_conn(ig, fd);
  }
  return static_cast<long>(ig->tlens.size());
}

long iotml_mqtt_ingest_drain(void* h, const uint8_t** blob,
                             const int32_t** tlens, const int32_t** plens) {
  auto* ig = static_cast<Ingest*>(h);
  *blob = ig->blob.data();
  *tlens = ig->tlens.data();
  *plens = ig->plens.data();
  return static_cast<long>(ig->tlens.size());
}

void iotml_mqtt_ingest_clear(void* h) {
  auto* ig = static_cast<Ingest*>(h);
  ig->blob.clear();
  ig->tlens.clear();
  ig->plens.clear();
  // hand freed heap back to the kernel: the burst buffers this engine
  // churns (arena + per-conn parse buffers) otherwise sit in glibc's
  // arenas and read as broker RSS forever.  Rate-limited to ~2/s —
  // clear() runs after EVERY drained pass under load, and an
  // every-pass trim would walk the arenas and madvise pages the next
  // burst faults straight back in.  malloc_trim is glibc-specific; on
  // other libcs it simply doesn't exist and this file is glibc/Linux-
  // only already (epoll).
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC_COARSE, &ts);
  int64_t now_ms = ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
  if (now_ms - ig->last_trim_ms >= 500) {
    ig->last_trim_ms = now_ms;
    malloc_trim(0);
  }
}

void iotml_mqtt_ingest_close(void* h) {
  auto* ig = static_cast<Ingest*>(h);
  for (auto& kv : ig->conns) ::close(kv.first);
  ::close(ig->lfd);
  ::close(ig->ep);
  delete ig;
}

}  // extern "C"
