// Sanitizer smoke driver — `make -C iotml/cpp sanitize`.
//
// The .so is loaded into ONE process and called from MANY Python threads
// concurrently: every wire-server handler thread runs the MessageSet
// codec (iotml_msgset_encode/decode), and ingest bridges poll their
// handles while other threads query them.  This driver reproduces that
// threading shape natively so TSan/ASan can see it without the Python
// interpreter in the way:
//
//   * T concurrent threads × R rounds of columnar encode → decode →
//     verify round-trips, all through the shared global state the codec
//     owns (crc table, allocator)
//   * an MQTT ingest handle created/queried/closed across threads
//
// Exit 0 with "sanitize smoke: OK" when clean; TSan/ASan abort with a
// report otherwise.  Build targets: `make tsan`, `make asan` (libraries)
// and `make sanitize` (this driver under both sanitizers).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int64_t iotml_msgset_encode(const uint8_t* values, const int64_t* val_off,
                            const uint8_t* keys, const int64_t* key_off,
                            const uint8_t* key_null,
                            const int64_t* timestamps,
                            const int64_t* offsets, int64_t n,
                            uint8_t* out_buf, int64_t out_cap);
int64_t iotml_msgset_decode(const uint8_t* buf, int64_t len, int64_t max_n,
                            int64_t* offsets, int64_t* ts,
                            int64_t* key_off, uint8_t* key_null,
                            uint8_t* keys, int64_t keys_cap,
                            int64_t* val_off, uint8_t* val_null,
                            uint8_t* values, int64_t values_cap);
void* iotml_mqtt_ingest_create(uint16_t port);
int iotml_mqtt_ingest_port(void* h);
long iotml_mqtt_ingest_conns(void* h);
void iotml_mqtt_ingest_close(void* h);
}

namespace {

std::atomic<long> g_failures{0};

void codec_worker(int seed, int rounds) {
  const int64_t n = 64;
  for (int r = 0; r < rounds; ++r) {
    // columnar batch: values "v<seed>-<r>-<i>", every 3rd key null
    std::string values, keys;
    std::vector<int64_t> voff(n + 1, 0), koff(n + 1, 0), ts(n), offs(n);
    std::vector<uint8_t> knull(n);
    for (int64_t i = 0; i < n; ++i) {
      char buf[64];
      snprintf(buf, sizeof buf, "v%d-%d-%lld", seed, r,
               static_cast<long long>(i));
      values += buf;
      voff[i + 1] = static_cast<int64_t>(values.size());
      knull[i] = i % 3 == 0;
      if (!knull[i]) {
        snprintf(buf, sizeof buf, "k%lld", static_cast<long long>(i));
        keys += buf;
      }
      koff[i + 1] = static_cast<int64_t>(keys.size());
      ts[i] = 1700000000000LL + i;
      offs[i] = seed * 100000 + r * 1000 + i;
    }
    std::vector<uint8_t> wire(values.size() + keys.size() + 64 * n);
    int64_t wlen = iotml_msgset_encode(
        reinterpret_cast<const uint8_t*>(values.data()), voff.data(),
        reinterpret_cast<const uint8_t*>(keys.data()), koff.data(),
        knull.data(), ts.data(), offs.data(), n, wire.data(),
        static_cast<int64_t>(wire.size()));
    if (wlen <= 0) { g_failures++; return; }

    std::vector<int64_t> d_off(n), d_ts(n), d_koff(n + 1), d_voff(n + 1);
    std::vector<uint8_t> d_knull(n), d_vnull(n);
    std::vector<uint8_t> d_keys(keys.size() + 1), d_values(values.size() + 1);
    int64_t got = iotml_msgset_decode(
        wire.data(), wlen, n, d_off.data(), d_ts.data(), d_koff.data(),
        d_knull.data(), d_keys.data(),
        static_cast<int64_t>(d_keys.size()), d_voff.data(), d_vnull.data(),
        d_values.data(), static_cast<int64_t>(d_values.size()));
    if (got != n || d_off[0] != offs[0] || d_ts[n - 1] != ts[n - 1] ||
        d_voff[n] != voff[n] ||
        memcmp(d_values.data(), values.data(), values.size()) != 0) {
      g_failures++;
      return;
    }
  }
}

}  // namespace

int main() {
  const int kThreads = 8, kRounds = 200;
  std::vector<std::thread> pool;
  pool.reserve(kThreads + 1);
  for (int t = 0; t < kThreads; ++t)
    pool.emplace_back(codec_worker, t, kRounds);

  // ingest handle shared across threads (create here, query from a
  // second thread, close after join) — the bridge's lifecycle shape
  void* ingest = iotml_mqtt_ingest_create(0);
  if (ingest != nullptr) {
    pool.emplace_back([ingest] {
      for (int i = 0; i < 100; ++i) {
        if (iotml_mqtt_ingest_port(ingest) <= 0) g_failures++;
        if (iotml_mqtt_ingest_conns(ingest) != 0) g_failures++;
      }
    });
  } else {
    g_failures++;
  }

  for (auto& th : pool) th.join();
  if (ingest != nullptr) iotml_mqtt_ingest_close(ingest);

  if (g_failures.load() != 0) {
    fprintf(stderr, "sanitize smoke: %ld failure(s)\n", g_failures.load());
    return 1;
  }
  printf("sanitize smoke: OK\n");
  return 0;
}
