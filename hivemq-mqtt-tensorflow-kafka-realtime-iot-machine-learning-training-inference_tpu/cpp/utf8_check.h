// Shared UTF-8 well-formedness check (RFC 3629: no overlongs, no
// surrogates, max U+10FFFF) — the parity gate for Python's
// bytes.decode("utf-8"), used by the JSON parser's whole-message prescan
// and the Avro decoder's strict mode.
#ifndef IOTML_UTF8_CHECK_H_
#define IOTML_UTF8_CHECK_H_

#include <cstdint>

namespace iotml {

inline bool valid_utf8(const uint8_t* p, const uint8_t* end) {
  while (p < end) {
    uint8_t c = *p;
    if (c < 0x80) {
      ++p;
      continue;
    }
    int n;
    uint32_t cp;
    if ((c & 0xE0) == 0xC0) {
      n = 1;
      cp = c & 0x1F;
      if (cp < 0x02) return false;  // overlong (< U+0080)
    } else if ((c & 0xF0) == 0xE0) {
      n = 2;
      cp = c & 0x0F;
    } else if ((c & 0xF8) == 0xF0) {
      n = 3;
      cp = c & 0x07;
    } else {
      return false;
    }
    if (end - p <= n) return false;
    for (int k = 1; k <= n; ++k) {
      if ((p[k] & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (p[k] & 0x3F);
    }
    if (n == 2 && (cp < 0x800 || (cp >= 0xD800 && cp <= 0xDFFF)))
      return false;
    if (n == 3 && (cp < 0x10000 || cp > 0x10FFFF)) return false;
    p += n + 1;
  }
  return true;
}

}  // namespace iotml

#endif  // IOTML_UTF8_CHECK_H_
