from .dataset import SensorBatches, Batch  # noqa: F401
from .prefetch import DevicePrefetcher  # noqa: F401
from .pipeline import DecodeRing  # noqa: F401
