"""Creditcard fraud workflow — the reference's second anomaly dataset.

The reference ships a Kafka pair for the Kaggle creditcard set
(`python-scripts/autoencoder-anomaly-detection/`): a producer that streams
raw CSV lines onto a topic (`Sensor-Kafka-Producer-From-CSV.py:5-15`) and a
consumer that `decode_csv`s 31 columns — Time, V1..V28, Amount, Class —
stacks the first 30 as features and trains the 30-dim autoencoder
(`Sensor-Kafka-Consumer-and-TensorFlow-Model-Training.py:32-49`).  The
notebook variant additionally StandardScaler-transforms Time/Amount, which
the streaming variant leaves as an explicit TODO ("may require all data
available") — here that gap is closed with a streaming-fittable scaler.

Kaggle data cannot ship with the framework, so `synth_creditcard_csv`
generates a statistically-shaped stand-in (unit-normal V columns, frauds
drawn off-distribution) for tests, demos and benches.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np

from ..stream.broker import Broker
from ..stream.consumer import StreamConsumer
from .dataset import Batch

N_FEATURES = 30  # Time + V1..V28 + Amount (Class is the label, not a feature)
COLUMNS = ["Time"] + [f"V{i}" for i in range(1, 29)] + ["Amount", "Class"]
# columns the notebook StandardScaler-transforms (reference consumer TODO)
SCALED_COLUMNS = (0, 29)  # Time, Amount


def synth_creditcard_csv(path: str, n_rows: int = 2000,
                         fraud_rate: float = 0.05, seed: int = 0) -> int:
    """Write a synthetic creditcard.csv: header + n_rows data lines.

    Normal rows: V ~ N(0,1) (the Kaggle set's PCA components are
    standardized), Time uniform over a day, Amount log-normal.  Fraud rows
    (Class=1): a random subset of V columns shifted by ±3-5σ — structurally
    separable, like the real set.  Returns the fraud count.
    """
    rng = np.random.default_rng(seed)
    n_fraud = 0
    with open(path, "w") as fh:
        fh.write(",".join(f'"{c}"' for c in COLUMNS) + "\n")
        for i in range(n_rows):
            is_fraud = rng.random() < fraud_rate
            v = rng.normal(0.0, 1.0, 28)
            if is_fraud:
                n_fraud += 1
                hot = rng.choice(28, size=8, replace=False)
                v[hot] += rng.choice([-1.0, 1.0], size=8) * rng.uniform(3.0, 5.0, 8)
            t = float(i)  # monotone event time, like the real set
            amount = float(np.round(rng.lognormal(3.0, 1.0), 2))
            row = [f"{t:.1f}"] + [f"{x:.6f}" for x in v] + \
                [f"{amount:.2f}", str(int(is_fraud))]
            fh.write(",".join(row) + "\n")
    return n_fraud


def produce_csv_lines(broker: Broker, topic: str, csv_path: str,
                      limit: Optional[int] = None) -> int:
    """Producer parity: skip the header, publish each raw CSV line as one
    message (Sensor-Kafka-Producer-From-CSV.py:8-14). Returns the count."""
    broker.create_topic(topic)
    n = 0
    with open(csv_path) as fh:
        next(fh)  # header
        for line in fh:
            line = line.rstrip()
            if not line:
                continue
            broker.produce(topic, line.encode())
            n += 1
            if limit and n >= limit:
                break
    return n


def decode_csv_batch(values) -> tuple:
    """Consumer parity: decode CSV-line messages into (x [B,30] float32,
    y [B] int32) — process_csv + process_x_y in the reference consumer."""
    rows = np.empty((len(values), N_FEATURES + 1), np.float64)
    for i, v in enumerate(values):
        if isinstance(v, bytes):
            v = v.decode()
        parts = v.replace('"', "").split(",")
        rows[i] = [float(p) for p in parts]
    return rows[:, :N_FEATURES].astype(np.float32), rows[:, N_FEATURES].astype(np.int32)


class StandardScaler:
    """Per-column (x − mean) / std, fittable incrementally off the stream.

    Closes the reference's TODO (consumer comment: runtime StandardScaler
    "may require all data available which may defeat the purpose of
    'streaming'") via Welford/Chan parallel-merge moments: each batch folds
    into running (n, mean, M2), so the scaler converges online without a
    second pass over the log.
    """

    def __init__(self, columns=None):
        self.columns = columns  # None = all
        self.n = 0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None
        self.frozen = False

    def freeze(self) -> "StandardScaler":
        """Stop accumulating moments: transform-only from here on.  Train
        fits the scaler; eval must score with the *training* moments, or
        the model sees differently-scaled inputs than it trained on."""
        self.frozen = True
        return self

    def partial_fit(self, x: np.ndarray) -> "StandardScaler":
        if self.frozen:
            return self
        x = np.asarray(x, np.float64)
        if self.mean is None:
            self.mean = np.zeros(x.shape[1])
            self.m2 = np.zeros(x.shape[1])
        nb = x.shape[0]
        if nb == 0:
            return self
        bmean = x.mean(axis=0)
        bm2 = ((x - bmean) ** 2).sum(axis=0)
        delta = bmean - self.mean
        tot = self.n + nb
        self.mean = self.mean + delta * (nb / tot)
        self.m2 = self.m2 + bm2 + delta ** 2 * (self.n * nb / tot)
        self.n = tot
        return self

    def fit(self, x: np.ndarray) -> "StandardScaler":
        self.n = 0
        self.mean = self.m2 = None
        return self.partial_fit(x)

    @property
    def std(self) -> np.ndarray:
        # population std, like sklearn's StandardScaler
        return np.sqrt(np.maximum(self.m2 / max(self.n, 1), 1e-12))

    def transform(self, x: np.ndarray) -> np.ndarray:
        out = np.array(x, np.float32, copy=True)
        cols = self.columns if self.columns is not None else range(out.shape[1])
        for c in cols:
            out[:, c] = (out[:, c] - self.mean[c]) / self.std[c]
        return out

    def fit_transform(self, x: np.ndarray) -> np.ndarray:
        return self.fit(x).transform(x)


@dataclasses.dataclass
class CreditcardBatches:
    """Fixed-shape [B, 30] batches off a CSV-line topic.

    Mirrors the reference consumer's knobs (batch 32, eof=True) plus the
    framework contracts: tail padding + validity mask, `only_normal`
    training filter (train on Class==0, the notebook's protocol), optional
    scaler for Time/Amount, and `epochs()` replay for multi-epoch fit.
    """

    consumer: StreamConsumer
    batch_size: int = 32
    only_normal: bool = False
    scaler: Optional[StandardScaler] = None
    pad_tail: bool = True

    def __iter__(self) -> Iterator[Batch]:
        self.consumer.seek_to_start()
        buf_x, buf_y = [], []
        emitted = 0

        def flush(xs, ys, first):
            x = np.stack(xs)
            y = np.asarray(ys, np.int32)
            n_valid = x.shape[0]
            if n_valid < self.batch_size:
                if not self.pad_tail:
                    return None
                pad = self.batch_size - n_valid
                x = np.concatenate([x, np.zeros((pad, x.shape[1]), np.float32)])
                y = np.concatenate([y, np.zeros((pad,), np.int32)])
            return Batch(x=x, n_valid=n_valid, first_index=first, labels=y)

        while True:
            msgs = self.consumer.poll(4096)
            if not msgs:
                break
            x, y = decode_csv_batch([m.value for m in msgs])
            if self.scaler is not None:
                self.scaler.partial_fit(x)
                x = self.scaler.transform(x)
            if self.only_normal:
                keep = y == 0
                x, y = x[keep], y[keep]
            for i in range(x.shape[0]):
                buf_x.append(x[i])
                buf_y.append(y[i])
                if len(buf_x) == self.batch_size:
                    yield flush(buf_x, buf_y, emitted)
                    emitted += self.batch_size
                    buf_x, buf_y = [], []
        if buf_x:
            b = flush(buf_x, buf_y, emitted)
            if b is not None:
                yield b

    def epochs(self, n: int):
        """Replay the stream n times (KafkaDataset re-read semantics)."""
        for _ in range(n):
            yield iter(self)
