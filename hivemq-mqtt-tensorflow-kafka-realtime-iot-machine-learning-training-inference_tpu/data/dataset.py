"""Unbounded stream → fixed-shape batches (the tf.data pipeline, TPU-first).

The reference builds its input pipeline in-graph:
KafkaDataset → substr(5) → decode_avro → normalize → filter(y=="false")
→ zip(x,x) → batch(100) → take(100)   (cardata-v3.py:197-218).

A TPU pipeline must deliver *static shapes* — XLA compiles one program per
shape, and an unbounded stream with data-dependent filtering produces ragged
batches.  The design here:

- decode + normalize happen host-side in columnar numpy (C++ engine later),
- filtering (label == "false") happens host-side *before* batching, so the
  device only ever sees dense [B, F] blocks,
- the tail batch is zero-padded to B with a validity mask `n_valid`, so the
  jitted step never sees a new shape and never recompiles.

`SensorBatches` mirrors the reference knobs (batch_size, take, skip) and its
per-epoch re-read semantics via `reset()`.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np

from ..core.normalize import Normalizer, CAR_NORMALIZER
from ..core.schema import KSQL_CAR_SCHEMA, RecordSchema
from ..obs import metrics as obs_metrics
from ..ops.avro import AvroCodec
from ..ops.framing import strip_frame
from ..stream.consumer import StreamConsumer


@dataclasses.dataclass
class Batch:
    """One fixed-shape batch. x is [B, F] float32; rows >= n_valid are padding.

    `first_index` is the global record index of row 0 within this stream view
    (after filtering/skip) — the index OutputSequence keys write-back on.
    """

    x: np.ndarray
    n_valid: int
    first_index: int
    labels: Optional[np.ndarray] = None  # object array of strings, if kept
    y: Optional[np.ndarray] = None  # supervised target (windowed/LSTM path)

    @property
    def mask(self) -> np.ndarray:
        m = np.zeros((self.x.shape[0],), np.float32)
        m[: self.n_valid] = 1.0
        return m


class SensorBatches:
    """Iterable of fixed-shape sensor batches off a StreamConsumer.

    Args mirror the reference pipeline:
      batch_size: rows per batch (reference: 100; LSTM: 1).
      take: max batches per epoch (reference: 100), None = to EOF.
      skip: batches to skip first (reference predict path: skip(100)).
      only_normal: keep rows with label "false" only (training filter,
        cardata-v3.py:212); False keeps everything (predict path).
      window: if set, emit [B, window, F] sliding windows (LSTM path,
        window(look_back, shift=1) — reference LSTM cardata-v1.py:184-190)
        together with next-step targets y [B, 1, F].
      pad_tail: zero-pad the final ragged batch (True) or drop it (False —
        the reference's drop_remainder-free batch() keeps ragged tails; we
        pad by default because static shapes are the TPU contract).
    """

    def __init__(self, consumer: StreamConsumer,
                 schema: RecordSchema = KSQL_CAR_SCHEMA,
                 normalizer: Normalizer = CAR_NORMALIZER,
                 batch_size: int = 100,
                 take: Optional[int] = None,
                 skip: int = 0,
                 only_normal: bool = False,
                 window: Optional[int] = None,
                 pad_tail: bool = True,
                 keep_labels: bool = False,
                 poll_chunk: int = 4096,
                 cache: bool = False):
        self.consumer = consumer
        self.schema = schema
        self.codec = AvroCodec(schema)
        self.normalizer = normalizer
        self.batch_size = batch_size
        self.take = take
        self.skip = skip
        self.only_normal = only_normal
        self.window = window
        self.pad_tail = pad_tail
        self.keep_labels = keep_labels
        self.poll_chunk = poll_chunk
        # cache=True decodes the stream once and replays batches from host
        # memory on later epochs.  The reference re-reads Kafka every epoch
        # only because KafkaDataset cannot cache (python-scripts/
        # README.md:114-117); over an immutable log slice the two are
        # semantically identical, so this is a pure throughput feature.
        self.cache = cache
        self._cached = None
        self.records_seen = 0  # pre-filter record count this epoch
        # skip applies once to the stream head (reference skip(100) targets
        # the offset-slice, cardata-v3.py:274), not once per drain — a
        # continuous scorer re-entering __iter__ must not re-skip new data.
        self._skipped = 0
        # Native (C++) columnar decode when the engine is built; the pure
        # codec is the fallback and the test oracle.
        self._native = None
        try:
            from ..stream.native import NativeCodec

            self._native = NativeCodec(schema)
            # label column index among the schema's string fields
            strings = [f.name for f in schema.fields if f.avro_type == "string"]
            self._label_col = strings.index(schema.label_field) \
                if schema.label_field in strings else None
        except Exception:
            self._native = None

    # ------------------------------------------------------------ core
    def _native_labels(self, lab: np.ndarray, n: int) -> np.ndarray:
        """Label column out of the native decoder's fixed-stride bytes."""
        return (lab[:, self._label_col].astype("U")
                if self._label_col is not None
                else np.full((n,), "", object))

    def _emit_chunk(self, num: np.ndarray, labels) -> tuple:
        """Shared tail of every decode path: normalize + account."""
        xs = self.normalizer.np(num)
        self.records_seen += len(xs)
        obs_metrics.records_consumed.inc(len(xs))
        return xs, np.asarray(labels)

    def _decoded_chunks(self):
        """Yield (xs [n, F] float32 normalized, labels [n] str) per poll."""
        label_f = self.schema.label_field
        if self._native is not None and \
                getattr(self.consumer.broker, "fetch_decode", None) is not None:
            # Fully-native path: broker-side fetch + framing strip + Avro
            # decode in one C++ call (NativeKafkaBroker.fetch_decode) — no
            # per-message Python objects.
            while True:
                num, lab = self.consumer.poll_decoded(
                    self._native, strip=5, max_messages=self.poll_chunk)
                if len(num) == 0:
                    return
                yield self._emit_chunk(num, self._native_labels(lab, len(num)))
        while True:
            msgs = self.consumer.poll(self.poll_chunk)
            if not msgs:
                return
            n = len(msgs)
            if self._native is not None:
                num, lab = self._native.decode_batch(
                    [m.value for m in msgs], strip=5)
                labels = self._native_labels(lab, n)
            else:
                raw = [strip_frame(m.value) for m in msgs]
                cols = self.codec.decode_batch(raw)
                num = self.codec.sensor_matrix(cols)  # [n, F] float64
                labels = cols[label_f] if label_f \
                    else np.full((n,), "", object)
            yield self._emit_chunk(num, labels)

    def _filtered_chunks(self):
        for xs, labels in self._decoded_chunks():
            if self.only_normal:
                keep = labels == "false"
                xs, labels = xs[keep], labels[keep]
            if len(xs):
                yield xs, labels

    def _filtered_rows(self):
        for xs, labels in self._filtered_chunks():
            for i in range(len(xs)):
                yield xs[i], labels[i]

    def __iter__(self) -> Iterator[Batch]:
        if self.window:
            yield from self._windowed_iter()
            return
        B = self.batch_size
        parts: list = []  # pending (xs, labels) chunks
        have = 0
        emitted = 0
        # index counts post-skip rows only, matching the reference's
        # OutputCallback `index = batch * batch_size` which starts at 0
        # after the skip slice (cardata-v3.py:243-249).
        index = 0

        def assemble():
            nonlocal parts, have
            xs = np.concatenate([p[0] for p in parts]) if len(parts) > 1 else parts[0][0]
            labels = np.concatenate([p[1] for p in parts]) if len(parts) > 1 else parts[0][1]
            parts = []
            have = 0
            return xs, labels

        def emit(xs, labels, lo):
            n_valid = min(B, len(xs) - lo)
            x = xs[lo:lo + n_valid].astype(np.float32, copy=True)
            if n_valid < B:
                x = np.concatenate([x, np.zeros((B - n_valid, x.shape[1]),
                                                np.float32)])
            lab = None
            if self.keep_labels:
                lab = np.empty((B,), object)
                lab[:n_valid] = labels[lo:lo + n_valid]
                lab[n_valid:] = ""
            return Batch(x, n_valid, 0, lab)  # first_index patched by caller

        for chunk in self._filtered_chunks():
            parts.append(chunk)
            have += len(chunk[0])
            if have < B:
                continue
            xs, labels = assemble()
            lo = 0
            while len(xs) - lo >= B:
                if self._skipped < self.skip:
                    self._skipped += 1
                else:
                    b = emit(xs, labels, lo)
                    b.first_index = index
                    yield b
                    emitted += 1
                    index += B
                    if self.take and emitted >= self.take:
                        return
                lo += B
            if lo < len(xs):
                parts = [(xs[lo:], labels[lo:])]
                have = len(xs) - lo
        if have and self.pad_tail and self._skipped >= self.skip and \
                (not self.take or emitted < self.take):
            xs, labels = assemble()
            b = emit(xs, labels, 0)
            b.first_index = index
            yield b

    def _windowed_iter(self) -> Iterator[Batch]:
        """Sliding windows x=[B,T,F] with next-step targets y=[B,1,F].

        Reproduces dataset.window(look_back, shift=1) zipped with
        dataset.skip(look_back) (reference LSTM cardata-v1.py:184-190): the
        window starting at record i is paired with record i+look_back.
        """
        T = self.window
        F = self.schema.num_sensors
        B = self.batch_size
        ring: list = []
        xs = np.zeros((B, T, F), np.float32)
        ys = np.zeros((B, 1, F), np.float32)
        fill = 0
        emitted = 0
        index = 0
        for x, _y in self._filtered_rows():
            ring.append(x)
            if len(ring) < T + 1:
                continue
            xs[fill] = np.stack(ring[:T])
            ys[fill] = ring[T][None]
            ring.pop(0)
            fill += 1
            if fill == B:
                if self._skipped < self.skip:
                    self._skipped += 1
                else:
                    yield Batch(xs.copy(), B, index, y=ys.copy())
                    emitted += 1
                    index += B
                    if self.take and emitted >= self.take:
                        return
                fill = 0
        if fill and self.pad_tail and self._skipped >= self.skip and \
                (not self.take or emitted < self.take):
            xs[fill:] = 0.0
            ys[fill:] = 0.0
            yield Batch(xs.copy(), fill, index, y=ys.copy())

    # --------------------------------------------------------- epoch API
    def reset(self):
        """Rewind for the next epoch (reference re-reads the topic per epoch,
        python-scripts/README.md:114-117)."""
        self.consumer.seek_to_start()
        self.records_seen = 0
        self._skipped = 0

    def epochs(self, n: int):
        """Yield epoch iterators with automatic rewind between them."""
        for e in range(n):
            if self.cache:
                if self._cached is None:
                    self._cached = list(iter(self))
                yield iter(self._cached)
                continue
            if e:
                self.reset()
            yield iter(self)
