"""Unbounded stream → fixed-shape batches (the tf.data pipeline, TPU-first).

The reference builds its input pipeline in-graph:
KafkaDataset → substr(5) → decode_avro → normalize → filter(y=="false")
→ zip(x,x) → batch(100) → take(100)   (cardata-v3.py:197-218).

A TPU pipeline must deliver *static shapes* — XLA compiles one program per
shape, and an unbounded stream with data-dependent filtering produces ragged
batches.  The design here:

- decode + normalize happen host-side in columnar numpy (C++ engine later),
- filtering (label == "false") happens host-side *before* batching, so the
  device only ever sees dense [B, F] blocks,
- the tail batch is zero-padded to B with a validity mask `n_valid`, so the
  jitted step never sees a new shape and never recompiles.

`SensorBatches` mirrors the reference knobs (batch_size, take, skip) and its
per-epoch re-read semantics via `reset()`.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Iterator, List, Optional

import numpy as np

from ..core.normalize import Normalizer, CAR_NORMALIZER
from ..core.schema import KSQL_CAR_SCHEMA, RecordSchema
from ..obs import metrics as obs_metrics
from ..obs import tracing
from ..ops.avro import AvroCodec, needs_resolution
from ..ops.framing import strip_frame
from ..stream.consumer import StreamConsumer


@dataclasses.dataclass
class Batch:
    """One fixed-shape batch. x is [B, F] float32; rows >= n_valid are padding.

    `first_index` is the global record index of row 0 within this stream view
    (after filtering/skip) — the index OutputSequence keys write-back on.
    """

    x: np.ndarray
    n_valid: int
    first_index: int
    labels: Optional[np.ndarray] = None  # object array of strings, if kept
    y: Optional[np.ndarray] = None  # supervised target (windowed/LSTM path)
    keys: Optional[np.ndarray] = None  # [B] S-bytes message keys, if kept

    @property
    def mask(self) -> np.ndarray:
        m = np.zeros((self.x.shape[0],), np.float32)
        m[: self.n_valid] = 1.0
        return m


class SensorBatches:
    """Iterable of fixed-shape sensor batches off a StreamConsumer.

    Args mirror the reference pipeline:
      batch_size: rows per batch (reference: 100; LSTM: 1).
      take: max batches per epoch (reference: 100), None = to EOF.
      skip: batches to skip first (reference predict path: skip(100)).
      only_normal: keep rows with label "false" only (training filter,
        cardata-v3.py:212); False keeps everything (predict path).
      window: if set, emit [B, window, F] sliding windows (LSTM path,
        window(look_back, shift=1) — reference LSTM cardata-v1.py:184-190)
        together with next-step targets y [B, 1, F].
      pad_tail: zero-pad the final ragged batch (True) or drop it (False —
        the reference's drop_remainder-free batch() keeps ragged tails; we
        pad by default because static shapes are the TPU contract).
    """

    def __init__(self, consumer: StreamConsumer,
                 schema: RecordSchema = KSQL_CAR_SCHEMA,
                 normalizer: Normalizer = CAR_NORMALIZER,
                 batch_size: int = 100,
                 take: Optional[int] = None,
                 skip: int = 0,
                 only_normal: bool = False,
                 window: Optional[int] = None,
                 pad_tail: bool = True,
                 keep_labels: bool = False,
                 keep_keys: bool = False,
                 exclude_key_marker: Optional[bytes] = None,
                 poll_chunk: int = 4096,
                 cache: bool = False):
        self.consumer = consumer
        self.schema = schema
        self.codec = AvroCodec(schema)
        self.normalizer = normalizer
        self.batch_size = batch_size
        self.take = take
        self.skip = skip
        self.only_normal = only_normal
        self.window = window
        self.pad_tail = pad_tail
        self.keep_labels = keep_labels
        # keep_keys threads each record's MESSAGE KEY (the car's routing
        # identity: MQTT topic → bridge key → KSQL pass-through) into
        # Batch.keys — what per-entity consumers (car-health detection)
        # join on.  Batched path only; the windowed path has no per-row
        # key semantics (a window spans records).
        self.keep_keys = keep_keys
        # exclude_key_marker drops every record whose message key
        # contains the marker BEFORE batching — the canary firewall
        # (obs.canary.CANARY_KEY_MARKER): synthetic probe records ride
        # the real ingest path but must never be scored into user-facing
        # prediction topics.  Exclusion needs the keys even when the
        # caller doesn't (keep_keys=False), so key capture is forced
        # internally and the keys are shed again after filtering.
        self.exclude_key_marker = exclude_key_marker
        self._capture_keys = keep_keys or exclude_key_marker is not None
        self.poll_chunk = poll_chunk
        # cache=True decodes the stream once and replays batches from host
        # memory on later epochs.  The reference re-reads Kafka every epoch
        # only because KafkaDataset cannot cache (python-scripts/
        # README.md:114-117); over an immutable log slice the two are
        # semantically identical, so this is a pure throughput feature.
        self.cache = cache
        self._cached = None
        self.records_seen = 0  # pre-filter record count this epoch
        # skip applies once to the stream head (reference skip(100) targets
        # the offset-slice, cardata-v3.py:274), not once per drain — a
        # continuous scorer re-entering __iter__ must not re-skip new data.
        self._skipped = 0
        # Rows still wanted by the current bounded iteration (None =
        # unbounded): `take` callers must not poll past what they will
        # batch — over-polled rows would advance the consumer cursor and
        # be skipped for good.  Updated by __iter__ between chunks, read
        # by the poll loop to cap each fetch.
        self._need_rows: Optional[int] = None
        # Trace contexts FORKED from consumed record headers, marked
        # `consume` at decode and held (bounded drop-oldest) for the
        # pipeline closer — the train step / scorer calls take_traces()
        # and closes each with its e2e span.  Forked, not shared: every
        # consumer group of a topic polls the same header object, and
        # closing it directly would let the first pipeline steal the
        # trace from the others (train-then-serve over one topic is the
        # demo's normal shape).  _seen_traces dedups epoch re-reads of
        # the same records within THIS batcher (bounded; a continuous
        # cursor never re-reads, only epoch loops do).  Empty and
        # untouched when tracing is off.  The pending bound must cover a
        # full drain at full sampling (a deep-backlog drain holds every
        # fork until the closing commit); past it the oldest forks drop
        # — counted into iotml_trace_spans_dropped_total, best-effort —
        # rather than growing without bound under a reader that never
        # closes (e.g. an evaluation-only pass over the stream).
        self._pending_traces: collections.deque = collections.deque(
            maxlen=65536)
        self._seen_traces: set = set()
        self._seen_traces_cap = 65536
        # Mixed-schema (evolution) decode path, built lazily on the
        # first chunk that actually carries a non-v1 writer id
        self._resolving = None
        # Native (C++) columnar decode when the engine is built; the pure
        # codec is the fallback and the test oracle.
        self._native = None
        try:
            from ..stream.native import NativeCodec

            self._native = NativeCodec(schema)
            # label column index among the schema's string fields
            strings = [f.name for f in schema.fields if f.avro_type == "string"]
            self._label_col = strings.index(schema.label_field) \
                if schema.label_field in strings else None
        except Exception:
            self._native = None
        # Zero-copy columnar raw-batch pipeline (ISSUE 10): raw store
        # frames decoded by the ONE FrameDecoder into a ring of
        # reusable preallocated column buffers.  Engaged for durable
        # and wire brokers (where the frames already exist as bytes);
        # the in-memory emulator would pay a re-framing encode per
        # record, so it keeps the fused/legacy paths.  Built lazily on
        # the first chunk; tri-state None=untried / ring / False=off.
        self._ring = None
        self._framedec = None

    # ------------------------------------------------------------ core
    def _native_labels(self, lab: np.ndarray, n: int) -> np.ndarray:
        """Label column out of the native decoder's fixed-stride bytes."""
        return (lab[:, self._label_col].astype("U")
                if self._label_col is not None
                else np.full((n,), "", object))

    def _emit_chunk(self, num: np.ndarray, labels, keys=None) -> tuple:
        """Shared tail of every decode path: normalize + account."""
        xs = self.normalizer.np(num)
        self.records_seen += len(xs)
        obs_metrics.records_consumed.inc(len(xs))
        return xs, np.asarray(labels), keys

    def _poll_limit(self) -> int:
        """Per-poll fetch cap: the configured chunk, bounded by what the
        current iteration still needs (see _need_rows)."""
        if self._need_rows is None:
            return self.poll_chunk
        return max(1, min(self.poll_chunk, self._need_rows))

    def _columnar_ready(self) -> bool:
        """Whether the zero-copy raw-batch path applies to this broker:
        native engine built, consumer/broker expose the raw duck-type,
        and the frames already exist as bytes (durable store or a wire
        hop) — the in-memory emulator would pay a per-record re-framing
        encode, so it keeps the fused/legacy paths."""
        if self._ring is False or self._native is None:
            return False
        broker = self.consumer.broker
        if getattr(self.consumer, "poll_into", None) is None or \
                getattr(broker, "fetch_raw", None) is None:
            return False
        durable = getattr(broker, "durable", None)
        if tracing.ENABLED and durable is not None:
            # record headers — the trace-context carrier — exist only on
            # the in-process broker, and the columnar path never
            # materialises them: a TRACED session keeps the header-
            # carrying message path there (the chaos/obs span-log
            # invariants read those spans).  Wire brokers drop headers
            # either way, so they stay columnar.
            return False
        return durable is None or bool(durable)

    def _columnar_chunks(self):
        """The zero-copy hot path: raw frame batches → FrameDecoder →
        ring slots — zero per-record Python objects from socket/disk to
        the normalized block.  The SAME `poll_into` entry serves live
        consume and timestamp-replay backfill (a backfill is a seek
        plus this), so the two cannot drift.

        Runtime guard (no more silent v1 pinning): the decoder verifies
        every value's Confluent header and STOPS at a frame whose
        writer id sits in the evolved-schema band — `poll_into` then
        reports ``fallback=True`` and ONE chunk is taken through the
        resolving Python path below before columnar resumes."""
        from . import pipeline as pl

        if self._ring is None:
            rows = max(int(self.poll_chunk), 1)
            self._ring = pl.DecodeRing(
                rows, self._native.n_numeric, self._native.n_strings,
                with_keys=self._capture_keys)
            self._framedec = self._native.frame_decoder()
        max_bytes = pl.raw_batch_bytes()
        while True:
            slot = self._ring.next_slot()
            res = self.consumer.poll_into(
                self._framedec, slot.x, slot.labels, slot.keys,
                max_rows=min(self._poll_limit(), self._ring.rows),
                max_bytes=max_bytes)
            if res is None:
                # broker lost raw support (wire server downgrade):
                # permanently hand back to the legacy paths
                self._ring = False
                return
            n, fallback = res
            if tracing.ENABLED:
                # batch-granular wire traces (ISSUE 13): poll_into
                # extracted any first-frame trace contexts — queue them
                # for the pipeline closer exactly like record traces,
                # so the scorer/train step closes them with e2e spans
                take = getattr(self.consumer, "take_batch_traces", None)
                if take is not None:
                    pending = self._pending_traces
                    for ctx in take():
                        if len(pending) == pending.maxlen:
                            tracing.spans_dropped.inc()
                        pending.append(ctx)
            if n:
                keys = slot.keys[:n].copy() if self._capture_keys else None
                yield self._emit_chunk(
                    slot.x[:n], self._native_labels(slot.labels[:n], n),
                    keys)
            if fallback:
                # evolved writer (or legacy-only bytes) at the cursor:
                # decode ONE chunk via the resolving message path, then
                # resume columnar
                msgs = self.consumer.poll(self._poll_limit())
                if msgs:
                    yield self._decode_msgs(msgs)
                continue
            if n == 0:
                return  # log end: same contract as an empty poll()

    def _decode_msgs(self, msgs):
        """Message-list decode (the fallback/oracle leg): trace forking,
        schema-evolution resolution, native-or-pure codec."""
        label_f = self.schema.label_field
        if any(m.value is None for m in msgs):
            # tombstones (compaction delete markers) carry no payload:
            # skipped here exactly like the columnar decoder skips them
            # natively — and the schema-guard fallbacks route tombstone-
            # bearing chunks through THIS leg, so it must not choke
            msgs = [m for m in msgs if m.value is not None]
            if not msgs:
                empty = np.zeros((0, self.schema.num_sensors))
                return self._emit_chunk(
                    empty, np.full((0,), "", object),
                    np.zeros((0,), "S64") if self._capture_keys else None)
        if tracing.ENABLED:
            # the zero-copy paths have no per-message Python objects
            # (and no headers) — traces ride this decode path only
            pending, overflowed = self._pending_traces, 0
            for m in msgs:
                if m.headers:
                    ctx = tracing.from_headers(m.headers)
                    if ctx is None \
                            or ctx.trace_id in self._seen_traces:
                        continue  # epoch re-read: trace once
                    if len(self._seen_traces) < self._seen_traces_cap:
                        self._seen_traces.add(ctx.trace_id)
                    # fork: this pipeline closes its own copy; the
                    # shared header object stays open for other
                    # consumer groups of the same topic
                    fork = ctx.fork()
                    fork.mark("consume")
                    if len(pending) == pending.maxlen:
                        overflowed += 1
                    pending.append(fork)
            if overflowed:
                tracing.spans_dropped.inc(overflowed)
        n = len(msgs)
        keys = None
        if self._capture_keys:
            # vectorized truncation: numpy clips each key to the S63
            # itemsize in C (matching the native paths' stride-1 cut),
            # then widens to the shared S64 stride — no per-record
            # slicing in Python
            keys = np.asarray([m.key or b"" for m in msgs],
                              dtype="S63").astype("S64")
        if any(needs_resolution(m.value) for m in msgs):
            # schema evolution on a live topic: at least one record
            # in this chunk was written under a newer schema — the
            # positional v1 decode (python AND native) would mis-
            # read it, so the whole chunk takes the name-resolving
            # path projected onto the reader schema.  Rare by
            # construction (only during a fleet's rolling upgrade),
            # so the fast paths stay untouched for v1-only chunks.
            if self._resolving is None:
                from ..ops.avro import ResolvingCodec

                self._resolving = ResolvingCodec(self.schema)
            cols = self._resolving.decode_batch_framed(
                [m.value for m in msgs])
            num = self.codec.sensor_matrix(cols)
            labels = cols[label_f] if label_f \
                else np.full((n,), "", object)
        elif self._native is not None:
            num, lab = self._native.decode_batch(
                [m.value for m in msgs], strip=5)
            labels = self._native_labels(lab, n)
        else:
            raw = [strip_frame(m.value) for m in msgs]
            cols = self.codec.decode_batch(raw)
            num = self.codec.sensor_matrix(cols)  # [n, F] float64
            labels = cols[label_f] if label_f \
                else np.full((n,), "", object)
        return self._emit_chunk(num, labels, keys)

    def _decoded_chunks(self):
        """Yield (xs [n, F] float32 normalized, labels [n] str,
        keys [n] bytes | None) per poll."""
        if self._columnar_ready():
            # Zero-copy columnar path: raw frame batches + the ONE
            # frame decoder + ring buffers (see _columnar_chunks).
            yield from self._columnar_chunks()
            if self._ring is not False:
                return
            # else: raw support vanished mid-stream; fall through
        fused_attr = "fetch_decode_keys" if self._capture_keys \
            else "fetch_decode"
        if self._native is not None and \
                getattr(self.consumer.broker, fused_attr, None) is not None:
            # Fused wire path: broker-side fetch + framing strip + Avro
            # decode in one C++ call (NativeKafkaBroker.fetch_decode) —
            # no per-message Python objects.  The old v1-only
            # LIMITATION is now a RUNTIME GUARD: the engine verifies
            # each frame's Confluent id against the evolved-schema band
            # before its strip=5 decode and raises SchemaIdMismatchError
            # at an evolved frame — that chunk detours through the
            # resolving Python path below, then the fused loop resumes.
            from ..stream.broker import SchemaIdMismatchError

            while True:
                try:
                    res = self.consumer.poll_decoded(
                        self._native, strip=5,
                        max_messages=self._poll_limit(),
                        with_keys=self._capture_keys)
                except SchemaIdMismatchError:
                    msgs = self.consumer.poll(self._poll_limit())
                    if msgs:
                        yield self._decode_msgs(msgs)
                    continue
                num, lab = res[0], res[1]
                if len(num) == 0:
                    return
                yield self._emit_chunk(num,
                                       self._native_labels(lab, len(num)),
                                       res[2] if self._capture_keys else None)
        while True:
            msgs = self.consumer.poll(self._poll_limit())
            if not msgs:
                return
            yield self._decode_msgs(msgs)

    def _filtered_chunks(self):
        marker = self.exclude_key_marker
        for xs, labels, keys in self._decoded_chunks():
            if marker is not None and keys is not None and len(keys):
                # canary firewall: reserved-id records never batch
                keep = np.char.find(keys, marker) == -1
                if not keep.all():
                    xs, labels, keys = xs[keep], labels[keep], keys[keep]
            if marker is not None and not self.keep_keys:
                keys = None  # captured for the filter only
            if self.only_normal:
                keep = labels == "false"
                xs, labels = xs[keep], labels[keep]
                if keys is not None:
                    keys = keys[keep]
            if len(xs):
                yield xs, labels, keys

    def __iter__(self) -> Iterator[Batch]:
        if self.window:
            yield from self._windowed_iter()
            return
        B = self.batch_size
        parts: list = []  # pending (xs, labels, keys) chunks
        have = 0
        emitted = 0
        # index counts post-skip rows only, matching the reference's
        # OutputCallback `index = batch * batch_size` which starts at 0
        # after the skip slice (cardata-v3.py:243-249).
        index = 0

        def assemble():
            nonlocal parts, have
            if len(parts) > 1:
                xs = np.concatenate([p[0] for p in parts])
                labels = np.concatenate([p[1] for p in parts])
                keys = np.concatenate([p[2] for p in parts]) \
                    if parts[0][2] is not None else None
            else:
                xs, labels, keys = parts[0]
            parts = []
            have = 0
            return xs, labels, keys

        def emit(xs, labels, keys, lo):
            n_valid = min(B, len(xs) - lo)
            x = xs[lo:lo + n_valid].astype(np.float32, copy=True)
            if n_valid < B:
                x = np.concatenate([x, np.zeros((B - n_valid, x.shape[1]),
                                                np.float32)])
            lab = None
            if self.keep_labels:
                lab = np.empty((B,), object)
                lab[:n_valid] = labels[lo:lo + n_valid]
                lab[n_valid:] = ""
            ks = None
            if keys is not None:
                ks = np.zeros((B,), keys.dtype)
                ks[:n_valid] = keys[lo:lo + n_valid]
            return Batch(x, n_valid, 0, lab,
                         keys=ks)  # first_index patched by caller

        chunks = self._filtered_chunks()
        try:
            while True:
                if self.take:
                    # cap polling at what this bounded iteration can still
                    # batch: rows polled past the `take` boundary would
                    # advance the cursor and be lost to the caller
                    needed = (self.take - emitted
                              + max(self.skip - self._skipped, 0))
                    self._need_rows = needed * B - have
                try:
                    chunk = next(chunks)
                except StopIteration:
                    break
                parts.append(chunk)
                have += len(chunk[0])
                if have < B:
                    continue
                xs, labels, keys = assemble()
                lo = 0
                while len(xs) - lo >= B:
                    if self._skipped < self.skip:
                        self._skipped += 1
                    else:
                        b = emit(xs, labels, keys, lo)
                        b.first_index = index
                        yield b
                        emitted += 1
                        index += B
                        if self.take and emitted >= self.take:
                            return
                    lo += B
                if lo < len(xs):
                    parts = [(xs[lo:], labels[lo:],
                              keys[lo:] if keys is not None else None)]
                    have = len(xs) - lo
            if have and self.pad_tail and self._skipped >= self.skip and \
                    (not self.take or emitted < self.take):
                xs, labels, keys = assemble()
                b = emit(xs, labels, keys, 0)
                b.first_index = index
                yield b
        finally:
            self._need_rows = None

    def _windowed_iter(self) -> Iterator[Batch]:
        """Sliding windows x=[B,T,F] with next-step targets y=[B,1,F].

        Reproduces dataset.window(look_back, shift=1) zipped with
        dataset.skip(look_back) (reference LSTM cardata-v1.py:184-190): the
        window starting at record i is paired with record i+look_back.

        Vectorized: windows materialize per decoded CHUNK via a strided
        view + one transpose-copy, not a Python ring per row — the row
        loop was the LSTM ingest bottleneck (10k windows ≈ seconds of
        pure interpreter time).
        """
        from numpy.lib.stride_tricks import sliding_window_view

        T = self.window
        B = self.batch_size
        carry = None          # last T rows: windows spanning chunk joints
        pend_x: list = []     # [n, T, F] window chunks awaiting batching
        pend_y: list = []     # [n, 1, F]
        have = 0
        emitted = 0
        index = 0

        def emit(wx, wy, lo):
            n_valid = min(B, len(wx) - lo)
            x = np.zeros((B, T, wx.shape[2]), np.float32)
            y = np.zeros((B, 1, wx.shape[2]), np.float32)
            x[:n_valid] = wx[lo:lo + n_valid]
            y[:n_valid] = wy[lo:lo + n_valid]
            return Batch(x, n_valid, 0, y=y)

        chunks = self._filtered_chunks()
        try:
            while True:
                if self.take:
                    needed = (self.take - emitted
                              + max(self.skip - self._skipped, 0))
                    # rows already in `carry` count toward the T lookahead
                    # a window needs — re-adding the full T every chunk
                    # would over-poll (and so permanently skip, for
                    # cursor-resuming callers) up to T-1 rows per round
                    covered = 0 if carry is None else len(carry)
                    self._need_rows = needed * B - have + max(T - covered,
                                                              0)
                try:
                    xs, _labels, _keys = next(chunks)
                except StopIteration:
                    break
                buf = xs.astype(np.float32, copy=False)
                if carry is not None and len(carry):
                    buf = np.concatenate([carry, buf])
                n_w = len(buf) - T  # windows with a next-step target
                if n_w <= 0:
                    carry = buf
                    continue
                # [n_w, T, F]: strided view (axis order [n, F, T]) then one
                # transpose-copy; y is the row T steps after each window
                wins = np.ascontiguousarray(
                    sliding_window_view(buf, T, axis=0)[:n_w]
                    .transpose(0, 2, 1))
                ys = buf[T: T + n_w][:, None, :]
                carry = buf[n_w:]
                pend_x.append(wins)
                pend_y.append(ys)
                have += n_w
                if have < B:
                    continue
                wx = np.concatenate(pend_x) if len(pend_x) > 1 else pend_x[0]
                wy = np.concatenate(pend_y) if len(pend_y) > 1 else pend_y[0]
                pend_x, pend_y = [], []
                have = 0
                lo = 0
                while len(wx) - lo >= B:
                    if self._skipped < self.skip:
                        self._skipped += 1
                    else:
                        b = emit(wx, wy, lo)
                        b.first_index = index
                        yield b
                        emitted += 1
                        index += B
                        if self.take and emitted >= self.take:
                            return
                    lo += B
                if lo < len(wx):
                    pend_x, pend_y = [wx[lo:]], [wy[lo:]]
                    have = len(wx) - lo
            if have and self.pad_tail and self._skipped >= self.skip and \
                    (not self.take or emitted < self.take):
                wx = np.concatenate(pend_x) if len(pend_x) > 1 else pend_x[0]
                wy = np.concatenate(pend_y) if len(pend_y) > 1 else pend_y[0]
                b = emit(wx, wy, 0)
                b.first_index = index
                yield b
        finally:
            self._need_rows = None

    # ----------------------------------------------------------- tracing
    def take_traces(self) -> List["tracing.TraceContext"]:
        """Hand the traces decoded since the last call to the caller —
        the pipeline closer (train step / scorer) owns their close()."""
        out: List[tracing.TraceContext] = []
        while True:
            try:
                out.append(self._pending_traces.popleft())
            except IndexError:
                return out

    # --------------------------------------------------------- epoch API
    def reset(self):
        """Rewind for the next epoch (reference re-reads the topic per epoch,
        python-scripts/README.md:114-117)."""
        self.consumer.seek_to_start()
        self.records_seen = 0
        self._skipped = 0

    def epochs(self, n: int):
        """Yield epoch iterators with automatic rewind between them."""
        for e in range(n):
            if self.cache:
                if self._cached is None:
                    self._cached = list(iter(self))
                yield iter(self._cached)
                continue
            if e:
                self.reset()
            yield iter(self)
